"""RBM building blocks (rebuild of ``znicz/rbm_unit.py``).

The reference decomposed contrastive-divergence training for a binary RBM
into units; the rebuild keeps that surface:

  - ``Binarization`` — stochastic binarize: out ~ Bernoulli(input) from the
    seeded device PRNG (inputs must be in [0, 1]);
  - the hidden layer is an ordinary ``All2AllSigmoid`` (h = σ(Wv + b_h));
  - ``GradientRBM`` — one CD-1 step against the hidden layer's tied
    weights/bias + its own visible bias:
        h0 ~ Bern(σ(W v0 + b_h));  v1 = σ(Wᵀ h0 + b_v);  h1 = σ(W v1 + b_h)
        ΔW ∝ h0ᵀ v0 − h1ᵀ v1;  Δb_h ∝ mean(h0 − h1);  Δb_v ∝ mean(v0 − v1)
    and reports per-minibatch reconstruction error (mean ||v0−v1||²).

One jitted step; the GEMMs ride the MXU.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.memory import Array
from znicz_tpu.core.units import Unit
from znicz_tpu.nn_units import ForwardBase


class Binarization(ForwardBase):
    has_weights = False

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self._step_counter = 0

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, x):
        raise NotImplementedError("stochastic unit; use run()")

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)

    def run(self):
        if self._compiled is None:
            import jax

            def sample(x, key):
                return jax.random.bernoulli(key, x).astype("float32")

            self._compiled = jax.jit(sample)
        key = prng.get(self.name).jax_key(self._step_counter)
        self._step_counter += 1
        self.output.devmem = self._compiled(self.input.devmem, key)


class GradientRBM(Unit):
    """CD-1 trainer tied to a hidden ``All2AllSigmoid`` unit."""

    def __init__(self, workflow=None, name=None, hidden=None,
                 learning_rate=0.1, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        assert hidden is not None, "GradientRBM needs hidden=<All2AllSigmoid>"
        self.hidden = hidden
        self.learning_rate = float(learning_rate)
        self.input: Optional[Array] = None      # linked: v0 (minibatch_data)
        self.batch_size = 0                     # linked: minibatch_size
        self.vbias = Array()
        self.reconstruction_error = 0.0
        self._step_counter = 0
        self._compiled = None

    @staticmethod
    def _step(w, bh, bv, v0, batch_size, lr, key):
        import jax
        import jax.numpy as jnp

        v0 = v0.reshape(v0.shape[0], -1)
        n = v0.shape[0]
        valid = (jnp.arange(n) < batch_size)[:, None].astype(v0.dtype)
        v0 = v0 * valid
        h0p = jax.nn.sigmoid(v0 @ w.T + bh) * valid
        h0 = jax.random.bernoulli(key, h0p).astype(v0.dtype) * valid
        v1 = jax.nn.sigmoid(h0 @ w + bv) * valid
        h1p = jax.nn.sigmoid(v1 @ w.T + bh) * valid
        b = jnp.maximum(batch_size, 1)
        dw = (h0p.T @ v0 - h1p.T @ v1) / b
        dbh = jnp.sum(h0p - h1p, axis=0) / b
        dbv = jnp.sum(v0 - v1, axis=0) / b
        rec = jnp.sum(jnp.square(v0 - v1)) / b
        return w + lr * dw, bh + lr * dbh, bv + lr * dbv, rec

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        if self.vbias.mem is None:
            self.vbias.mem = np.zeros(self.input.sample_size, np.float32)
        self.vbias.initialize(device)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self._step)
        key = prng.get(self.name).jax_key(self._step_counter)
        self._step_counter += 1
        w, bh, bv, rec = self._compiled(
            self.hidden.weights.devmem, self.hidden.bias.devmem,
            self.vbias.devmem, self.input.devmem,
            np.int32(int(self.batch_size)),
            np.float32(self.learning_rate), key)
        self.hidden.weights.devmem = w
        self.hidden.bias.devmem = bh
        self.vbias.devmem = bv
        self.reconstruction_error = float(rec)
