"""Snapshotter: periodic + best-on-validation checkpointing (rebuild of
``veles/snapshotter.py``, SURVEY.md §3.5 / §5 "Checkpoint / resume").

Format change from the reference (documented): the reference pickled the
*entire workflow object graph* (code-coupled, fragile).  Here a snapshot is a
plain dict of numpy arrays + JSON-able metadata, gzip-pickled:

  {"config": {...}, "units": {unit_name: {param: ndarray}},
   "velocities": {gd_name: {param: ndarray}}, "loader": {...},
   "decision": {...}, "prng": {...}, "epoch": N, "metric": x}

Resume rebuilds the workflow from config and calls ``restore(workflow,
snapshot)`` — the reference's ``--snapshot`` CLI flag maps to the launcher's
``snapshot=`` argument.  Best-on-validation trigger semantics preserved: the
unit is gated on ``decision.improved & decision.epoch_ended``.
"""

from __future__ import annotations

import gzip
import os
import pickle
import time
from typing import Dict, Optional

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit
from znicz_tpu.telemetry import metrics as telemetry_metrics


def collect(workflow, device_arrays: bool = False) -> Dict:
    """Gather a snapshot dict from a workflow's units.  With
    ``device_arrays`` the param/velocity leaves are the live ``devmem``
    jax arrays — under a mesh these are SHARDED, and the orbax format
    writes each shard from the device/process that owns it (no host
    gather; the multi-host-safe save path)."""
    from znicz_tpu.nn_units import ForwardBase, GradientDescentBase

    def leaf(a):
        return a.devmem if device_arrays else np.array(a.map_read())

    snap = collect_meta(workflow)
    for unit in workflow:
        if isinstance(unit, ForwardBase) and unit.has_weights:
            snap["units"][unit.name] = {
                k: leaf(a) for k, a in unit.params().items()}
        elif isinstance(unit, GradientDescentBase):
            snap["velocities"][unit.name] = {
                k: leaf(a) for k, a in unit._velocities.items()}
    return snap


def collect_meta(workflow) -> Dict:
    """The non-array half of a snapshot (loader/decision/prng metadata,
    empty units/velocities) — the fused fast path pairs it with its own
    device param/velocity trees (``FusedTrainer.snapshot_from_trees``)
    so a snapshot never has to round-trip through the unit Arrays."""
    from znicz_tpu.core import prng
    from znicz_tpu.decision import DecisionBase
    from znicz_tpu.loader.base import Loader

    snap: Dict = {"units": {}, "velocities": {}, "loader": {},
                  "decision": {}, "prng": {}, "time": time.time()}
    for unit in workflow:
        if isinstance(unit, Loader):
            snap["loader"] = {
                "epoch_number": unit.epoch_number,
                "samples_served": unit.samples_served,
                # epoch_number increments LAZILY (on the next run() after a
                # tail); a boundary snapshot must record the tail state so
                # the resumed loader ADVANCES to the next epoch instead of
                # repeating the one whose updates the weights already carry
                "last_minibatch": bool(unit.last_minibatch),
            }
            if unit._shuffled_indices is not None:
                # each epoch's shuffle permutes the PREVIOUS order in
                # place, so the composed order is training state: without
                # it a resumed run reshuffles a fresh arange and the
                # sample order diverges from uninterrupted training.
                # (None = snapshot taken before the loader's first run();
                # restore already tolerates the missing key — ADVICE r4)
                snap["loader"]["shuffled_indices"] = \
                    np.array(unit._shuffled_indices)
            norm = getattr(unit, "normalizer", None)
            if norm is not None:
                snap["loader"]["normalizer"] = norm.state()
        elif isinstance(unit, DecisionBase):
            snap["decision"] = {
                "best_metric": unit.best_metric,
                "best_epoch": unit.best_epoch,
                "fails": unit._fails,
            }
            snap["epoch"] = int(unit.epoch_number)
            snap["metric"] = float(unit.best_metric)
    snap["prng"] = {name: s.state.bit_generator.state
                    for name, s in prng._streams.items()}
    return snap


def restore(workflow, snap: Dict) -> None:
    """Apply a snapshot dict onto an initialized workflow (in place)."""
    from znicz_tpu.core import prng
    from znicz_tpu.decision import DecisionBase
    from znicz_tpu.loader.base import Loader
    from znicz_tpu.nn_units import ForwardBase, GradientDescentBase

    for unit in workflow:
        if isinstance(unit, ForwardBase) and unit.name in snap["units"]:
            for k, a in unit.params().items():
                a.mem = snap["units"][unit.name][k].copy()
        elif isinstance(unit, GradientDescentBase) and \
                unit.name in snap.get("velocities", {}):
            for k, a in unit._velocities.items():
                # the checkpoint stores the THEN-configured state_dtype;
                # cast to the live accumulator dtype so resuming under a
                # different precision config neither errors nor silently
                # overrides it (ADVICE r4)
                leaf = np.asarray(snap["velocities"][unit.name][k])
                a.mem = (leaf.copy() if a.mem is None
                         or leaf.dtype == a.mem.dtype
                         else leaf.astype(a.mem.dtype))
        elif isinstance(unit, Loader) and snap.get("loader"):
            unit.epoch_number = snap["loader"]["epoch_number"]
            unit.samples_served = snap["loader"].get("samples_served", 0)
            unit.last_minibatch = snap["loader"].get("last_minibatch",
                                                     False)
            order = snap["loader"].get("shuffled_indices")
            if order is not None:
                unit._shuffled_indices = np.asarray(order, np.int32).copy()
            norm = getattr(unit, "normalizer", None)
            if norm is not None and "normalizer" in snap["loader"]:
                norm.restore(snap["loader"]["normalizer"])
        elif isinstance(unit, DecisionBase) and snap.get("decision"):
            unit.best_metric = snap["decision"]["best_metric"]
            unit.best_epoch = snap["decision"]["best_epoch"]
            unit._fails = snap["decision"]["fails"]
    for name, state in snap.get("prng", {}).items():
        stream = prng.get(name)
        stream.state.bit_generator.state = state


def restore_inference(workflow, snap: Dict) -> None:
    """The INFERENCE-load path (ISSUE 4): apply ONLY the forward params
    onto a built+initialized workflow.  Velocities, loader cursors,
    decision state and prng streams are training state a serving process
    neither has nor wants — restoring them would couple the service to a
    loader/decision graph it never runs.  Raises on a snapshot whose
    units don't cover the workflow's weighted forwards (serving half a
    model silently would answer garbage)."""
    from znicz_tpu.nn_units import ForwardBase

    units = snap.get("units") or {}
    missing = [f.name for f in workflow.forwards
               if getattr(f, "has_weights", False) and f.name not in units]
    if missing:
        raise ValueError(
            f"snapshot has no params for weighted forward(s) {missing}; "
            f"it covers {sorted(units)} — wrong snapshot for this "
            "workflow?")
    for unit in workflow:
        if isinstance(unit, ForwardBase) and unit.name in units:
            for k, a in unit.params().items():
                a.mem = np.asarray(units[unit.name][k]).copy()


def load_inference(workflow, path: str) -> Dict:
    """Load ``path`` and :func:`restore_inference` it; returns the
    snapshot's metadata (epoch/metric/config — the serving panel shows
    what checkpoint is live) without the param arrays."""
    snap = Snapshotter.load(path)
    restore_inference(workflow, snap)
    return {k: v for k, v in snap.items()
            if k not in ("units", "velocities")}


def _refuse_cross_host(fmt: str, name: str) -> None:
    """The ONE policy message for 'host-format saves need replicated
    state' — raised by both the sync (unit-Array) and async (raw jax
    leaf) guards so the two paths cannot drift (ADVICE-style dedup)."""
    raise ValueError(
        f"snapshot format={fmt!r}: {name} holds state sharded across "
        "hosts; host-format saves assume replicated state — use "
        "format='orbax', sharded=True")


def _jax_cross_host_sharded(a) -> bool:
    """Array.cross_host_sharded's predicate, for a raw jax array leaf."""
    return (hasattr(a, "sharding")
            and not getattr(a, "is_fully_addressable", True)
            and not a.sharding.is_fully_replicated)


class Snapshotter(Unit):
    """Writes snapshots at epoch boundaries.  Wire its gate to
    ``decision.epoch_ended`` and link ``improved`` / ``epoch_number`` from
    the decision; then:

      - validation improved        -> saves ``<prefix>_best``
      - every ``interval`` epochs  -> saves ``<prefix>_epoch_<N>`` (0 = off)
    """

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self._async_thread = None
        self._async_pending = None       # queued (snap, tags) jobs (list)
        self._async_lock = None
        self._async_error = None
        # telemetry (ISSUE 5): writer counters in the registry under
        # component="snapshotter"; historical names via the properties
        from znicz_tpu import telemetry

        _sc = telemetry.scope("snapshotter")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self.prefix = kwargs.get("prefix", "wf")
        self.directory = kwargs.get(
            "directory", root.common.dirs.get("snapshots", "snapshots"))
        self.interval = int(kwargs.get("interval", 0))   # 0 = best-only
        self.compression = kwargs.get("compression", "gz")
        #: "pickle" (reference-style single file) or "orbax" (TPU-native
        #: tensorstore checkpoint dir + meta.json — SURVEY §3.5 rebuild
        #: note); also settable via root.common.engine.snapshot_format
        self.format = kwargs.get(
            "format", root.common.engine.get("snapshot_format", "pickle"))
        #: orbax-only: save the live (possibly mesh-sharded) device arrays
        #: instead of host-gathered numpy — each shard written by its
        #: owner; restore with ``FusedTrainer.restore_sharded`` reshards
        #: onto ANY topology (root.common.engine.snapshot_sharded)
        self.sharded = bool(kwargs.get(
            "sharded", root.common.engine.get("snapshot_sharded", False)))
        #: minimum wall-clock seconds between ON-BEST saves (0 = every
        #: improvement).  On-best snapshots exist for crash recovery;
        #: when epochs are seconds apart, saving every improvement just
        #: saturates the device->host link (each pull is the full param+
        #: velocity set).  Under a rate limit the written best lags the
        #: true best by at most this interval.  Interval (epoch_N) saves
        #: are never rate-limited — their cadence is already the knob.
        #: Config: root.common.engine.snapshot_min_interval_s.
        self.min_save_interval_s = float(kwargs.get(
            "min_save_interval_s",
            root.common.engine.get("snapshot_min_interval_s", 0.0)))
        self._last_best_save_t = -1e18
        self.destination: Optional[str] = None            # last written path
        self.improved = False                             # link from decision
        self.epoch_number = 0                             # link from decision
        self._last_saved_epoch = -1

    #: writer counters registered under component="snapshotter"
    #: (ISSUE 5): name -> HELP text; properties generated after the
    #: class body
    COUNTERS = {
        "async_saves_written": "files written by the async worker",
        "async_saves_coalesced": "superseded queued jobs dropped",
    }

    def snapshot_path(self, tag: str) -> str:
        if self.format == "orbax":
            return os.path.join(self.directory,
                                f"{self.prefix}_{tag}.orbax")
        ext = ".pickle.gz" if self.compression == "gz" else ".pickle"
        return os.path.join(self.directory, f"{self.prefix}_{tag}{ext}")

    def save(self, tag: str) -> str:
        import jax

        multiproc = jax.process_count() > 1
        path = self.snapshot_path(tag)
        if multiproc and self.format != "orbax":
            # host-format saves are not collective: every process holds
            # the same replicated state, so only process 0 writes (two
            # writers would tear the file).  That assumption breaks for
            # state sharded over a cross-host axis — collect() would choke
            # on a non-addressable global array deep inside map_read, so
            # detect it here with an actionable message (ADVICE r4).
            for unit in self.workflow:
                arrays = {}
                if hasattr(unit, "params"):
                    arrays.update(unit.params())
                arrays.update(getattr(unit, "_velocities", None) or {})
                for a in arrays.values():
                    # fully-REPLICATED global arrays are fine (every
                    # process holds a complete copy, np.array works);
                    # only state actually SHARDED across hosts cannot be
                    # host-collected (ADVICE r4)
                    if getattr(a, "cross_host_sharded", False):
                        _refuse_cross_host(self.format, unit.name)
            if jax.process_index() != 0:
                self.destination = path
                return path
        os.makedirs(self.directory, exist_ok=True)
        snap = collect(self.workflow,
                       device_arrays=(self.format == "orbax"
                                      and self.sharded))
        snap["config"] = root.to_dict()
        if self.format == "orbax":
            # collective: every process participates (each writes the
            # array shards it owns); _save_orbax gates the dir reset and
            # meta sidecar to process 0 with barriers
            _save_orbax(path, snap)
        else:
            self._write_host_format(path, snap)
        self.destination = path
        self.info("snapshot -> %s", path)
        return path

    def _interval_due(self, epoch: int) -> bool:
        return bool(self.interval and epoch != self._last_saved_epoch and
                    (epoch + 1) % self.interval == 0)

    def _best_due(self, improved) -> bool:
        return bool(improved) and (
            time.time() - self._last_best_save_t
            >= self.min_save_interval_s)

    def due(self, epoch: int, improved) -> bool:
        """Would ``run()`` write anything for this epoch?  The fused path
        asks BEFORE paying the device->host param writeback — on slow host
        links an unconditional every-epoch writeback was a fixed per-epoch
        tax (VERDICT r3 weak #3)."""
        return self._best_due(improved) or self._interval_due(int(epoch))

    def run(self):
        if self._best_due(self.improved):
            self._last_best_save_t = time.time()
            self.save("best")
        epoch = int(self.epoch_number)
        if self._interval_due(epoch):
            self.save(f"epoch_{epoch}")
            self._last_saved_epoch = epoch

    @staticmethod
    def load(path: str) -> Dict:
        if path.rstrip("/").endswith(".orbax") or os.path.isdir(path):
            return _load_orbax(path.rstrip("/"))
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            return pickle.load(f)

    # -- async (host-format) saves ----------------------------------------
    #
    # The fused fast path snapshots WITHOUT stalling training (VERDICT r4
    # item 4): the trainer hands over a snapshot dict whose param/velocity
    # leaves are still DEVICE arrays (donation-safe copies), and a single
    # background worker pulls them to host and writes the file(s) while
    # the next epoch computes.  Backlog control: a queued-but-unstarted
    # 'best' job is COALESCED away when a newer 'best' arrives (same
    # filename, newer weights — the old job is strictly superseded);
    # interval tags (epoch_N — distinct files) are never dropped, and
    # their rate is bounded by the interval itself, so the queue stays
    # small even on hosts where the device->host pull is link-bound.
    # Pickle-format only; orbax saves are multi-process collectives with
    # barrier ordering and stay synchronous.

    def tags_for(self, epoch: int, improved) -> list:
        """The tags run() would write for this epoch, consuming the
        interval and rate-limit bookkeeping (the async path's equivalent
        of run())."""
        tags = []
        if self._best_due(improved):
            self._last_best_save_t = time.time()
            tags.append("best")
        epoch = int(epoch)
        if self._interval_due(epoch):
            tags.append(f"epoch_{epoch}")
            self._last_saved_epoch = epoch
        return tags

    def save_async(self, snap: Dict, tags) -> None:
        """Queue ``snap`` (leaves may be jax device arrays) to be written
        under ``tags`` by the background worker.  Raises for the orbax
        format (collective — cannot run off-thread)."""
        import threading

        import jax

        if self.format == "orbax":
            raise ValueError("save_async is host-format only; orbax "
                             "saves are collective and synchronous")
        if jax.process_count() > 1:
            for group in ("units", "velocities"):
                for name, leaves in snap.get(group, {}).items():
                    for a in leaves.values():
                        if _jax_cross_host_sharded(a):
                            _refuse_cross_host(self.format, name)
            if jax.process_index() != 0:
                if tags:
                    self.destination = self.snapshot_path(tags[-1])
                return
        if self._async_lock is None:
            self._async_lock = threading.Condition()
        with self._async_lock:
            if self._async_error is not None:
                err, self._async_error = self._async_error, None
                raise err
            if self._async_pending is None:
                self._async_pending = []
            if "best" in tags:
                # a queued-but-unstarted best is strictly superseded by
                # this newer best (same file, newer weights); interval
                # tags on the same queued job survive with THEIR snapshot
                kept = []
                for snap_p, tags_p in self._async_pending:
                    rem = [t for t in tags_p if t != "best"]
                    self._m["async_saves_coalesced"].inc(
                        len(tags_p) - len(rem))
                    if rem:
                        kept.append((snap_p, rem))
                self._async_pending = kept
            self._async_pending.append((snap, list(tags)))
            if self._async_thread is None:
                self._async_thread = threading.Thread(
                    target=self._async_worker, daemon=True,
                    name="znicz-snapshot")
                self._async_thread.start()
            self._async_lock.notify_all()

    def _async_worker(self) -> None:
        while True:
            with self._async_lock:
                while not self._async_pending:
                    self._async_lock.wait()
                snap, tags = self._async_pending.pop(0)
                self._async_busy = True
            try:
                from znicz_tpu import telemetry

                # the device->host pull happens HERE, off the training
                # thread; np.asarray on a (replicated) jax array is the
                # same transfer collect()'s map_read would have paid
                with telemetry.span("snapshot", "pull", tags=list(tags)):
                    for group in ("units", "velocities"):
                        for leaves in snap.get(group, {}).values():
                            for k, a in leaves.items():
                                leaves[k] = np.asarray(a)
                os.makedirs(self.directory, exist_ok=True)
                for tag in tags:
                    path = self.snapshot_path(tag)
                    self._write_host_format(path, snap)
                    # the training thread writes destination too (sync
                    # saves) and save() reads _async_error under this
                    # lock — publish both under it (znicz-lint
                    # thread-shared-state)
                    with self._async_lock:
                        self.destination = path
                    self._m["async_saves_written"].inc()
                    self.info("snapshot (async) -> %s", path)
            except BaseException as exc:   # surfaced on flush/next save
                with self._async_lock:
                    self._async_error = exc
            finally:
                with self._async_lock:
                    self._async_busy = False
                    self._async_lock.notify_all()

    _async_busy = False

    def flush_async(self) -> None:
        """Block until every queued async save is durably written;
        re-raise any worker error (run ends, tests, process exit)."""
        if self._async_lock is None:
            return
        with self._async_lock:
            while self._async_pending or self._async_busy:
                self._async_lock.wait(timeout=0.5)
            if self._async_error is not None:
                err, self._async_error = self._async_error, None
                raise err

    def _write_host_format(self, path: str, snap: Dict) -> None:
        write_host_pickle(path, snap, self.compression)


for _name, _help in Snapshotter.COUNTERS.items():
    setattr(Snapshotter, _name, telemetry_metrics.registered_property(
        _name, _help))
del _name, _help


def write_host_pickle(path: str, snap: Dict, compression: str = "gz") -> None:
    """Atomic (temp file + rename) host-format snapshot write, shared by
    the Snapshotter and the master's crash-resume file (server.py): a
    crash — or the daemon writer dying with the process — mid-dump must
    never truncate the previous good checkpoint; these files exist for
    crash RECOVERY."""
    from znicz_tpu import telemetry

    tmp = path + ".tmp"
    opener = gzip.open if compression == "gz" else open
    try:
        # span site (ISSUE 5): every host-format snapshot write — the
        # Snapshotter's sync and async paths AND the master's
        # crash-resume file all funnel through here
        with telemetry.span("snapshot", "write", path=path,
                            compression=compression):
            with opener(tmp, "wb") as f:
                pickle.dump(snap, f, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Atomic byte-blob write (temp + rename) for sidecar files written
    next to snapshots — the AOT executable cache (serving/aot_cache.py)
    uses this so a replica killed mid-store can never leave a truncated
    entry for the next boot to refuse.  The temp name is pid-suffixed:
    a whole FLEET of replicas may store the same cache entry
    concurrently (same digest, same bytes), and two writers sharing one
    temp path would race the rename against each other's unlink."""
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


_ORBAX_CKPTR = None


def _orbax_checkpointer():
    """One long-lived StandardCheckpointer: per-call instances tear down
    orbax's async executor each time, which races interpreter shutdown."""
    global _ORBAX_CKPTR
    if _ORBAX_CKPTR is None:
        import orbax.checkpoint as ocp

        _ORBAX_CKPTR = ocp.StandardCheckpointer()
    return _ORBAX_CKPTR


def _jsonify(obj):
    """Faithful JSON encoding for the metadata sidecar — numpy arrays (e.g.
    loader-normalizer state) round-trip exactly instead of degrading to a
    (possibly truncated) repr string.  Large arrays (the loader's
    composed shuffle order is O(dataset)) go base64-binary instead of a
    per-element integer list — ~5 bytes/element instead of ~8 chars."""
    if isinstance(obj, np.ndarray):
        if obj.size > 1024:
            import base64

            return {"__ndarray_b64__":
                    base64.b64encode(np.ascontiguousarray(obj)
                                     .tobytes()).decode("ascii"),
                    "__dtype__": str(obj.dtype),
                    "__shape__": list(obj.shape)}
        return {"__ndarray__": obj.tolist(), "__dtype__": str(obj.dtype)}
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, dict):
        return {str(k): _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    return obj


def _dejsonify(obj):
    if isinstance(obj, dict):
        if set(obj) == {"__ndarray__", "__dtype__"}:
            return np.asarray(obj["__ndarray__"], dtype=obj["__dtype__"])
        if set(obj) == {"__ndarray_b64__", "__dtype__", "__shape__"}:
            import base64

            return np.frombuffer(
                base64.b64decode(obj["__ndarray_b64__"]),
                dtype=obj["__dtype__"]).reshape(obj["__shape__"]).copy()
        return {k: _dejsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_dejsonify(v) for v in obj]
    return obj


def _save_orbax(path: str, snap: Dict) -> None:
    """TPU-native checkpoint layout: the weight/velocity pytrees go through
    orbax/tensorstore (sharded-array-capable, no pickled code), everything
    else (loader/decision/prng/config metadata) is a JSON sidecar.
    Multi-controller: COLLECTIVE — every process must call this (each
    writes the shards it owns); only process 0 touches the directory and
    the sidecar, with barriers around the destructive reset."""
    import json
    import shutil

    import jax

    multiproc = jax.process_count() > 1
    path = os.path.abspath(path)
    if not multiproc or jax.process_index() == 0:
        if os.path.exists(path):
            shutil.rmtree(path)
        os.makedirs(path)
    if multiproc:
        from jax.experimental import multihost_utils

        # nobody starts writing into a directory another process may
        # still be deleting
        multihost_utils.sync_global_devices("znicz_snapshot_dir_ready")
    arrays = {"units": snap["units"], "velocities": snap["velocities"]}
    ckptr = _orbax_checkpointer()
    ckptr.save(os.path.join(path, "arrays"), arrays)
    # StandardCheckpointer is async: save() returns before the tensorstore
    # commit.  Block until durable — otherwise the logged destination can
    # name a checkpoint that a crash loses, and a follow-up save to the
    # same tag would rmtree the directory while the commit is still
    # renaming its tmpdir inside it (ADVICE r3).
    ckptr.wait_until_finished()
    if not multiproc or jax.process_index() == 0:
        meta = {k: v for k, v in snap.items()
                if k not in ("units", "velocities")}
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(_jsonify(meta), f, default=repr)   # inf/nan: py-style


def load_orbax_meta(path: str) -> Dict:
    import json

    with open(os.path.join(os.path.abspath(path), "meta.json")) as f:
        return _dejsonify(json.load(f))


def load_orbax_arrays(path: str, template=None):
    """Restore the {"units", "velocities"} pytree.  ``template`` (a pytree
    of ``jax.ShapeDtypeStruct`` with per-leaf ``sharding``) makes orbax/
    tensorstore deliver each leaf ALREADY placed in the target sharding —
    the cross-topology half of checkpoint/resume: save under one mesh,
    restore under another (or a single chip) without a host round-trip."""
    return _orbax_checkpointer().restore(
        os.path.join(os.path.abspath(path), "arrays"), target=template)


def _load_orbax(path: str) -> Dict:
    arrays = load_orbax_arrays(path)
    meta = load_orbax_meta(path)
    return {**meta, "units": arrays["units"],
            "velocities": arrays["velocities"]}
