"""Accelerated-unit layer (rebuild of ``veles/accelerated_units.py``).

The reference's L3 did three jobs; here is where each went on TPU:

  1. **Per-backend method dispatch** (``ocl_run``/``cuda_run``/``numpy_run``)
     — gone by construction: every compute unit's ``apply`` is a pure jax
     function and XLA is the only backend; ``jax.jit`` on CPU *is* the
     reference's "numpy backend" (same code, same numbers, no divergence to
     test against).  ``AcceleratedUnit``/``AcceleratedWorkflow`` below are
     therefore aliases of the real bases, kept so reference-era code and
     readers find the layer where they expect it.
  2. **Kernel source assembly + caching** (#define injection, .cl/.cu
     builds) — replaced by jit tracing: shapes/hyperparameters are Python
     attributes read at trace time, and XLA's compilation cache replaces
     the reference's on-disk kernel cache.
  3. **DeviceBenchmark** — preserved below: micro-benchmarks available jax
     backends with a representative fused matmul step and reports/selects
     the fastest (the reference used this to auto-pick OpenCL vs CUDA).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import numpy as np

from znicz_tpu.nn_units import ForwardBase, GradientDescentBase
from znicz_tpu.core.workflow import Workflow

#: reference-era names for the same layers
AcceleratedUnit = ForwardBase
AcceleratedGDUnit = GradientDescentBase
AcceleratedWorkflow = Workflow


class DeviceBenchmark:
    """Times one representative fused step (matmul + bias + tanh, fwd+bwd)
    per available backend; ``best()`` returns the fastest platform name."""

    def __init__(self, size: int = 1024, repeats: int = 5):
        self.size = int(size)
        self.repeats = int(repeats)
        self.results: Dict[str, float] = {}

    def _step_time(self, device) -> float:
        import jax
        import jax.numpy as jnp

        n = self.size
        x = jax.device_put(np.ones((n, n), np.float32), device)
        w = jax.device_put(
            np.random.default_rng(0).normal(
                0, 0.01, (n, n)).astype(np.float32), device)

        @jax.jit
        def step(w, x):
            def loss(w):
                return jnp.sum(jnp.tanh(x @ w))

            g = jax.grad(loss)(w)
            return w - 0.01 * g

        w = step(w, x)                      # compile + warm
        jax.block_until_ready(w)
        t0 = time.perf_counter()
        for _ in range(self.repeats):
            w = step(w, x)
        jax.block_until_ready(w)
        return (time.perf_counter() - t0) / self.repeats

    def run(self) -> Dict[str, float]:
        import jax

        platforms = {d.platform for d in jax.devices()}
        for platform in platforms:
            try:
                dev = jax.devices(platform)[0]
                self.results[platform] = self._step_time(dev)
            except RuntimeError:
                continue
        return self.results

    def best(self) -> Optional[str]:
        if not self.results:
            self.run()
        if not self.results:
            return None
        return min(self.results, key=self.results.get)
