"""Learning-rate scheduling (rebuild of ``znicz/lr_adjust.py``).

Caffe-style policies applied to GD units over training iterations:

  - ``fixed``     — lr(t) = base
  - ``step``      — lr(t) = base · gamma^floor(t / step)
  - ``exp``       — lr(t) = base · gamma^t
  - ``inv``       — lr(t) = base · (1 + gamma·t)^(−power)
  - ``arbitrary`` — lr(t) = fn(base, t)

``LearningRateAdjust`` sits in the control graph after the GD chain (or the
decision in fused mode), counts train iterations, and writes the scheduled
lr into each bound GD unit's ``learning_rate``/``learning_rate_bias`` —
which both execution paths read per step (the fused step takes hypers as
traced arguments precisely so this never recompiles).
"""

from __future__ import annotations

from typing import Callable, List, Optional

from znicz_tpu.core.units import Unit


class LRPolicyBase:
    def __call__(self, base: float, it: int) -> float:
        raise NotImplementedError


class FixedPolicy(LRPolicyBase):
    def __call__(self, base, it):
        return base


class StepPolicy(LRPolicyBase):
    def __init__(self, gamma=0.1, step=1000):
        self.gamma, self.step = float(gamma), int(step)

    def __call__(self, base, it):
        return base * self.gamma ** (it // self.step)


class ExpPolicy(LRPolicyBase):
    def __init__(self, gamma=0.999):
        self.gamma = float(gamma)

    def __call__(self, base, it):
        return base * self.gamma ** it


class InvPolicy(LRPolicyBase):
    def __init__(self, gamma=0.0001, power=0.75):
        self.gamma, self.power = float(gamma), float(power)

    def __call__(self, base, it):
        return base * (1.0 + self.gamma * it) ** (-self.power)


class ArbitraryPolicy(LRPolicyBase):
    def __init__(self, fn: Callable[[float, int], float]):
        self.fn = fn

    def __call__(self, base, it):
        return self.fn(base, it)


POLICIES = {"fixed": FixedPolicy, "step": StepPolicy, "exp": ExpPolicy,
            "inv": InvPolicy}


def make_policy(name: str, **kwargs) -> LRPolicyBase:
    return POLICIES[name](**kwargs)


class LearningRateAdjust(Unit):
    """Bind with ``add_gd(gd_unit, policy [, bias_policy])``; each run()
    (one per train minibatch) advances the iteration counter and rewrites
    the bound units' learning rates."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.iteration = 0
        self._bindings: List[tuple] = []

    def add_gd(self, gd, policy: LRPolicyBase,
               bias_policy: Optional[LRPolicyBase] = None) -> None:
        self._bindings.append(
            (gd, float(gd.learning_rate), float(gd.learning_rate_bias),
             policy, bias_policy or policy))

    def _apply(self, it: int) -> None:
        for gd, base, base_bias, pol, bias_pol in self._bindings:
            gd.learning_rate = pol(base, it)
            gd.learning_rate_bias = bias_pol(base_bias, it)

    def run(self):
        self._apply(self.iteration)
        self.iteration += 1

    def restore_iteration(self, iteration: int) -> None:
        """Rewind the schedule to the state right after ``iteration`` many
        ``run()`` calls (the fused deep pipeline's speculation rollback):
        counter reset and the bound units' lrs rewritten accordingly —
        back to the configured bases for iteration 0."""
        self.iteration = int(iteration)
        if self.iteration > 0:
            self._apply(self.iteration - 1)
        else:
            for gd, base, base_bias, _pol, _bias_pol in self._bindings:
                gd.learning_rate = base
                gd.learning_rate_bias = base_bias
