"""Unit: the dataflow-graph node (rebuild of the reference's ``veles/units.py``).

Semantics preserved from the reference (SURVEY.md §2.1 "Unit graph"):

  - control edges via ``link_from`` — a unit runs when *all* units it is
    linked from have fired in the current wave;
  - data edges via ``link_attrs`` — attribute reads forward to the source
    unit's attribute at access time (aliasing, not copying);
  - ``gate_block`` (don't run, don't propagate) and ``gate_skip`` (don't run,
    but propagate) as linkable ``Bool``s;
  - ``initialize()`` / ``run()`` lifecycle.

What changed for TPU: the reference executed units on a thread pool with
event-driven firing; device queues made that safe.  Here execution is a
deterministic single-threaded breadth-first wave over the control graph
(``Workflow.run``) — JAX's async dispatch already overlaps host control with
device compute, so host threads would add nondeterminism for zero throughput.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from znicz_tpu.core.logger import Logger
from znicz_tpu.core.mutable import Bool, LinkableAttribute

AttrLink = Union[str, Tuple[str, str]]


class Unit(Logger):
    """A node in the workflow graph."""

    def __init__(self, workflow: Optional["Unit"] = None,
                 name: Optional[str] = None, **kwargs) -> None:
        # NB: bypass __setattr__ while the link tables don't exist yet.
        object.__setattr__(self, "_linked_attrs", {})
        self.name = name or type(self).__name__
        self.workflow = None
        self.links_from: Dict["Unit", bool] = {}   # unit -> fired this wave
        self.links_to: List["Unit"] = []
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self._initialized = False
        self.run_count = 0
        self.run_time = 0.0                         # host seconds, cumulative
        if workflow is not None:
            workflow.add_unit(self)

    # -- attribute linking ---------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        # Only called when normal lookup fails -> consult attr links.
        links = object.__getattribute__(self, "_linked_attrs")
        link = links.get(name)
        if link is not None:
            return link.get()
        raise AttributeError(
            f"{type(self).__name__} {getattr(self, 'name', '?')!r} has no "
            f"attribute {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        links = object.__getattribute__(self, "_linked_attrs")
        link = links.get(name)
        if link is not None and link.two_way:
            link.set(value)
            return
        if link is not None:
            # Writing a one-way linked attr detaches the link (reference
            # allowed shadowing); warn in debug builds via logger.
            del links[name]
        object.__setattr__(self, name, value)

    def link_attrs(self, other: "Unit", *attrs: AttrLink,
                   two_way: bool = False) -> "Unit":
        """Create data edges.  Each attr is either ``"name"`` (same name both
        sides) or ``("mine", "theirs")``."""
        for attr in attrs:
            mine, theirs = (attr, attr) if isinstance(attr, str) else attr
            # Drop any instance attribute that would shadow the link.
            if mine in self.__dict__:
                object.__delattr__(self, mine)
            self._linked_attrs[mine] = LinkableAttribute(other, theirs,
                                                         two_way=two_way)
        return self

    def unlink_attrs(self, *names: str) -> None:
        for name in names:
            self._linked_attrs.pop(name, None)

    def has_linked_attr(self, name: str) -> bool:
        return name in self._linked_attrs

    # -- control linking -----------------------------------------------------

    def link_from(self, *units: "Unit") -> "Unit":
        for unit in units:
            if unit is self:
                raise ValueError(f"{self.name}: cannot link from itself")
            self.links_from[unit] = False
            if self not in unit.links_to:
                unit.links_to.append(self)
        return self

    def unlink_from(self, *units: "Unit") -> "Unit":
        for unit in units:
            self.links_from.pop(unit, None)
            if self in unit.links_to:
                unit.links_to.remove(self)
        return self

    def unlink_all(self) -> None:
        for unit in list(self.links_from):
            self.unlink_from(unit)
        for unit in list(self.links_to):
            unit.unlink_from(self)

    # -- lifecycle -----------------------------------------------------------

    def initialize(self, **kwargs) -> None:
        """Allocate state; called once by the owning workflow before run.
        Subclasses override and should call super().initialize(**kwargs)."""
        self._initialized = True

    def run(self) -> None:
        """Execute one firing.  Subclasses override."""

    def stop(self) -> None:
        """Called at workflow teardown; subclasses release resources here."""

    @property
    def is_initialized(self) -> bool:
        return self._initialized

    def reset_links(self) -> None:
        for unit in self.links_from:
            self.links_from[unit] = False

    def ready(self) -> bool:
        return all(self.links_from.values()) if self.links_from else True

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.name!r}>"


class TrivialUnit(Unit):
    """A unit with no compute — pure control-graph plumbing."""
