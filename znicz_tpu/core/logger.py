"""Logging mixin (rebuild of the reference's ``veles/logger.py``).

Colored per-unit console logging; every Unit mixes this in and logs under its
own name.  MongoDB event logging from the reference is intentionally dropped
(documented gap — structured per-epoch metrics go through the Decision /
bench harness instead).
"""

from __future__ import annotations

import logging
import sys
import time

_CONFIGURED = False

_COLORS = {
    logging.DEBUG: "\033[37m",
    logging.INFO: "\033[36m",
    logging.WARNING: "\033[33m",
    logging.ERROR: "\033[31m",
    logging.CRITICAL: "\033[1;31m",
}
_RESET = "\033[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        msg = super().format(record)
        if sys.stderr.isatty():
            color = _COLORS.get(record.levelno, "")
            return f"{color}{msg}{_RESET}"
        return msg


def setup_logging(level: int = logging.INFO) -> None:
    global _CONFIGURED
    if _CONFIGURED:
        logging.getLogger("znicz").setLevel(level)
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        _ColorFormatter("%(asctime)s %(levelname).1s %(name)s: %(message)s",
                        datefmt="%H:%M:%S"))
    log = logging.getLogger("znicz")
    log.addHandler(handler)
    log.setLevel(level)
    log.propagate = False
    _CONFIGURED = True


class Logger:
    """Mixin giving subclasses a named logger and debug/info/warning helpers."""

    @property
    def logger(self) -> logging.Logger:
        name = getattr(self, "name", None) or type(self).__name__
        return logging.getLogger(f"znicz.{name}")

    def debug(self, msg: str, *args) -> None:
        self.logger.debug(msg, *args)

    def info(self, msg: str, *args) -> None:
        self.logger.info(msg, *args)

    def warning(self, msg: str, *args) -> None:
        self.logger.warning(msg, *args)

    def error(self, msg: str, *args) -> None:
        self.logger.error(msg, *args)


class timeit:
    """Context manager: ``with timeit() as t: ...; t.elapsed``."""

    def __enter__(self) -> "timeit":
        self.start = time.perf_counter()
        self.elapsed = 0.0
        return self

    def __exit__(self, *exc) -> None:
        self.elapsed = time.perf_counter() - self.start
