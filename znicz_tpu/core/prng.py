"""Seeded PRNG service (rebuild of the reference's ``veles/prng/``).

The reference kept one globally-seeded xorshift stream consumed in
unit-creation order, plus device-side xorshift kernels for dropout /
stochastic pooling.  That design is hostile to SPMD reproducibility, so the
TPU rebuild replaces it (documented RNG divergence, SURVEY.md §7 hard part 2)
with:

  - named host streams: ``get(name)`` returns a ``RandomGenerator`` with a
    numpy Generator seeded by hash(global_seed, name) — used for weight init,
    loader shuffling, GA mutation.  Deterministic and order-independent.
  - device keys: ``RandomGenerator.jax_key(step)`` folds the stream's seed and
    a step counter into a ``jax.random`` threefry key — used inside jitted
    train steps for dropout / stochastic pooling masks.  Per-step folding
    keeps the train step pure (no RNG state threading through the loop).

Parity with the reference is *distributional* (same loss curves within
tolerance), not bitwise.
"""

from __future__ import annotations

import hashlib
from typing import Dict

import numpy as np


def _derive_seed(global_seed: int, name: str) -> int:
    digest = hashlib.sha256(f"{global_seed}:{name}".encode()).digest()
    return int.from_bytes(digest[:8], "little") & 0x7FFFFFFFFFFFFFFF


class RandomGenerator:
    """One named random stream: numpy host RNG + jax device-key derivation."""

    def __init__(self, name: str, seed: int) -> None:
        self.name = name
        self.seed = _derive_seed(seed, name)
        self.state = np.random.default_rng(self.seed)
        self._jax_base = None       # cached PRNGKey(seed), built lazily

    # -- host-side (numpy) ---------------------------------------------------

    def fill_uniform(self, arr: np.ndarray, low: float, high: float) -> None:
        arr[...] = self.state.uniform(low, high, size=arr.shape).astype(
            arr.dtype, copy=False)

    def fill_normal(self, arr: np.ndarray, stddev: float) -> None:
        arr[...] = self.state.normal(0.0, stddev, size=arr.shape).astype(
            arr.dtype, copy=False)

    def uniform(self, low: float, high: float, shape, dtype=np.float32):
        return self.state.uniform(low, high, size=shape).astype(dtype)

    def normal(self, stddev: float, shape, dtype=np.float32):
        return self.state.normal(0.0, stddev, size=shape).astype(dtype)

    def permutation(self, n: int) -> np.ndarray:
        return self.state.permutation(n)

    def randint(self, low: int, high: int) -> int:
        return int(self.state.integers(low, high))

    # -- device-side (jax) ---------------------------------------------------

    def jax_base_key(self):
        """The stream's base PRNGKey(seed), built once and cached — per-step
        keys are ``fold_in(base, step)``; consumers inside jit should take
        the base as an argument and fold_in IN-GRAPH (each eager
        PRNGKey+fold_in pair costs several host->device dispatches, ~3ms
        on tunneled platforms)."""
        if self._jax_base is None:
            import jax

            self._jax_base = jax.random.PRNGKey(self.seed)
        return self._jax_base

    def jax_key(self, step: int = 0):
        """A threefry key derived from (stream seed, step) — identical to
        ``fold_in(jax_base_key(), step)``.  Import of jax is deferred so
        pure-host users (loaders, GA) never touch the device."""
        import jax

        return jax.random.fold_in(self.jax_base_key(), step)

    def reseed(self, seed: int) -> None:
        self.seed = _derive_seed(seed, self.name)
        self.state = np.random.default_rng(self.seed)
        self._jax_base = None


_streams: Dict[str, RandomGenerator] = {}
_global_seed: int | None = None


def _seed() -> int:
    global _global_seed
    if _global_seed is None:
        from znicz_tpu.core.config import root

        _global_seed = int(root.common.engine.get("seed", 1013))
    return _global_seed


def get(name: str = "default") -> RandomGenerator:
    """Return (creating on first use) the named stream."""
    stream = _streams.get(name)
    if stream is None:
        stream = RandomGenerator(name, _seed())
        _streams[name] = stream
    return stream


def seed_all(seed: int) -> None:
    """Reset the global seed and reseed every existing stream (tests use this
    to make module-order irrelevant)."""
    global _global_seed
    _global_seed = int(seed)
    from znicz_tpu.core.config import root

    root.common.engine.seed = int(seed)
    for stream in _streams.values():
        stream.reseed(_global_seed)


def reset(seed: int) -> None:
    """Drop every named stream and reseed: the state is indistinguishable
    from a fresh process started with this global seed.  The public home of
    the ``_streams.clear(); seed_all(seed)`` idiom."""
    _streams.clear()
    seed_all(seed)
