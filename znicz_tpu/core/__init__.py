"""Core engine: config, mutable gates, units, workflow, PRNG, logging."""
