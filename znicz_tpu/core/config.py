"""Global dotted config tree.

TPU-native rebuild of the reference's ``veles/config.py`` (SURVEY.md §2.1
"Config"): a global attribute-tree ``root`` that sample configs mutate
(``root.mnistr.decision.max_epochs = 3``) and that the CLI can override with
dotted ``key.path=value`` arguments.  Unlike the reference we also support
snapshot/restore of subtrees to plain dicts (used by the snapshotter to make
checkpoints self-describing).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, Iterator, Tuple


class Config:
    """An attribute tree node.  Accessing an unknown attribute creates a child
    ``Config``, so configs can be assigned deeply without pre-declaration::

        root.mnist.loader.minibatch_size = 60
    """

    def __init__(self, path: str = "") -> None:
        # NB: use object.__setattr__ to dodge our own __setattr__ guard.
        object.__setattr__(self, "_path", path)
        object.__setattr__(self, "_children", {})

    # -- tree access ---------------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        children = object.__getattribute__(self, "_children")
        if name not in children:
            children[name] = Config(self._join(name))
        return children[name]

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        if isinstance(value, dict):
            node = Config(self._join(name))
            node.update(value)
            value = node
        self._children[name] = value

    def __delattr__(self, name: str) -> None:
        self._children.pop(name, None)

    def _join(self, name: str) -> str:
        return f"{self._path}.{name}" if self._path else name

    # -- dict-ish API --------------------------------------------------------

    def update(self, values: Dict[str, Any]) -> "Config":
        """Recursively merge a plain dict into this subtree."""
        for key, value in values.items():
            if isinstance(value, dict):
                child = getattr(self, key)
                if not isinstance(child, Config):
                    child = Config(self._join(key))
                    self._children[key] = child
                child.update(value)
            else:
                setattr(self, key, value)
        return self

    def defaults(self, values: Dict[str, Any]) -> "Config":
        """Like update(), but existing leaves win — sample modules use this
        so user/CLI overrides set before import are not clobbered."""
        for key, value in values.items():
            existing = self._children.get(key)
            # An empty Config node is what a mere *read* autovivifies —
            # treat it as absent (same rule get() uses), not as user-set.
            is_vacant = (existing is None or
                         (isinstance(existing, Config) and not existing))
            if isinstance(value, dict):
                if existing is not None and isinstance(existing, Config):
                    existing.defaults(value)
                elif is_vacant:
                    setattr(self, key, value)
                # else: user set a leaf where we default a subtree — user wins
            elif is_vacant:
                setattr(self, key, value)
        return self

    def get(self, name: str, default: Any = None) -> Any:
        """Return a leaf value, or ``default`` if absent or still a bare node."""
        value = self._children.get(name, default)
        if isinstance(value, Config) and not value._children:
            return default
        return value

    def items(self) -> Iterator[Tuple[str, Any]]:
        return iter(self._children.items())

    def __contains__(self, name: str) -> bool:
        return name in self._children

    def __bool__(self) -> bool:
        return bool(self._children)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for key, value in self._children.items():
            out[key] = value.to_dict() if isinstance(value, Config) else value
        return out

    def __repr__(self) -> str:
        return f"Config({self._path!r}: {self.to_dict()!r})"

    # -- dotted-path access (CLI overrides) ----------------------------------

    def set_by_path(self, dotted: str, value: Any) -> None:
        parts = dotted.split(".")
        node: Config = self
        for part in parts[:-1]:
            node = getattr(node, part)
            if not isinstance(node, Config):
                raise KeyError(f"{dotted}: {part} is a leaf, not a subtree")
        setattr(node, parts[-1], value)

    def get_by_path(self, dotted: str, default: Any = None) -> Any:
        parts = dotted.split(".")
        node: Any = self
        for part in parts[:-1]:
            if not isinstance(node, Config):
                return default
            node = node._children.get(part)
        if not isinstance(node, Config):
            return default
        return node.get(parts[-1], default)


def parse_override(arg: str) -> Tuple[str, Any]:
    """Parse one CLI override ``a.b.c=value``; value via literal_eval with a
    string fallback (so ``root.x.path=/tmp/foo`` works unquoted)."""
    if "=" not in arg:
        raise ValueError(f"override must look like key.path=value, got {arg!r}")
    key, raw = arg.split("=", 1)
    key = key.strip()
    if key.startswith("root."):
        key = key[len("root."):]
    try:
        value = ast.literal_eval(raw)
    except (ValueError, SyntaxError):
        value = raw
    return key, value


def apply_overrides(cfg: "Config", args: list[str]) -> None:
    for arg in args:
        key, value = parse_override(arg)
        cfg.set_by_path(key, value)


#: The global config tree, mirroring the reference's ``veles.config.root``.
root = Config("root")

# Engine-wide defaults (the reference kept these under root.common.*).
root.common.engine.seed = 1013
root.common.engine.backend = "auto"      # "tpu" | "cpu" | "auto"
root.common.engine.fuse = True           # compile fused train steps
root.common.engine.precision = "float32"  # "float32" | "bfloat16" activations
root.common.dirs.snapshots = "snapshots"
root.common.dirs.cache = ".znicz_cache"
root.common.dirs.datasets = "datasets"

#: Declaration table for every ``root.common.engine.*`` knob the package
#: reads (ISSUE 7 satellite — the serving DEFAULTS discipline extended to
#: the engine tree).  The Config tree autovivifies, so an undeclared or
#: typo'd knob silently reads as its default forever under dotted CLI
#: overrides; tests/test_no_adhoc_counters.py greps every literal
#: ``root.common.engine`` access in the package and fails on keys missing
#: here.  Values are the DOCUMENTED defaults (the read sites keep their
#: own — this table declares, it does not apply).
ENGINE_DEFAULTS = {
    # core
    "seed": 1013,
    "backend": "auto",            # "tpu" | "cpu" | "auto"
    "fuse": True,                 # compile fused train steps
    "fused": False,               # launcher --fused (fast-path engine)
    # precision (ISSUE 7: compute_dtype is canonical; precision legacy)
    "precision": "float32",       # legacy alias of compute_dtype
    "compute_dtype": None,        # "float32" | "bf16"/"bfloat16"
    "master_dtype": "float32",    # bf16-STORED master weights (variant)
    "state_dtype": "float32",     # optimizer-state (velocity) storage
    # fused-trainer shape
    "remat": False,
    "scan_chunk": 8,
    "pipeline_depth": 1,
    "async_snapshot": True,
    # fusion experiments / kernels
    "fused_elementwise": False,   # conv1/conv2 single-pass Pallas block
    "fused_tail": False,          # ISSUE 7: conv3-5 + FC + loss epilogues
    "lrn_pow": False,
    "lrn_autodiff": False,
    "pallas_lrn": False,
    "pool_bwd": "sas",            # "sas" | "mask"
    # ingest / staging (ISSUE 7)
    "prefetch_segments": 2,
    "decode_workers": None,
    "stream_budget_mb": None,
    "native_shuffle": False,
    "async_staging": True,        # double-buffered device staging
    "staging_donate": True,       # donate staged buffers (non-CPU)
    "xla_latency_hiding": False,  # XLA latency-hiding-scheduler flags
    # snapshots
    "snapshot_format": "pickle",
    "snapshot_sharded": False,
    "snapshot_min_interval_s": 0.0,
    # master/slave roles + wire
    "mode": "",                   # "" | "master" | "slave"
    "master_bind": "tcp://*:5570",
    "master_resume": "",
    "slave_endpoint": None,
    "job_segment": 1,
    "job_prefetch": True,
    "job_timeout_mult": 8.0,
    "slave_ttl": 60.0,
    "slave_reconnects": 8,
    "slave_backoff_base": 0.25,
    "slave_backoff_cap": 5.0,
    # unified transport core (ISSUE 14)
    "slave_breaker_failures": 4,  # consecutive transport failures that
    #                               open the training client's breaker
    #                               (fail-fast to a dead master); 0 off
    "ingress_rate_limit": 0.0,    # per-slave JOB requests/s the master
    #                               admits (flood -> wait); 0 = off
    "ingress_rate_burst": 0.0,    # bucket capacity; 0 = auto (1s rate)
    "job_deadline": True,         # stamp deadline_ms budgets on jobs;
    #                               expired jobs drop at slave/relay
    # fleet observability (ISSUE 20): training-plane SLO — apply
    # progress (accepted delta applies vs refused/stale/quarantined),
    # advisory burn rates on /slo.json, never a readiness gate
    "obs_slo_apply_progress": 0.99,
    "obs_slo_fast_window_s": 60.0,
    "obs_slo_slow_window_s": 600.0,
    "quarantine_norm_mult": 25.0,
    "master_snapshot_s": 10.0,
    "wire_dtype": "float32",      # "float32" | "bfloat16" | "int8"
    "wire_compress": "none",      # "none" | "zlib" | "lz4"
    # relay-tree aggregation (ISSUE 10)
    "tree_fanout": 2,             # children per relay; job-batch factor
    "relay_flush_s": 0.05,        # max buffered-contribution age
    "relay_child_ttl": 30.0,      # relay-tier child eviction window (a
    #                               tree wants a SHORTER leaf TTL than
    #                               the master's relay TTL: slave_ttl)
    # sequence workloads (ISSUE 15)
    "seq_parallel": 0,            # ring-attention sp mesh size for
    #                               MultiHeadAttention (0/1 = off; the
    #                               single-device path, bit-exact)
    # pod-sliced training (ISSUE 18): each slave/relay leaf a mesh slice
    "train_shard": False,         # gate; OFF = single-device bit-exact
    #                               whatever the mesh knobs say
    "mesh": {                     # the training slice (train_shard on):
        "data": 1,                # batch sharding over ICI (psum tier)
        "model": 1,               # column-sharded wide FC weights
    },
    # elastic async training (ISSUE 11)
    "min_slaves": 0,              # quorum gate; 0 = no gate
    "staleness_bound": 0,         # refuse deltas staler than this many
    #                               applies (re-queued); 0 = unbounded
    "staleness_weight": False,    # scale applies by 1/(1+staleness)
    "elastic_rehome": False,      # master redirects orphan leaves that
    #                               register directly to a live relay
}
