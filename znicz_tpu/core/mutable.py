"""Linkable mutable booleans — the control-flow currency of the unit graph.

Rebuild of the reference's ``veles/mutable.py`` (SURVEY.md §2.1 "Mutable
flags"): a ``Bool`` is a tiny mutable cell whose truth value can change over
time and that supports composition (``~a``, ``a & b``, ``a | b``) by
*reference*, so a unit's ``gate_block`` can be wired to, e.g.,
``~decision.complete`` once and track it forever.  Units' gates and the
Decision's ``complete``/``improved`` flags are Bools.
"""

from __future__ import annotations

from typing import Callable, List, Optional


class Bool:
    """A mutable boolean cell, composable by reference.

    Derived Bools (from ``~``, ``&``, ``|``) recompute from their sources on
    every truth test, so flipping a source flips every expression built on it.
    Assignment via ``<<=`` copies the *current* value (detaching any derived
    expression), matching the reference semantics where gates could be both
    expressions and plain flags.
    """

    __slots__ = ("_value", "_compute", "on_change")

    def __init__(self, value: bool = False) -> None:
        self._value = bool(value)
        self._compute: Optional[Callable[[], bool]] = None
        self.on_change: List[Callable[["Bool"], None]] = []

    # -- value ---------------------------------------------------------------

    def __bool__(self) -> bool:
        if self._compute is not None:
            return self._compute()
        return self._value

    @property
    def value(self) -> bool:
        return bool(self)

    @property
    def derived(self) -> bool:
        """True when this Bool is a live expression over other Bools (its
        value can flip when a source flips), False for a plain cell."""
        return self._compute is not None

    def set(self, value: bool) -> None:
        """Set a concrete value (detaches any derived expression)."""
        value = bool(value)
        changed = value != bool(self)
        self._compute = None
        self._value = value
        if changed:
            for cb in tuple(self.on_change):
                cb(self)

    def __ilshift__(self, value) -> "Bool":  # b <<= True / b <<= other_bool
        self.set(bool(value))
        return self

    # -- composition (by reference) ------------------------------------------

    @classmethod
    def _derived(cls, compute: Callable[[], bool]) -> "Bool":
        b = cls()
        b._compute = compute
        return b

    def __invert__(self) -> "Bool":
        return Bool._derived(lambda: not bool(self))

    def __and__(self, other) -> "Bool":
        return Bool._derived(lambda: bool(self) and bool(other))

    def __or__(self, other) -> "Bool":
        return Bool._derived(lambda: bool(self) or bool(other))

    def __repr__(self) -> str:
        kind = "derived" if self._compute is not None else "plain"
        return f"Bool({bool(self)}, {kind})"


class LinkableAttribute:
    """Forwarding descriptor support: ``link_attrs`` on units stores
    (source_object, source_name) pairs; attribute reads on the linked unit
    resolve through to the source at access time, so rebinding the source's
    attribute (a new jax array each step) is always visible downstream.

    Implemented inside ``Unit.__getattr__``/``__setattr__``; this class only
    holds the link record, kept as its own type for introspection/graphviz.
    """

    __slots__ = ("obj", "name", "two_way")

    def __init__(self, obj, name: str, two_way: bool = False) -> None:
        self.obj = obj
        self.name = name
        self.two_way = two_way

    def get(self):
        return getattr(self.obj, self.name)

    def set(self, value) -> None:
        setattr(self.obj, self.name, value)

    def __repr__(self) -> str:
        arrow = "<->" if self.two_way else "->"
        return f"Link({arrow} {type(self.obj).__name__}.{self.name})"
