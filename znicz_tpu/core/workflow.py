"""Workflow: a container Unit with a run loop (rebuild of ``veles/workflow.py``
+ ``veles/plumbing.py``).

Control semantics preserved from the reference: ``StartPoint`` fires first;
units fire when all their control predecessors fired (``Repeater`` fires when
*any* did, closing the training loop); ``EndPoint`` stops the workflow.
Execution is a deterministic single-threaded event queue (see units.py for
why the reference's thread pool was dropped).
"""

from __future__ import annotations

import time
from collections import deque
from typing import List, Optional

from znicz_tpu.core.mutable import Bool
from znicz_tpu.core.units import TrivialUnit, Unit


class StartPoint(TrivialUnit):
    pass


class EndPoint(TrivialUnit):
    def run(self) -> None:
        self.workflow.stopped.set(True)


class Repeater(TrivialUnit):
    """Loop-closing unit: opens its gate when ANY predecessor fired (the
    reference's plumbing.Repeater), so start_point and the tail of the GD
    chain can both feed it."""

    gate_any = True


class Workflow(Unit):
    """A Unit that owns a set of units and runs their control graph."""

    def __init__(self, workflow: Optional[Unit] = None,
                 name: Optional[str] = None, **kwargs) -> None:
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.units: List[Unit] = []
        self.start_point = StartPoint(name="start_point")
        self.end_point = EndPoint(name="end_point")
        self.add_unit(self.start_point)
        self.add_unit(self.end_point)
        self.stopped = Bool(False)
        self.device = None
        self._run_time_started = 0.0

    # -- membership ----------------------------------------------------------

    def add_unit(self, unit: Unit) -> None:
        if unit not in self.units:
            # Uniquify the name: snapshots and attr-link debugging key units
            # by name, so two default-named All2AllTanh's must not collide.
            taken = {u.name for u in self.units}
            if unit.name in taken:
                i = 2
                while f"{unit.name}_{i}" in taken:
                    i += 1
                unit.name = f"{unit.name}_{i}"
            self.units.append(unit)
            unit.workflow = self

    def del_unit(self, unit: Unit) -> None:
        if unit in self.units:
            unit.unlink_all()
            self.units.remove(unit)
            unit.workflow = None

    def __iter__(self):
        return iter(self.units)

    def index_of(self, unit: Unit) -> int:
        return self.units.index(unit)

    # -- lifecycle -----------------------------------------------------------

    def initialize(self, device=None, **kwargs) -> None:
        """Initialize every unit.  Units whose initialize raises a documented
        ``ReInitRequired`` are retried after the rest (the reference iterated
        until attribute links resolved; one retry pass suffices here because
        links are lazy)."""
        super().initialize(**kwargs)
        if device is None:
            from znicz_tpu.backends import Device
            device = Device.auto()
        self.device = device
        pending = [u for u in self.units if not u.is_initialized]
        retry: List[tuple] = []
        for unit in pending:
            try:
                unit.initialize(device=device, **kwargs)
            except AttributeError as exc:
                retry.append((unit, exc))
        for unit, first_exc in retry:
            try:
                unit.initialize(device=device, **kwargs)
            except Exception as exc:
                # A genuinely broken unit fails both passes; surface the
                # first-pass error as the cause instead of hiding it.
                raise exc from first_exc

    def run(self) -> None:
        """Run the control graph until EndPoint fires (or nothing is ready)."""
        from znicz_tpu import telemetry

        if not self.is_initialized:
            self.initialize()
        self.stopped.set(False)
        for unit in self.units:
            unit.reset_links()
        tracer = telemetry.tracer()
        self._run_time_started = time.perf_counter()
        queue: deque[Unit] = deque([self.start_point])
        queued = {self.start_point}
        while queue and not self.stopped:
            unit = queue.popleft()
            queued.discard(unit)
            if bool(unit.gate_block):
                continue
            if not bool(unit.gate_skip):
                started = time.perf_counter()
                unit.run()
                elapsed = time.perf_counter() - started
                unit.run_time += elapsed
                unit.run_count += 1
                if tracer.enabled:
                    # reuse the timing above: one deque append per unit
                    # firing, no extra clock reads (ISSUE 5 span site)
                    tracer.add("unit", unit.name, started, elapsed)
            for target in unit.links_to:
                target.links_from[unit] = True
                fire = (any(target.links_from.values())
                        if getattr(target, "gate_any", False)
                        else all(target.links_from.values()))
                if fire and target not in queued:
                    # Dedup: a gate_any unit (Repeater) fed by two units that
                    # fire in the same wave must still run once per wave.
                    target.reset_links()
                    queue.append(target)
                    queued.add(target)
        self.run_time += time.perf_counter() - self._run_time_started

    def stop(self) -> None:
        self.stopped.set(True)
        for unit in self.units:
            if unit is not self:
                unit.stop()

    # -- observability -------------------------------------------------------

    def print_stats(self) -> str:
        """Per-unit wall-time table (the reference printed this at stop)."""
        total = sum(u.run_time for u in self.units) or 1e-12
        rows = sorted(self.units, key=lambda u: -u.run_time)
        lines = [f"{'unit':<32}{'runs':>8}{'time_s':>12}{'%':>8}"]
        for u in rows:
            if u.run_count == 0:
                continue
            lines.append(f"{u.name:<32}{u.run_count:>8}{u.run_time:>12.4f}"
                         f"{100.0 * u.run_time / total:>8.1f}")
        fused = getattr(self, "fused_stats", None)
        if fused and fused.get("wall_s"):
            line = (f"fused: {fused['train_steps']} train + "
                    f"{fused['eval_steps']} eval steps in "
                    f"{fused['wall_s']:.3f}s  "
                    f"({fused['steps_per_sec']} steps/s, "
                    f"{fused['img_per_sec']} img/s, "
                    f"last {fused['last_step_ms']} ms)")
            if fused.get("warm_steps"):
                line += (f"; warm (excl. compiles): "
                         f"{fused['warm_img_per_sec']} img/s over "
                         f"{fused['warm_steps']} steps")
            lines.append(line)
        table = "\n".join(lines)
        self.info("unit timing:\n%s", table)
        return table

    def generate_graph(self) -> str:
        """Graphviz dot text of the control graph (reference:
        ``--workflow-graph``)."""
        lines = ["digraph workflow {", "  rankdir=TB;"]
        for unit in self.units:
            lines.append(f'  "{unit.name}" [shape=box];')
        for unit in self.units:
            for target in unit.links_to:
                lines.append(f'  "{unit.name}" -> "{target.name}";')
        lines.append("}")
        return "\n".join(lines)
