"""Evaluators: loss + metrics + the err_output that seeds the GD chain
(rebuild of ``znicz/evaluator.py``, SURVEY.md §2.2 "Evaluators").

``EvaluatorSoftmax`` — consumes the softmax output of ``All2AllSoftmax``:
  - ``err_output = (probs - onehot(labels)) / n_valid`` (the CE cotangent at
    the logits, batch-mean scaled — the reference's fused softmax+CE backward)
  - ``n_err`` (misclassified count), ``confusion_matrix``, ``loss`` (mean CE),
    ``max_err_output_sum`` (reference's divergence monitor).

``EvaluatorMSE`` — for regression/autoencoders:
  - ``err_output = (output - target) / n_valid`` — exactly the gradient of
    ``loss = 0.5 · Σ_samples ||y-t||² / n_valid``, which is what ``loss``
    reports (so the loss curve is the integral of the served gradient);
  - ``mse`` = per-sample squared error ``||y-t||²`` (sum over features).

Padded tail minibatches: the loader serves fixed-size minibatches with
``minibatch_size <= max_minibatch_size``; rows past minibatch_size are masked
out of both err_output and all metrics (reference semantics, SURVEY.md §7
hard part 1).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from znicz_tpu.core.units import Unit
from znicz_tpu.memory import Array


class EvaluatorBase(Unit):
    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.output: Optional[Array] = None        # linked from last forward
        self.batch_size: int = 0                   # linked: minibatch_size
        self.err_output = Array()
        self.loss = 0.0                            # mean loss, this minibatch
        self._compiled = None

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self.err_output.initialize(device)


class EvaluatorSoftmax(EvaluatorBase):
    #: heads wider than this default to confusion=off — a (C, C) int32
    #: matrix shipped per minibatch/epoch is pure reporting, and at
    #: ImageNet scale (1000x1000 = 4MB) it dominated training wall time
    #: on slow host links; set ``compute_confusion=True`` to force it
    CONFUSION_AUTO_LIMIT = 128

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.labels: Optional[Array] = None        # linked: minibatch_labels
        self.n_err = 0
        self.n_classes = kwargs.get("n_classes", 0)
        self.compute_confusion = kwargs.get("compute_confusion", None)
        #: whether the user pinned compute_confusion (vs the auto default).
        #: The fused path accumulates confusion on device and ships it once
        #: per epoch, so it ignores the unit path's width-based auto-off
        #: unless the user explicitly disabled collection.
        self.confusion_explicit = self.compute_confusion is not None
        self.confusion_matrix = Array()            # (pred, true) counts
        self.max_err_output_sum = 0.0

    @staticmethod
    def compute(probs, labels, batch_size, n_classes, with_confusion=True):
        """Pure metrics+cotangent computation (jit-compiled once).  With
        ``with_confusion`` off the confusion slot is a (1, 1) zero —
        DecisionGD treats size<=1 as "not collected"."""
        import jax.numpy as jnp

        n = probs.shape[0]
        valid = (jnp.arange(n) < batch_size)
        onehot = jnp.eye(n_classes, dtype=probs.dtype)[labels]
        err = (probs - onehot) * valid[:, None] / jnp.maximum(batch_size, 1)
        pred = jnp.argmax(probs, axis=-1)
        wrong = (pred != labels) & valid
        n_err = jnp.sum(wrong)
        eps = jnp.finfo(probs.dtype).tiny
        ce = -jnp.log(jnp.maximum(
            jnp.take_along_axis(probs, labels[:, None], axis=-1)[:, 0], eps))
        loss = jnp.sum(jnp.where(valid, ce, 0.0)) / jnp.maximum(batch_size, 1)
        if with_confusion:
            conf = jnp.zeros((n_classes, n_classes), jnp.int32).at[
                pred, labels].add(valid.astype(jnp.int32))
        else:
            conf = jnp.zeros((1, 1), jnp.int32)
        max_err_sum = jnp.max(jnp.sum(jnp.abs(err), axis=-1))
        return err, n_err, loss, conf, max_err_sum

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        if not self.n_classes:
            self.n_classes = int(self.output.shape[-1])
        if self.compute_confusion is None:
            self.compute_confusion = \
                self.n_classes <= self.CONFUSION_AUTO_LIMIT
        shape = ((self.n_classes, self.n_classes)
                 if self.compute_confusion else (1, 1))
        self.confusion_matrix.mem = np.zeros(shape, np.int32)
        self.confusion_matrix.initialize(device)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self.compute, static_argnums=(3, 4))
        err, n_err, loss, conf, mes = self._compiled(
            self.output.devmem, self.labels.devmem,
            np.int32(self.batch_size), self.n_classes,
            bool(self.compute_confusion))
        self.err_output.devmem = err
        self.confusion_matrix.devmem = conf
        self.n_err = int(n_err)
        self.loss = float(loss)
        self.max_err_output_sum = float(mes)


class EvaluatorSeqSoftmax(EvaluatorSoftmax):
    """Per-token softmax-CE over a sequence head (ISSUE 15): probs are
    (batch, seq, vocab), labels (batch, seq) — every token of every
    valid row is one classification.  Metrics flatten tokens into the
    batch axis and reuse the softmax math verbatim (n_err counts WRONG
    TOKENS, loss is the mean CE per token over valid rows), so the
    Decision/printing machinery consumes them unchanged.  The fused
    trainer mirrors this flatten in its own loss head
    (``FusedTrainer.loss_and_metrics``) — the two must not drift.
    Confusion defaults off (a vocab x vocab int32 matrix per minibatch
    is pure reporting weight)."""

    def __init__(self, workflow=None, name=None, **kwargs):
        kwargs.setdefault("compute_confusion", False)
        super().__init__(workflow=workflow, name=name, **kwargs)

    @staticmethod
    def compute_seq(probs, labels, batch_size, n_classes, with_confusion):
        """Flatten-and-delegate: valid SAMPLES are a prefix, so their
        tokens are a prefix of the flattened rows too — the base
        per-class math applies verbatim with ``batch_size * t`` as the
        valid-row count AND the mean denominator (per-token loss)."""
        import jax.numpy as jnp

        n, t = probs.shape[0], probs.shape[1]
        err, n_err, loss, conf, max_err_sum = EvaluatorSoftmax.compute(
            probs.reshape(n * t, probs.shape[-1]),
            labels.reshape(n * t).astype(jnp.int32),
            batch_size * t, n_classes, with_confusion)
        return err.reshape(probs.shape), n_err, loss, conf, max_err_sum

    def initialize(self, device=None, **kwargs):
        if not self.n_classes:
            self.n_classes = int(self.output.shape[-1])
        super().initialize(device=device, **kwargs)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self.compute_seq,
                                     static_argnums=(3, 4))
        err, n_err, loss, conf, mes = self._compiled(
            self.output.devmem, self.labels.devmem,
            np.int32(self.batch_size), self.n_classes,
            bool(self.compute_confusion))
        self.err_output.devmem = err
        self.confusion_matrix.devmem = conf
        self.n_err = int(n_err)
        self.loss = float(loss)
        self.max_err_output_sum = float(mes)


class EvaluatorMSE(EvaluatorBase):
    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.target: Optional[Array] = None        # linked: minibatch_targets
        self.mse = Array()                         # per-sample ||y-t||^2
        #: optional classification-through-regression mode (the reference's
        #: EvaluatorMSE + class_targets): link ``labels`` AND set
        #: ``class_targets`` (n_classes, *sample_shape); n_err counts samples
        #: whose nearest class target (L2) disagrees with the label.
        self.labels = None
        self.class_targets = Array()
        self.n_err = 0
        self._compiled_nerr = None

    @staticmethod
    def compute(output, target, batch_size):
        import jax.numpy as jnp

        n = output.shape[0]
        y = output.reshape(n, -1)
        t = target.reshape(n, -1)
        valid = (jnp.arange(n) < batch_size)
        diff = (y - t) * valid[:, None]
        err = diff / jnp.maximum(batch_size, 1)
        se = jnp.sum(jnp.square(diff), axis=-1)    # per-sample ||y-t||^2
        loss = 0.5 * jnp.sum(se) / jnp.maximum(batch_size, 1)
        return err.reshape(output.shape), se, loss

    @staticmethod
    def compute_n_err(output, class_targets, labels, batch_size):
        import jax.numpy as jnp

        n = output.shape[0]
        y = output.reshape(n, 1, -1)
        ct = class_targets.reshape(1, class_targets.shape[0], -1)
        pred = jnp.argmin(jnp.sum(jnp.square(y - ct), axis=-1), axis=-1)
        valid = (jnp.arange(n) < batch_size)
        return jnp.sum((pred != labels) & valid)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self.compute)
        err, mse, loss = self._compiled(
            self.output.devmem, self.target.devmem, np.int32(self.batch_size))
        self.err_output.devmem = err
        self.mse.devmem = mse
        self.loss = float(loss)
        if self.labels is not None and self.class_targets:
            if self._compiled_nerr is None:
                import jax
                self._compiled_nerr = jax.jit(self.compute_n_err)
            self.n_err = int(self._compiled_nerr(
                self.output.devmem, self.class_targets.devmem,
                self.labels.devmem, np.int32(self.batch_size)))
