"""Standalone activation units fwd+bwd (rebuild of ``znicz/activation.py``).

The reference shipped ``ActivationForward``/``ActivationBackward`` pairs for
Tanh, Sigmoid, RELU (softplus), StrictRELU, Log, TanhLog, SinCos and Mul as
separate graph units (used when an activation isn't fused into an
All2All/Conv).  Backwards are vjps of the forward fn — no hand-derived
derivative constants to drift (SURVEY.md §7 hard part 1).
"""

from __future__ import annotations

from znicz_tpu.nn_units import ForwardBase, GradientDescentBase
from znicz_tpu.ops import activations


class ActivationForward(ForwardBase):
    has_weights = False
    ACTIVATION = staticmethod(activations.identity)

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, x):
        return type(self).ACTIVATION(x)

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)


class ActivationBackward(GradientDescentBase):
    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)


def is_strict_relu_unit(unit) -> bool:
    """True for a parameter-free standalone StrictRELU activation unit —
    the recognizer the fused conv-block matcher uses to absorb a
    Conv -> StrictRELU pair into the single-pass kernel
    (znicz_tpu/pallas_fused_block.py)."""
    return (isinstance(unit, ActivationForward)
            and type(unit).ACTIVATION is activations.strict_relu)


def _make(name, fn):
    fwd = type(f"Forward{name}", (ActivationForward,),
               {"ACTIVATION": staticmethod(fn)})
    bwd = type(f"Backward{name}", (ActivationBackward,), {})
    return fwd, bwd


ForwardTanh, BackwardTanh = _make("Tanh", activations.tanh_scaled)
ForwardSigmoid, BackwardSigmoid = _make("Sigmoid", activations.sigmoid)
ForwardRELU, BackwardRELU = _make("RELU", activations.relu_log)
ForwardStrictRELU, BackwardStrictRELU = _make(
    "StrictRELU", activations.strict_relu)
ForwardLog, BackwardLog = _make("Log", activations.log_act)
ForwardSinCos, BackwardSinCos = _make("SinCos", activations.sincos)


def _tanhlog(x):
    """Reference's TanhLog: scaled tanh for |x| < 10, log-tail outside."""
    import jax.numpy as jnp

    t = activations.tanh_scaled(x)
    tail = jnp.sign(x) * (activations.TANH_A +
                          jnp.log(jnp.maximum(jnp.abs(x) - 9.0, 1.0)))
    return jnp.where(jnp.abs(x) < 10.0, t, tail)


ForwardTanhLog, BackwardTanhLog = _make("TanhLog", _tanhlog)


class ForwardMul(ForwardBase):
    """Elementwise product with a second linked input ``x2`` (the
    reference's Mul gate)."""

    has_weights = False

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, x):
        raise NotImplementedError("ForwardMul consumes two inputs; use run()")

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(lambda a, b: a * b)
        self.output.devmem = self._compiled(self.input.devmem,
                                            self.x2.devmem)
