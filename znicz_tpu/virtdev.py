"""Virtual-device provisioning — the ONE home of the axon-plugin gotchas.

Forces an n-virtual-device CPU platform so sharding/collective code runs on
hosts without n real chips (SURVEY.md §4 "multi-device tests on CPU via
XLA_FLAGS=--xla_force_host_platform_device_count").  Shared by
``tests/conftest.py`` and ``__graft_entry__.dryrun_multichip`` so the fragile
recipe (env forcing, axon deregistration, jax.config re-pin) is maintained in
exactly one place.

Must be called BEFORE the first jax *backend initialization*; calling it
after ``import jax`` is fine (XLA parses the flags at first client creation,
verified empirically on this stack).
"""

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def provision_cpu_devices(n: int, verify: bool = True) -> None:
    """Pin this process to a CPU platform exposing >= n virtual devices.

    Safe to call repeatedly; an existing forced count is only ever raised,
    never lowered.  The axon (remote-TPU) PJRT plugin registers itself from
    sitecustomize at interpreter start and pins jax_platforms=axon via
    jax.config (which overrides the env var); its tunnel is single-claim, so
    we deregister the factory before jax can claim it for a CPU-only run.

    ``verify=False`` skips the device-count check, leaving backends
    UNinitialized — required before ``jax.distributed.initialize`` (which
    must precede the first backend creation).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n}").strip()
    elif int(m.group(1)) < n:
        flags = re.sub(_COUNT_FLAG + r"=\d+", f"{_COUNT_FLAG}={n}", flags)
    os.environ["XLA_FLAGS"] = flags
    try:
        import jax
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    if not verify:
        return
    # XLA parses the flags at FIRST client creation only: if backends were
    # already initialized with fewer devices, the env rewrite above silently
    # did nothing — fail here with the real cause instead of a confusing
    # device-count error far downstream.
    import jax

    have = len(jax.devices())
    if have < n:
        raise RuntimeError(
            f"provision_cpu_devices({n}): jax already initialized with "
            f"{have} device(s); virtual CPU devices must be provisioned "
            "before the first backend creation (re-exec in a fresh process)")
