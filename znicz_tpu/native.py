"""ctypes bindings for the C++ host runtime (``native/znicz_native.cpp``).

The reference's native layer was hand-written device kernels plus libzmq;
here the device side belongs to XLA and the HOST data path is the native
C++ piece: xorshift128+ PRNG (the reference's rand kernel family),
Fisher-Yates shuffling, minibatch row gather, u8->f32 decode.

The shared library is built on first use with g++ (cached under
``root.common.dirs.cache``); every function has a numpy fallback so the
framework works without a toolchain.  Consumers: the Loader's opt-in
``native_shuffle`` path (``root.common.engine.native_shuffle`` or the
per-loader kwarg), the image loader's u8->f32 decode, and host-side
minibatch assembly via ``gather_f32``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _source_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "znicz_native.cpp")


def _cache_dir() -> str:
    from znicz_tpu.core.config import root

    d = root.common.dirs.get("cache", ".znicz_cache")
    os.makedirs(d, exist_ok=True)
    return d


def build() -> Optional[str]:
    """Compile the shared library; returns its path or None."""
    src = _source_path()
    if not os.path.exists(src):
        return None
    out = os.path.join(_cache_dir(), "libznicz_native.so")
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", src, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        path = build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        u64p = ctypes.POINTER(ctypes.c_uint64)
        f32p = ctypes.POINTER(ctypes.c_float)
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.znicz_seed.argtypes = [u64p, ctypes.c_uint64]
        lib.znicz_fill_uniform.argtypes = [u64p, f32p, ctypes.c_size_t,
                                           ctypes.c_float, ctypes.c_float]
        lib.znicz_fill_normal.argtypes = [u64p, f32p, ctypes.c_size_t,
                                          ctypes.c_float]
        lib.znicz_shuffle_i32.argtypes = [u64p, i32p, ctypes.c_size_t]
        lib.znicz_gather_f32.argtypes = [f32p, i32p, f32p, ctypes.c_size_t,
                                         ctypes.c_size_t]
        lib.znicz_u8_to_f32.argtypes = [u8p, f32p, ctypes.c_size_t,
                                        ctypes.c_float, ctypes.c_float]
        lib.znicz_native_abi.restype = ctypes.c_int
        if lib.znicz_native_abi() != 1:
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class XorShift128P:
    """The reference's device RNG family, as a host stream.  Deterministic
    across the native and numpy implementations is NOT guaranteed — the
    native path is bit-exact xorshift128+; the fallback delegates to
    numpy's PCG (both seeded, both reproducible within their path)."""

    def __init__(self, seed: int):
        self._native = available()
        if self._native:
            self.state = np.zeros(2, np.uint64)
            _lib.znicz_seed(self.state.ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint64)), ctypes.c_uint64(seed))
        else:
            self._rng = np.random.default_rng(seed)

    def _sp(self):
        return self.state.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))

    def fill_uniform(self, out: np.ndarray, low: float, high: float) -> None:
        assert out.dtype == np.float32 and out.flags.c_contiguous
        if self._native:
            _lib.znicz_fill_uniform(
                self._sp(), out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                out.size, low, high)
        else:
            out[...] = self._rng.uniform(low, high, out.shape)

    def fill_normal(self, out: np.ndarray, stddev: float) -> None:
        assert out.dtype == np.float32 and out.flags.c_contiguous
        if self._native:
            _lib.znicz_fill_normal(
                self._sp(), out.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_float)),
                out.size, stddev)
        else:
            out[...] = self._rng.normal(0, stddev, out.shape)

    def shuffle(self, arr: np.ndarray) -> None:
        assert arr.dtype == np.int32 and arr.flags.c_contiguous
        if self._native:
            _lib.znicz_shuffle_i32(
                self._sp(), arr.ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int32)), arr.size)
        else:
            self._rng.shuffle(arr)


def gather_f32(src: np.ndarray, idx: np.ndarray,
               dst: Optional[np.ndarray] = None) -> np.ndarray:
    """Row gather src[idx] -> dst (native memcpy loop or numpy take).
    Indices are validated up front — the C path is unchecked memcpy."""
    rows = np.ascontiguousarray(src.reshape(len(src), -1), np.float32)
    idx = np.ascontiguousarray(idx, np.int32)
    if idx.size and (idx.min() < 0 or idx.max() >= len(rows)):
        raise IndexError(f"gather index out of range [0, {len(rows)})")
    out_shape = (len(idx),) + src.shape[1:]
    if dst is None:
        dst = np.empty(out_shape, np.float32)
    elif not (dst.flags.c_contiguous and dst.dtype == np.float32):
        raise ValueError("dst must be a C-contiguous float32 buffer "
                         "(reshape of a strided view would write a copy)")
    if available():
        flat = dst.reshape(len(idx), -1)
        _lib.znicz_gather_f32(
            rows.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(idx), rows.shape[1])
    else:
        np.take(rows, idx, axis=0, out=dst.reshape(len(idx), -1))
    return dst


def u8_to_f32(src: np.ndarray, scale: float = 1.0 / 255.0,
              shift: float = 0.0) -> np.ndarray:
    src = np.ascontiguousarray(src, np.uint8)
    dst = np.empty(src.shape, np.float32)
    if available():
        _lib.znicz_u8_to_f32(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            src.size, scale, shift)
    else:
        dst[...] = src.astype(np.float32) * scale + shift
    return dst
