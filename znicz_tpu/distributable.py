"""Distributable protocol (rebuild of ``veles/distributable.py``).

The reference's master/slave distribution required every unit to implement
a 4-method data protocol:

    generate_data_for_slave / apply_data_from_master   (master -> slave)
    generate_data_for_master / apply_data_from_slave   (slave -> master)

On TPU that transport no longer exists — gradient aggregation is a psum
inside the fused jitted step (SURVEY.md §2.4) — but the PROTOCOL survives
because it is also the unit-state serialization surface (snapshots, and any
future DCN-side elastic mode).  ``Distributable`` gives every unit a
default implementation over its param Arrays; ``GradientDescentBase`` and
``ForwardBase`` get exactly the semantics the reference's NN units had
(weights travel master->slave, gradients/updated-weights travel
slave->master)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class Distributable:
    """Mixin; default: stateless unit (empty payloads)."""

    negotiates_on_connect = False

    def _param_arrays(self) -> Dict[str, "np.ndarray"]:
        # independent C-contiguous COPIES, on purpose: wire protocol v3
        # (parallel/wire.py) ships each as one raw zero-copy buffer frame
        # that may still be queued in ZMQ (send copy=False) while the
        # live param Arrays are already being mutated by the next
        # apply_deltas — aliasing the live memory here would tear the
        # payload on the wire
        params = getattr(self, "params", None)
        if callable(params):
            return {k: np.array(a.map_read())
                    for k, a in self.params().items()}
        return {}

    # -- master side ----------------------------------------------------------

    def generate_data_for_slave(self) -> Optional[dict]:
        """Master -> slave payload: current parameters."""
        data = self._param_arrays()
        return data or None

    def apply_data_from_slave(self, data: Optional[dict]) -> None:
        """Master absorbs a slave's update.  The reference's async
        aggregation applied whole updated tensors; keep that semantic."""
        if not data:
            return
        params = getattr(self, "params", None)
        if callable(params):
            for k, arr in self.params().items():
                if k in data:
                    arr.mem = np.asarray(data[k]).copy()

    # -- slave side -----------------------------------------------------------

    def apply_data_from_master(self, data: Optional[dict]) -> None:
        if not data:
            return
        params = getattr(self, "params", None)
        if callable(params):
            for k, arr in self.params().items():
                if k in data:
                    arr.mem = np.asarray(data[k]).copy()

    def generate_data_for_master(self) -> Optional[dict]:
        """Slave -> master payload: updated parameters (the reference's GD
        units shipped gradients or weights depending on mode; the rebuild
        ships weights — the psum path never serializes at all)."""
        data = self._param_arrays()
        return data or None
