"""Trace spans (ISSUE 5 tentpole, part 2): a bounded in-memory ring of
begin/end events with explicit timestamps, exportable as Chrome
trace-event JSON (load ``/trace.json`` in Perfetto or
``chrome://tracing``).

Span sites: unit ``run()`` (core/workflow.py), fused-trainer dispatch /
flush / tail / eval (parallel/fused.py), wire codec encode/decode
(parallel/wire.py), master REP handling (server.py), serving batch
assemble / compute / reply (serving/frontend.py), and snapshot writes
(snapshotter.py).  Cross-process correlation rides the ``trace_id`` /
``job_id`` keys the wire-v3 metadata frames carry end-to-end (optional
dict keys — old peers decode fine): two processes' trace files can be
joined on ``args.trace_id``.

Cost discipline: recording one span is two ``perf_counter()`` reads and
one deque append (the deque's ``maxlen`` gives the bounded ring for
free — appends past capacity evict the oldest event without locking).
When the ring is disabled, ``span()`` returns a shared no-op context
manager, so instrumented hot paths pay one attribute check.  The
``bench.py --telemetry`` gate holds the whole layer under 2% on the
training hot loop.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional

#: default ring capacity (events); override per-TraceRing, or via
#: root.common.telemetry.trace_capacity for the process-wide ring —
#: which is created lazily on first use, so set the override any time
#: BEFORE the first telemetry consumer (Codec/Server/trainer/...) is
#: constructed (importing telemetry alone does not latch it)
DEFAULT_CAPACITY = 16384


class _NullSpan:
    """Shared do-nothing context manager for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_ring", "cat", "name", "args", "_t0")

    def __init__(self, ring: "TraceRing", cat: str, name: str, args):
        self._ring = ring
        self.cat = cat
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._ring.add(self.cat, self.name, self._t0,
                       time.perf_counter() - self._t0, self.args)
        return False


class TraceRing:
    """Bounded ring of complete ("X") trace events.

    Events are stored as plain tuples ``(cat, name, ts_us, dur_us, tid,
    args)``; the Chrome trace-event dicts are built only at export.
    ``deque.append`` is atomic under the GIL, so the EVENT path takes no
    lock; ``events()`` snapshots via ``list(deque)`` for the same
    reason — export never blocks recording.  The lifetime ``recorded``
    counter is the one piece that needs read-modify-write, so it rides
    its own micro-lock (spans arrive concurrently from the training,
    router, compute and snapshot-writer threads; a bare ``+=`` would
    silently drop increments).
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True):
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity)
        self.recorded = 0               # lifetime count (ring may evict)
        self._count_lock = threading.Lock()
        self._sinks: List = []          # fleet span exporters (ISSUE 20)

    def add_sink(self, sink) -> None:
        """Register a callable fed every recorded event tuple (the fleet
        ``SpanExporter``).  Sinks must be non-blocking and non-raising;
        the empty-list check keeps the no-sink hot path at one ``if``."""
        self._sinks.append(sink)

    # -- recording -------------------------------------------------------------

    def span(self, cat: str, name: str, **args):
        """Context manager recording one complete event around its body;
        a no-op singleton while disabled."""
        if not self.enabled:
            return NULL_SPAN
        return _Span(self, cat, name, args or None)

    def add(self, cat: str, name: str, t0_s: float, dur_s: float,
            args: Optional[Dict] = None) -> None:
        """Record a complete event from an ALREADY-MEASURED interval
        (perf_counter seconds) — the workflow unit loop reuses its own
        timing instead of paying a second pair of clock reads."""
        if not self.enabled:
            return
        evt = (cat, name, int(t0_s * 1e6), max(int(dur_s * 1e6), 0),
               threading.get_ident(), args)
        self._events.append(evt)
        with self._count_lock:
            self.recorded += 1
        if self._sinks:
            for sink in self._sinks:
                sink(evt)

    def instant(self, cat: str, name: str, **args) -> None:
        """Zero-duration marker event."""
        self.add(cat, name, time.perf_counter(), 0.0, args or None)

    # -- export ----------------------------------------------------------------

    def events(self) -> List[tuple]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()

    def chrome_trace(self) -> Dict:
        """The ring as a Chrome trace-event JSON object (Perfetto /
        chrome://tracing load it directly).  Snapshot-then-build: the
        caller can serialize and write the result with no ring state
        shared with recorders."""
        pid = os.getpid()
        out = []
        for cat, name, ts, dur, tid, args in self.events():
            ev = {"name": name, "cat": cat, "ph": "X", "ts": ts,
                  "dur": dur, "pid": pid, "tid": tid}
            if args:
                ev["args"] = args
            out.append(ev)
        return {"traceEvents": out, "displayTimeUnit": "ms"}
