"""Unified telemetry subsystem (ISSUE 5): ONE process-wide metrics
registry + trace ring that the master server, slave client, wire codec,
chaos proxy, batcher, serving frontend, model runner, snapshotter,
fused trainer, unit engine and decision loop all register into.

Surfaces:

  - ``/metrics`` on web_status: Prometheus text exposition of every
    registered counter/gauge/histogram (metrics.py);
  - ``/trace.json`` on web_status: the span ring as Chrome trace-event
    JSON, loadable in Perfetto (trace.py);
  - ``--profile-dir`` on the launcher: programmatic
    ``jax.profiler.start_trace``/``stop_trace`` capture with
    ``StepTraceAnnotation`` wrapped around each fused train step
    (:func:`step_annotation`);
  - ``bench.py --telemetry``: the <2% hot-loop overhead gate.

``set_enabled(False)`` turns the OPTIONAL layer off: spans stop
recording and the trainer's step histogram stops observing.  Service
ACCOUNTING counters (bytes, jobs, refusals — state other subsystems
and dashboards depend on) always run; they predate this module and are
not "telemetry overhead".
"""

from __future__ import annotations

import os
import threading

from znicz_tpu.core.config import root

from .events import EventJournal, FleetEventStore  # noqa: F401
from .fleet import (FleetMetricsStore, FleetTraceStore,  # noqa: F401
                    SloTracker, SpanExporter, process_identity,
                    registry_snapshot, render_fleet_prometheus)
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, Scope, registered_property,
                      weak_fn)
from .trace import NULL_SPAN, TraceRing  # noqa: F401

#: declaration table for the ``root.common.telemetry.*`` knobs (the
#: telemetry tree is process-wide, not plane-specific, so its knobs
#: live here rather than in ENGINE_DEFAULTS / serving DEFAULTS; same
#: contract — a key read anywhere below must appear here)
TELEMETRY_DEFAULTS = {
    "enabled": True,            # optional layer (spans + hot histograms)
    "trace_capacity": 16384,    # process span-ring size (events)
    "profile_steps": False,     # jax StepTraceAnnotation on train steps
    # -- fleet observability plane (ISSUE 20) ------------------------------
    "events_capacity": 512,     # process event-journal ring (events)
    "span_export_capacity": 1024,   # exporter buffer (spans, drops-oldest)
    "span_export_all": False,   # export spans without a trace_id too
    "span_export_batch": 128,   # max spans per piggyback carrier
    "fleet_trace_capacity": 8192,   # coordinator stitched-span ring
    "fleet_events_capacity": 2048,  # coordinator merged-journal ring
}

_REGISTRY = MetricsRegistry()
_TRACER = None
_TRACER_LOCK = threading.Lock()
_PROFILE_STEPS = False
_IDENTITY = None
_JOURNAL = None
_EXPORTER = None
_FLEET_TRACE = None
_FLEET_EVENTS = None
_FLEET_METRICS = None
_SLO_TRACKERS = []


def registry() -> MetricsRegistry:
    """The process-wide registry (the ``/metrics`` exposition source)."""
    return _REGISTRY


def tracer() -> TraceRing:
    """The process-wide span ring (the ``/trace.json`` source).

    Created LAZILY on first use, so ``root.common.telemetry
    .trace_capacity`` / ``.enabled`` set any time before the first
    telemetry consumer is constructed (launcher overrides, test/config
    setup) take effect — merely importing a module that imports
    telemetry does not latch the config.  ``set_enabled`` toggles at
    runtime; capacity is fixed once the ring exists."""
    global _TRACER
    if _TRACER is None:
        # double-checked under a lock: components construct from
        # multiple threads (a slave thread's Client racing the main
        # thread's Server) and each caches the ring it gets — two rings
        # would leave one component deaf to set_enabled and its spans
        # missing from /trace.json for the process lifetime
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = TraceRing(
                    capacity=int(root.common.telemetry.get(
                        "trace_capacity", 16384)),
                    enabled=bool(root.common.telemetry.get("enabled",
                                                           True)))
    return _TRACER


def scope(component: str, **labels) -> Scope:
    """``registry().scope(...)`` shorthand — what components call in
    their constructors."""
    return _REGISTRY.scope(component, **labels)


def span(cat: str, name: str, **args):
    """``tracer().span(...)`` shorthand (no-op context when disabled)."""
    return tracer().span(cat, name, **args)


def enabled() -> bool:
    return tracer().enabled


def set_enabled(on: bool) -> None:
    """Toggle the optional layer (spans + hot-loop histograms) at
    runtime — the bench's interleaved on/off overhead protocol."""
    tracer().enabled = bool(on)


def render_prometheus() -> str:
    return _REGISTRY.render_prometheus()


def chrome_trace() -> dict:
    return tracer().chrome_trace()


def set_identity(role: str) -> str:
    """Name this logical process for the fleet plane (``balancer``,
    ``replica-3``, ``master``, ``slave-w1``, ``client``...).  Returns
    the full origin (``role@pid``).  Latches the journal/exporter
    origin if they already exist; call early (component constructors
    do)."""
    global _IDENTITY
    _IDENTITY = process_identity(role)
    if _JOURNAL is not None:
        _JOURNAL.origin = _IDENTITY
    if _EXPORTER is not None:
        _EXPORTER.origin = _IDENTITY
    return _IDENTITY


def identity() -> str:
    """This logical process's fleet origin (defaulted from the pid)."""
    global _IDENTITY
    if _IDENTITY is None:
        _IDENTITY = process_identity("proc")
    return _IDENTITY


def journal() -> EventJournal:
    """The process-wide structured event journal (``/events.json``
    source).  Lazy and config-sized like :func:`tracer`."""
    global _JOURNAL
    if _JOURNAL is None:
        with _TRACER_LOCK:
            if _JOURNAL is None:
                _JOURNAL = EventJournal(
                    capacity=int(root.common.telemetry.get(
                        "events_capacity", 512)),
                    origin=identity())
    return _JOURNAL


def emit(kind: str, plane: str, **fields) -> int:
    """``journal().emit(...)`` shorthand — THE idiom every state
    transition uses (the znicz-lint ``event-journal`` rule greps the
    named decision points for exactly this call)."""
    return journal().emit(kind, plane, **fields)


def exporter() -> SpanExporter:
    """The process-wide fleet span exporter, registered as a tracer
    sink on first use.  Drained by the piggyback carriers (heartbeats,
    update messages, reply summaries)."""
    global _EXPORTER
    if _EXPORTER is None:
        ring = tracer()   # materialize OUTSIDE the lock (non-reentrant)
        with _TRACER_LOCK:
            if _EXPORTER is None:
                exp = SpanExporter(
                    origin=identity(),
                    capacity=int(root.common.telemetry.get(
                        "span_export_capacity", 1024)),
                    export_all=bool(root.common.telemetry.get(
                        "span_export_all", False)))
                ring.add_sink(exp)
                _EXPORTER = exp
    return _EXPORTER


def span_export_batch() -> int:
    return int(root.common.telemetry.get("span_export_batch", 128))


def fleet_trace() -> FleetTraceStore:
    """Coordinator-side stitched-trace store (``/trace.json?fleet=1``)."""
    global _FLEET_TRACE
    if _FLEET_TRACE is None:
        with _TRACER_LOCK:
            if _FLEET_TRACE is None:
                _FLEET_TRACE = FleetTraceStore(
                    capacity=int(root.common.telemetry.get(
                        "fleet_trace_capacity", 8192)))
    return _FLEET_TRACE


def fleet_events() -> FleetEventStore:
    """Coordinator-side merged event journal (``/events.json?fleet=1``)."""
    global _FLEET_EVENTS
    if _FLEET_EVENTS is None:
        with _TRACER_LOCK:
            if _FLEET_EVENTS is None:
                _FLEET_EVENTS = FleetEventStore(
                    capacity=int(root.common.telemetry.get(
                        "fleet_events_capacity", 2048)))
    return _FLEET_EVENTS


def fleet_metrics() -> FleetMetricsStore:
    """Coordinator-side member registry snapshots (``/metrics``
    superset + ``/fleet.json`` rollup)."""
    global _FLEET_METRICS
    if _FLEET_METRICS is None:
        with _TRACER_LOCK:
            if _FLEET_METRICS is None:
                _FLEET_METRICS = FleetMetricsStore()
    return _FLEET_METRICS


def drain_own_spans() -> int:
    """Coordinator self-ingest: spans recorded in THIS process flow
    into the fleet trace store under per-span origins derived from
    their category (``client@pid``, ``balancer@pid``...) — a bench or
    launcher process hosting several logical roles (client + balancer
    share one interpreter) still renders them as DISTINCT fleet
    participants in the stitched timeline."""
    spans = exporter().drain(span_export_batch())
    if not spans:
        return 0
    store = fleet_trace()
    pid = os.getpid()
    n = 0
    for s in spans:
        n += store.ingest(f"{s.get('cat', 'proc')}@{pid}", [s])
    return n


def drain_own_events() -> int:
    """Coordinator self-ingest of the local journal into the merged
    fleet journal (the store's per-origin high-water dedups repeats)."""
    store = fleet_events()
    me = identity()
    return store.ingest(me, journal().since(store.cursor(me)))


def register_slo(tracker: SloTracker) -> SloTracker:
    """Expose a plane's SLO tracker on ``/slo.json`` / the web panel
    (latest tracker per plane wins — rebuilt components replace their
    predecessor like registry children do)."""
    global _SLO_TRACKERS
    _SLO_TRACKERS = [t for t in _SLO_TRACKERS if t.plane != tracker.plane]
    _SLO_TRACKERS.append(tracker)
    return tracker


def slo_trackers() -> list:
    return list(_SLO_TRACKERS)


def slo_snapshot() -> dict:
    """All registered planes' SLO state, plus the fleet-advisory
    rollup ``/readyz`` reports (never gates on)."""
    planes = {t.plane: t.snapshot() for t in _SLO_TRACKERS}
    states = [p["state"] for p in planes.values()]
    overall = ("burning" if "burning" in states
               else "warn" if "warn" in states
               else "ok" if states else "idle")
    return {"state": overall, "planes": planes}


def set_profile_steps(on: bool) -> None:
    """Arm :func:`step_annotation` (the launcher's ``--profile-dir``
    does this so fused train steps land as named steps in the jax
    profiler timeline)."""
    global _PROFILE_STEPS
    _PROFILE_STEPS = bool(on)


def profile_steps() -> bool:
    return _PROFILE_STEPS or bool(
        root.common.telemetry.get("profile_steps", False))


def step_annotation(step: int, name: str = "train_step"):
    """``jax.profiler.StepTraceAnnotation`` around one train step when
    step-profiling is armed; a shared no-op context otherwise (jax is
    not even imported on the cold path)."""
    if not profile_steps():
        return NULL_SPAN
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=int(step))
