"""Unified telemetry subsystem (ISSUE 5): ONE process-wide metrics
registry + trace ring that the master server, slave client, wire codec,
chaos proxy, batcher, serving frontend, model runner, snapshotter,
fused trainer, unit engine and decision loop all register into.

Surfaces:

  - ``/metrics`` on web_status: Prometheus text exposition of every
    registered counter/gauge/histogram (metrics.py);
  - ``/trace.json`` on web_status: the span ring as Chrome trace-event
    JSON, loadable in Perfetto (trace.py);
  - ``--profile-dir`` on the launcher: programmatic
    ``jax.profiler.start_trace``/``stop_trace`` capture with
    ``StepTraceAnnotation`` wrapped around each fused train step
    (:func:`step_annotation`);
  - ``bench.py --telemetry``: the <2% hot-loop overhead gate.

``set_enabled(False)`` turns the OPTIONAL layer off: spans stop
recording and the trainer's step histogram stops observing.  Service
ACCOUNTING counters (bytes, jobs, refusals — state other subsystems
and dashboards depend on) always run; they predate this module and are
not "telemetry overhead".
"""

from __future__ import annotations

import threading

from znicz_tpu.core.config import root

from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      MetricsRegistry, Scope, registered_property,
                      weak_fn)
from .trace import NULL_SPAN, TraceRing  # noqa: F401

_REGISTRY = MetricsRegistry()
_TRACER = None
_TRACER_LOCK = threading.Lock()
_PROFILE_STEPS = False


def registry() -> MetricsRegistry:
    """The process-wide registry (the ``/metrics`` exposition source)."""
    return _REGISTRY


def tracer() -> TraceRing:
    """The process-wide span ring (the ``/trace.json`` source).

    Created LAZILY on first use, so ``root.common.telemetry
    .trace_capacity`` / ``.enabled`` set any time before the first
    telemetry consumer is constructed (launcher overrides, test/config
    setup) take effect — merely importing a module that imports
    telemetry does not latch the config.  ``set_enabled`` toggles at
    runtime; capacity is fixed once the ring exists."""
    global _TRACER
    if _TRACER is None:
        # double-checked under a lock: components construct from
        # multiple threads (a slave thread's Client racing the main
        # thread's Server) and each caches the ring it gets — two rings
        # would leave one component deaf to set_enabled and its spans
        # missing from /trace.json for the process lifetime
        with _TRACER_LOCK:
            if _TRACER is None:
                _TRACER = TraceRing(
                    capacity=int(root.common.telemetry.get(
                        "trace_capacity", 16384)),
                    enabled=bool(root.common.telemetry.get("enabled",
                                                           True)))
    return _TRACER


def scope(component: str, **labels) -> Scope:
    """``registry().scope(...)`` shorthand — what components call in
    their constructors."""
    return _REGISTRY.scope(component, **labels)


def span(cat: str, name: str, **args):
    """``tracer().span(...)`` shorthand (no-op context when disabled)."""
    return tracer().span(cat, name, **args)


def enabled() -> bool:
    return tracer().enabled


def set_enabled(on: bool) -> None:
    """Toggle the optional layer (spans + hot-loop histograms) at
    runtime — the bench's interleaved on/off overhead protocol."""
    tracer().enabled = bool(on)


def render_prometheus() -> str:
    return _REGISTRY.render_prometheus()


def chrome_trace() -> dict:
    return tracer().chrome_trace()


def set_profile_steps(on: bool) -> None:
    """Arm :func:`step_annotation` (the launcher's ``--profile-dir``
    does this so fused train steps land as named steps in the jax
    profiler timeline)."""
    global _PROFILE_STEPS
    _PROFILE_STEPS = bool(on)


def profile_steps() -> bool:
    return _PROFILE_STEPS or bool(
        root.common.telemetry.get("profile_steps", False))


def step_annotation(step: int, name: str = "train_step"):
    """``jax.profiler.StepTraceAnnotation`` around one train step when
    step-profiling is armed; a shared no-op context otherwise (jax is
    not even imported on the cold path)."""
    if not profile_steps():
        return NULL_SPAN
    import jax

    return jax.profiler.StepTraceAnnotation(name, step_num=int(step))
