"""Process-wide metrics registry (ISSUE 5 tentpole, part 1).

Before this module, every service kept its own ad-hoc counter attributes
(``Server.bad_frames``, ``wire.Codec`` byte accounting, the batcher's
shed/occupancy numbers, ...), readable only through the bespoke
``web_status`` panels.  The registry gives them ONE home with a uniform
export surface (Prometheus text exposition on ``/metrics``, web_status),
while the owning objects keep their historical attribute names as thin
properties over registry metrics — resume snapshots and the status
panels stay byte-compatible.

Three metric kinds:

  - :class:`Counter` — monotonically increasing (``inc``); also
    **settable**, because the master's crash-resume restore writes
    counter values back (``Server.restore_resume``);
  - :class:`Gauge` — a set value OR a zero-argument callable sampled at
    collect time (live values like queue depth, jit-cache size, the
    decision's epoch number — no write traffic on the hot path at all);
  - :class:`Histogram` — a fixed-size RING of observations: quantiles
    are computed over the most recent ``size`` samples, so a long run's
    p99 reflects current behaviour, not the cold start.  ``count`` and
    ``sum`` remain totals over everything ever observed.

Naming/label conventions (README "Telemetry"): every series is
``znicz_<name>[_total]`` with a ``component`` label naming the owning
subsystem (``master``, ``slave``, ``wire``, ``serving``, ``batcher``,
``model``, ``trainer``, ``decision``, ``snapshotter``, ``chaos``).  A
:class:`Scope` binds that label; metric families are shared across
scopes, children are keyed by their full label set and the LATEST
registered child wins (a re-built component — tests build hundreds —
replaces its predecessor in the export instead of leaking series;
the predecessor's metric objects keep working standalone).

Threading: each metric carries its own small lock (``inc``/``observe``
are a few hundred ns — "lock-cheap"); the registry's structural lock
guards family/child tables only.  ``render_prometheus`` SNAPSHOTS under
those locks and returns a string — callers (the web_status handler)
must write that string to the socket AFTER the call returns, so no
lock is ever held across a socket write (the ISSUE 5 de-flake
contract, regression-tested in tests/test_telemetry.py).
"""

from __future__ import annotations

import math
import threading
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

#: quantiles exported for histograms (Prometheus summary convention)
EXPORT_QUANTILES = (0.5, 0.9, 0.99)


def registered_property(name: str, doc: str = "") -> property:
    """The ONE home for the thin compatibility layer every migrated
    component uses: a read/write property over ``self._m[name]`` (its
    registry metric), so historical counter attribute names —
    ``srv.bad_frames``, ``client.prefetch_hits``, ... — keep working
    for web_status, resume snapshots and tests.  Writable because the
    master's crash-resume restore assigns counters back."""

    def fget(self):
        return self._m[name].value

    def fset(self, value):
        self._m[name].set(value)

    return property(fget, fset,
                    doc=doc or f"registry-backed counter {name!r}")


def weak_fn(obj, read: Callable) -> Callable[[], float]:
    """A collect-time gauge callable that does NOT pin ``obj``: the
    process-wide registry lives forever, so a gauge closing over a
    heavyweight owner (a ModelRunner's jitted executables, a workflow's
    decision) would leak the whole object graph after the owner is
    dropped.  ``read(obj)`` runs while the owner is alive; afterwards
    the gauge renders NaN (the registry's latest-wins replacement
    usually retires the series first anyway)."""
    ref = weakref.ref(obj)

    def fn():
        o = ref()
        return None if o is None else read(o)

    return fn


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _format_value(v) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)       # exact: never round-trip an int through float
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f.is_integer():
        return str(int(f))
    return repr(f)


def _render_labels(labels: Dict[str, str], extra: Dict[str, str] = None
                   ) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter.  ``set`` exists for resume restores only."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    @property
    def value(self):
        return self._value

    def samples(self) -> Iterable[Tuple[Dict[str, str], float]]:
        yield {}, self._value


class Gauge:
    """Set-or-sampled value; ``fn`` (zero-arg callable) wins when given
    and is evaluated at collect time — a broken fn renders NaN instead
    of failing the whole scrape."""

    __slots__ = ("name", "help", "labels", "_value", "_fn", "_lock")

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0.0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v

    def inc(self, n=1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        if self._fn is not None:
            try:
                v = self._fn()
            except Exception:
                return float("nan")
            return float("nan") if v is None else v
        return self._value

    def samples(self) -> Iterable[Tuple[Dict[str, str], float]]:
        yield {}, self.value


class Histogram:
    """Ring-buffer histogram: ``observe`` overwrites the oldest slot once
    the ring is full, so quantiles always describe the most recent
    ``size`` observations (order inside the ring is irrelevant to a
    quantile).  ``count``/``sum`` are lifetime totals.  Exported as a
    Prometheus ``summary`` (quantile children + ``_sum``/``_count``)."""

    __slots__ = ("name", "help", "labels", "_buf", "_size", "_n", "_sum",
                 "_lock")

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None, size: int = 1024):
        if size < 1:
            raise ValueError(f"histogram ring size must be >= 1, got {size}")
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._buf = np.zeros(int(size), np.float64)
        self._size = int(size)
        self._n = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v) -> None:
        v = float(v)
        with self._lock:
            self._buf[self._n % self._size] = v
            self._n += 1
            self._sum += v

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def window(self) -> np.ndarray:
        """Copy of the current ring contents (the last ``min(count,
        size)`` observations, unordered)."""
        with self._lock:
            return self._buf[:min(self._n, self._size)].copy()

    def quantile(self, q: float) -> Optional[float]:
        """Quantile over the ring window; None while empty (a synthetic
        0.0 would read as a real observation)."""
        data = self.window()
        if data.size == 0:
            return None
        return float(np.quantile(data, q))

    def quantiles(self, qs: Iterable[float] = EXPORT_QUANTILES
                  ) -> Dict[float, Optional[float]]:
        data = self.window()
        if data.size == 0:
            return {float(q): None for q in qs}
        vals = np.quantile(data, list(qs))
        return {float(q): float(v) for q, v in zip(qs, vals)}

    def samples(self) -> Iterable[Tuple[Dict[str, str], float]]:
        for q, v in self.quantiles().items():
            if v is not None:
                yield {"quantile": repr(float(q))}, v


class Family:
    """All children of one metric name: one type, one help line, children
    keyed by their full label set (latest registration wins).  HELP is
    FAMILY-level, Prometheus-style: the first registrant's non-empty
    help wins, so components sharing a metric name across ``component``
    labels (master/slave ``jobs_done``) must word their help to fit
    every series (the call sites do)."""

    __slots__ = ("name", "kind", "help", "children")

    def __init__(self, name: str, kind: str, help: str):
        self.name = name
        self.kind = kind
        self.help = help
        self.children: Dict[tuple, object] = {}


class Scope:
    """A label-binding view of a registry: every metric created through a
    scope carries ``component=<name>`` (plus any extra labels given per
    metric).  Creating a scope is cheap; components create one in their
    constructor."""

    __slots__ = ("_registry", "labels")

    def __init__(self, registry: "MetricsRegistry", component: str,
                 **labels):
        self._registry = registry
        self.labels = {"component": str(component), **labels}

    def _full(self, extra: Dict[str, str]) -> Dict[str, str]:
        merged = dict(self.labels)
        merged.update({k: str(v) for k, v in extra.items()})
        return merged

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        m = Counter(name, help, self._full(labels))
        self._registry._register(m)
        return m

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None, **labels) -> Gauge:
        m = Gauge(name, help, self._full(labels), fn=fn)
        self._registry._register(m)
        return m

    def histogram(self, name: str, help: str = "", size: int = 1024,
                  **labels) -> Histogram:
        m = Histogram(name, help, self._full(labels), size=size)
        self._registry._register(m)
        return m


class MetricsRegistry:
    """The family table + the exposition renderer.  One process-wide
    instance lives in ``znicz_tpu.telemetry``; tests build their own."""

    def __init__(self, prefix: str = "znicz"):
        self.prefix = str(prefix)
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def scope(self, component: str, **labels) -> Scope:
        return Scope(self, component, **labels)

    def exported_name(self, metric) -> str:
        name = f"{self.prefix}_{metric.name}" if self.prefix else metric.name
        if metric.kind == "counter" and not name.endswith("_total"):
            name += "_total"
        return name

    def _register(self, metric) -> None:
        name = self.exported_name(metric)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, metric.kind, metric.help)
                self._families[name] = fam
            elif fam.kind != metric.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"cannot re-register as {metric.kind}")
            if not fam.help and metric.help:
                # first NON-EMPTY help wins (a helpless first registrant
                # must not permanently blank the family's # HELP line)
                fam.help = metric.help
            # latest-wins per label set: a rebuilt component replaces its
            # predecessor's child instead of leaking a stale series
            fam.children[_label_key(metric.labels)] = metric

    def collect(self) -> List[Tuple[Family, List[object]]]:
        """Snapshot of (family, children) pairs; taken under the
        structural lock, VALUES are read after it is released."""
        with self._lock:
            return [(fam, list(fam.children.values()))
                    for fam in self._families.values()]

    def render_prometheus(self) -> str:
        """Prometheus text exposition (0.0.4).  Builds the whole body as
        a string — no registry or metric lock is held by the caller
        while it writes the result to a socket."""
        out: List[str] = []
        for fam, children in self.collect():
            if fam.help:
                out.append(f"# HELP {fam.name} {fam.help}")
            # histograms export as the summary type (quantile children)
            kind = "summary" if fam.kind == "histogram" else fam.kind
            out.append(f"# TYPE {fam.name} {kind}")
            for m in children:
                if isinstance(m, Histogram):
                    for extra, v in m.samples():
                        out.append(f"{fam.name}"
                                   f"{_render_labels(m.labels, extra)} "
                                   f"{_format_value(v)}")
                    lbl = _render_labels(m.labels)
                    out.append(f"{fam.name}_sum{lbl} "
                               f"{_format_value(m.sum)}")
                    out.append(f"{fam.name}_count{lbl} "
                               f"{_format_value(m.count)}")
                else:
                    for extra, v in m.samples():
                        out.append(f"{fam.name}"
                                   f"{_render_labels(m.labels, extra)} "
                                   f"{_format_value(v)}")
        return "\n".join(out) + "\n"
