"""Structured event journal: the fleet's causal timeline.

Counters say *how many* failovers happened; they cannot say that the
failover at t=12.4s was caused by the preemption at t=12.1s and led to
the autoscale-up at t=14.0s.  The :class:`EventJournal` is the missing
middle layer: a bounded, wall-clock-timestamped, sequence-numbered ring
of typed events, one per *state transition* (membership, swap wave
phase, quorum flip, autoscale decision, rollback, breaker open,
prefix-cache eviction, page-pressure shed), each carrying the numbers
that drove the decision.

Design constraints, in order:

  - **never blocks, never throws** at the emit site — journal writes
    ride hot paths (heartbeat handlers, scheduler ticks) and a broken
    or contended journal must not take the data plane down with it;
  - **bounded** — a ``deque(maxlen=...)`` drops the *oldest* events
    under pressure; ``seq`` keeps counting so a reader can detect the
    gap (``events[0].seq > cursor`` ⇒ events were lost);
  - **seq is monotone** per process for the journal's lifetime, even
    across ring wraparound — the fleet merge keys on ``(origin, seq)``
    so re-delivered piggyback batches dedup exactly;
  - **cursorable** — ``since(seq)`` returns only events newer than the
    cursor, which is both the ``/events.json?since=`` contract and the
    incremental piggyback export used by heartbeats / update replies.

The process-global journal lives behind ``telemetry.journal()`` /
``telemetry.emit(...)`` (lazy, config-sized like the process tracer).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

#: Known event kinds.  Purely documentary — ``emit`` accepts any kind —
#: but the znicz-lint ``event-journal`` rule anchors on the decision
#: points that must produce one of these, so keep the list in sync.
KINDS = (
    "failover",            # balancer re-dispatched in-flight work off a dead replica
    "replica_lost",        # balancer evicted a member (TTL lapse / preemption)
    "replica_joined",      # new member admitted to the fleet
    "heal",                # balancer respawned a replica to restore min_replicas
    "autoscale_up",        # autoscaler spawned a replica (carries load numbers)
    "autoscale_down",      # autoscaler retired a replica (carries load numbers)
    "swap_begin",          # canary rollover requested
    "swap_phase",          # rollover wave advanced (canary/wave/finalize)
    "swap_done",           # rollover completed fleet-wide
    "rollback",            # rollover aborted; cause carried in fields
    "quorum_degraded",     # training quorum fell below min_slaves
    "quorum_restored",     # training quorum recovered
    "replan",              # master rebuilt the relay tree (cause carried)
    "preemption",          # master rode out a dead slave/relay
    "breaker_open",        # a circuit breaker opened (peer carried)
    "prefix_evict",        # prefix cache evicted a cached block under pressure
    "page_shed",           # generation scheduler stalled/shed on page pressure
)


class EventJournal:
    """Bounded, seq-numbered, drops-oldest ring of structured events."""

    def __init__(self, capacity: int = 512,
                 origin: Optional[str] = None,
                 clock: Callable[[], float] = time.time) -> None:
        self.capacity = max(1, int(capacity))
        self.origin = origin or ""
        self._clock = clock
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0            # last assigned seq; 0 = nothing emitted
        self._dropped = 0        # lifetime count of events pushed off the ring
        self._lock = threading.Lock()

    # -- write side ----------------------------------------------------------

    def emit(self, kind: str, plane: str, **fields: Any) -> int:
        """Append one event; returns its seq.  Never raises."""
        try:
            ts = self._clock()
        except Exception:
            ts = 0.0
        evt: Dict[str, Any] = {"kind": str(kind), "plane": str(plane)}
        for k, v in fields.items():
            # keep the journal JSON-clean without paying for a deep
            # scrub: coerce non-primitive values to str at the edge
            if isinstance(v, (str, int, float, bool)) or v is None:
                evt[k] = v
            else:
                evt[k] = str(v)
        with self._lock:
            self._seq += 1
            evt["seq"] = self._seq
            evt["ts"] = ts
            if self.origin:
                evt["origin"] = self.origin
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(evt)
            return self._seq

    # -- read side -----------------------------------------------------------

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def since(self, seq: int = 0, limit: Optional[int] = None
              ) -> List[Dict[str, Any]]:
        """Events with ``seq > cursor``, oldest first (bounded copy)."""
        with self._lock:
            out = [dict(e) for e in self._ring if e["seq"] > seq]
        if limit is not None and len(out) > limit:
            out = out[-int(limit):]
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"origin": self.origin,
                    "last_seq": self._seq,
                    "dropped": self._dropped,
                    "capacity": self.capacity,
                    "events": [dict(e) for e in self._ring]}


class FleetEventStore:
    """Coordinator-side merge of member journals.

    Ingest is idempotent per ``(origin, seq)`` — piggyback batches may
    overlap when a sender retries — and the merged view carries a
    coordinator-assigned monotone ``mseq`` so ``/events.json?fleet=1``
    is cursorable exactly like a single-process journal.
    """

    def __init__(self, capacity: int = 2048) -> None:
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._mseq = 0
        self._high: Dict[str, int] = {}     # origin -> highest ingested seq
        self._lock = threading.Lock()

    def ingest(self, origin: str, events: List[Dict[str, Any]]) -> int:
        """Merge a member batch; returns how many were new."""
        if not events:
            return 0
        fresh = 0
        with self._lock:
            high = self._high.get(origin, 0)
            for e in events:
                try:
                    seq = int(e.get("seq", 0))
                except (TypeError, ValueError):
                    continue
                if seq <= high:
                    continue
                high = seq
                self._mseq += 1
                merged = dict(e)
                merged["origin"] = merged.get("origin") or origin
                merged["mseq"] = self._mseq
                self._ring.append(merged)
                fresh += 1
            self._high[origin] = high
        return fresh

    def cursor(self, origin: str) -> int:
        with self._lock:
            return self._high.get(origin, 0)

    def since(self, mseq: int = 0, limit: Optional[int] = None
              ) -> List[Dict[str, Any]]:
        with self._lock:
            out = [dict(e) for e in self._ring if e["mseq"] > mseq]
        if limit is not None and len(out) > limit:
            out = out[-int(limit):]
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"last_mseq": self._mseq,
                    "origins": dict(self._high),
                    "events": [dict(e) for e in self._ring]}
