"""Fleet observability plane (ISSUE 20 tentpole): cross-process trace
stitching, fleet metrics aggregation, and SLO burn-rate tracking.

PR 5 gave every process its own ``/metrics`` and ``/trace.json``; this
module gives the *fleet* one of each.  Four pieces:

  - :class:`SpanExporter` — a bounded, drops-oldest sink registered on
    the process :class:`~znicz_tpu.telemetry.trace.TraceRing`.  It keeps
    only spans that carry a ``trace_id`` arg (the cross-process
    correlation key wire-v3 metadata already propagates), converts
    their ``perf_counter`` timestamps to wall-clock µs (so spans from
    different hosts land on one timeline), and hands them out in small
    batches that ride *existing* traffic: replica heartbeats to the
    balancer, slave/relay update messages to the master, and serving
    replies back to the client.  Export never blocks recording and
    never blocks the carrier — a full buffer drops the oldest span and
    counts it.

  - :class:`FleetTraceStore` — the coordinator-side assembly: spans
    ingested per origin (a logical process identity like
    ``replica-1@4711``), indexed by ``trace_id``, rendered as ONE
    merged Chrome-trace timeline (``/trace.json?fleet=1``) with a
    synthetic ``pid`` per origin so Perfetto shows client → balancer →
    replica frontend → scheduler tick → prefill/decode as stacked
    process tracks.

  - :func:`registry_snapshot` / :class:`FleetMetricsStore` /
    :func:`render_fleet_prometheus` — member registries serialized
    (counters/gauges exact; histogram rings carried as a capped window
    plus exact lifetime count/sum), merged under the coordinator's own
    families with a ``member=<origin>`` label added, so one scrape of
    the coordinator's ``/metrics`` sees the whole fleet and every
    per-process series name survives verbatim.  ``/fleet.json`` serves
    the structured rollup (summed counters, per-member gauges, merged
    histogram quantiles).

  - :class:`SloTracker` — config-declared objectives per plane
    (serving p99 / TTFT / inter-token / availability; training
    apply-progress) tracked as good/bad counts in time buckets, with
    fast- and slow-window burn rates (rate 1.0 = exactly consuming the
    error budget) and an advisory state (``ok``/``warn``/``burning``)
    that ``/readyz`` reports WITHOUT ever flipping its existing gates.

TPU protocol note: everything here is host-side Python over numbers the
process already measured — span export adds no device syncs and nothing
below touches jax.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from .metrics import (EXPORT_QUANTILES, Histogram, MetricsRegistry,
                      _format_value, _render_labels)

#: cap on histogram-window samples carried per child in a registry
#: snapshot — keeps a heartbeat piggyback to a few KB while count/sum
#: stay exact (quantiles over the cap approximate the member's ring)
SNAPSHOT_WINDOW_CAP = 64


def process_identity(role: str) -> str:
    """A fleet-unique logical-process identity: ``<role>@<pid>``.  Two
    logical processes sharing an OS pid (a bench driving the balancer
    in-process) still get distinct origins."""
    return f"{role}@{os.getpid()}"


# ---------------------------------------------------------------------------
# span export (member side)
# ---------------------------------------------------------------------------

class SpanExporter:
    """Bounded drops-oldest buffer of completed spans, fed as a
    :class:`TraceRing` sink and drained by the piggyback carriers.

    ``offer`` is the hot-path side: one dict membership test for the
    ``trace_id`` filter, one deque append.  A full buffer evicts the
    oldest span (``deque(maxlen=...)``) and counts the drop — export
    pressure can never stall a heartbeat or a reply.
    """

    def __init__(self, origin: str, capacity: int = 1024,
                 export_all: bool = False) -> None:
        self.origin = origin
        self.capacity = max(1, int(capacity))
        self.export_all = bool(export_all)
        self._buf: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.offered = 0       # lifetime spans accepted into the buffer
        self.dropped = 0       # lifetime spans evicted before a drain
        # perf_counter -> wall clock, captured once; drift over a run is
        # far below span durations and keeps conversion to one add
        self._offset_us = (time.time() - time.perf_counter()) * 1e6

    # sink signature: the raw TraceRing event tuple
    def __call__(self, evt: tuple) -> None:
        try:
            cat, name, ts_us, dur_us, tid, args = evt
            if not self.export_all and not (args and "trace_id" in args):
                return
            span = {"cat": cat, "name": name,
                    "ts": int(ts_us + self._offset_us), "dur": int(dur_us),
                    "tid": int(tid)}
            if args:
                span["args"] = dict(args)
            with self._lock:
                if len(self._buf) == self._buf.maxlen:
                    self.dropped += 1
                self._buf.append(span)
                self.offered += 1
        except Exception:
            # a broken exporter must never take the tracer down
            return

    def drain(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Remove and return up to ``limit`` oldest spans (all if None)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            n = len(self._buf) if limit is None else min(int(limit),
                                                         len(self._buf))
            for _ in range(n):
                out.append(self._buf.popleft())
        return out

    def peek_trace(self, trace_id: str, limit: int = 32
                   ) -> List[Dict[str, Any]]:
        """Non-destructive scan for one trace's spans — the reply-side
        summary (replies carry only their own request's spans; the
        heartbeat drain still delivers everything to the balancer)."""
        with self._lock:
            out = [dict(s) for s in self._buf
                   if s.get("args", {}).get("trace_id") == trace_id]
        return out[-int(limit):]

    def pending(self) -> int:
        with self._lock:
            return len(self._buf)


# ---------------------------------------------------------------------------
# trace stitching (coordinator side)
# ---------------------------------------------------------------------------

class FleetTraceStore:
    """Spans from many origins, assembled by ``trace_id`` into one
    merged Chrome-trace timeline.  Bounded by total span count
    (drops-oldest across the whole fleet)."""

    def __init__(self, capacity: int = 8192) -> None:
        self.capacity = max(1, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)   # (origin, span)
        self._lock = threading.Lock()
        self.ingested = 0

    def ingest(self, origin: str, spans: Iterable[Dict[str, Any]]) -> int:
        n = 0
        with self._lock:
            for s in spans or ():
                if not isinstance(s, dict):
                    continue
                self._ring.append((str(origin), s))
                self.ingested += 1
                n += 1
        return n

    def spans(self) -> List[Tuple[str, Dict[str, Any]]]:
        with self._lock:
            return list(self._ring)

    def traces(self) -> Dict[str, List[Tuple[str, Dict[str, Any]]]]:
        out: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        for origin, s in self.spans():
            tid = s.get("args", {}).get("trace_id")
            if tid is not None:
                out.setdefault(str(tid), []).append((origin, s))
        return out

    def trace_origins(self, trace_id: str) -> List[str]:
        seen: List[str] = []
        for origin, _ in self.traces().get(str(trace_id), ()):
            if origin not in seen:
                seen.append(origin)
        return seen

    def best_stitched(self) -> Tuple[Optional[str], List[str]]:
        """The trace crossing the most origins (the bench gate's
        evidence that stitching works end-to-end)."""
        best: Tuple[Optional[str], List[str]] = (None, [])
        for tid, members in self.traces().items():
            origins: List[str] = []
            for origin, _ in members:
                if origin not in origins:
                    origins.append(origin)
            if len(origins) > len(best[1]):
                best = (tid, origins)
        return best

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Merged Chrome trace-event JSON: one synthetic pid per origin,
        named via ``process_name`` metadata events, spans on the shared
        wall-clock axis.  ``trace_id`` narrows to one request/job."""
        snap = self.spans()
        pids: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []
        for origin, s in snap:
            args = s.get("args") or {}
            if trace_id is not None and args.get("trace_id") != trace_id:
                continue
            pid = pids.get(origin)
            if pid is None:
                pid = pids[origin] = len(pids) + 1
                events.append({"name": "process_name", "ph": "M",
                               "pid": pid, "tid": 0,
                               "args": {"name": origin}})
            ev = {"name": s.get("name", "?"), "cat": s.get("cat", "?"),
                  "ph": "X", "ts": int(s.get("ts", 0)),
                  "dur": int(s.get("dur", 0)), "pid": pid,
                  "tid": int(s.get("tid", 0))}
            if args:
                ev["args"] = args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "fleet": {"origins": sorted(pids),
                          "spans": len(events) - len(pids)}}

    def snapshot(self) -> Dict[str, Any]:
        snap = self.spans()
        per_origin: Dict[str, int] = {}
        trace_ids = set()
        for origin, s in snap:
            per_origin[origin] = per_origin.get(origin, 0) + 1
            tid = (s.get("args") or {}).get("trace_id")
            if tid is not None:
                trace_ids.add(str(tid))
        return {"spans": len(snap), "ingested": self.ingested,
                "origins": per_origin, "traces": len(trace_ids)}


# ---------------------------------------------------------------------------
# metrics aggregation
# ---------------------------------------------------------------------------

def _json_value(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    if f != f or f in (float("inf"), float("-inf")):   # NaN / Inf
        return None
    return v if isinstance(v, (int, bool)) else f


def registry_snapshot(reg: MetricsRegistry,
                      window_cap: int = SNAPSHOT_WINDOW_CAP
                      ) -> Dict[str, Any]:
    """Serialize a registry for piggyback: counters/gauges exact,
    histograms as lifetime ``count``/``sum`` plus a capped ring window
    (enough for coordinator-side quantiles).  JSON-clean by
    construction (NaN gauges are dropped, not shipped)."""
    fams: List[Dict[str, Any]] = []
    for fam, children in reg.collect():
        kids: List[Dict[str, Any]] = []
        for m in children:
            if isinstance(m, Histogram):
                win = m.window()
                if win.size > window_cap:
                    win = win[-window_cap:]
                kids.append({"labels": dict(m.labels),
                             "count": int(m.count),
                             "sum": float(m.sum),
                             "window": [float(x) for x in win]})
            else:
                v = _json_value(m.value)
                if v is None:
                    continue
                kids.append({"labels": dict(m.labels), "value": v})
        if kids:
            fams.append({"name": fam.name, "kind": fam.kind,
                         "help": fam.help, "children": kids})
    return {"families": fams}


class FleetMetricsStore:
    """Latest-wins member registry snapshots, keyed by origin."""

    def __init__(self) -> None:
        self._members: Dict[str, Dict[str, Any]] = {}
        self._stamp: Dict[str, float] = {}
        self._lock = threading.Lock()

    def update(self, origin: str, snapshot: Dict[str, Any]) -> None:
        if not isinstance(snapshot, dict) or "families" not in snapshot:
            return
        with self._lock:
            self._members[str(origin)] = snapshot
            self._stamp[str(origin)] = time.time()

    def members(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return dict(self._members)

    def ages(self) -> Dict[str, float]:
        now = time.time()
        with self._lock:
            return {o: now - t for o, t in self._stamp.items()}

    def rollup(self) -> Dict[str, Any]:
        """Structured fleet view for ``/fleet.json``: counters summed
        across members, gauges listed per member, histogram windows
        merged into fleet quantiles."""
        members = self.members()
        fams: Dict[str, Dict[str, Any]] = {}
        for origin, snap in members.items():
            for fam in snap.get("families", []):
                name, kind = fam.get("name"), fam.get("kind")
                agg = fams.setdefault(name, {"kind": kind, "total": 0.0,
                                             "members": {}, "_win": [],
                                             "count": 0, "sum": 0.0})
                for child in fam.get("children", []):
                    if kind == "histogram":
                        agg["count"] += int(child.get("count", 0))
                        agg["sum"] += float(child.get("sum", 0.0))
                        agg["_win"].extend(child.get("window", []))
                    else:
                        v = child.get("value", 0)
                        agg["total"] += float(v)
                        agg["members"][origin] = \
                            agg["members"].get(origin, 0.0) + float(v)
        out: Dict[str, Any] = {}
        for name, agg in fams.items():
            entry: Dict[str, Any] = {"kind": agg["kind"]}
            if agg["kind"] == "histogram":
                entry["count"] = agg["count"]
                entry["sum"] = agg["sum"]
                win = np.asarray(agg["_win"], np.float64)
                if win.size:
                    entry["quantiles"] = {
                        repr(float(q)): float(np.quantile(win, q))
                        for q in EXPORT_QUANTILES}
            else:
                entry["total"] = agg["total"]
                entry["members"] = agg["members"]
            out[name] = entry
        return {"members": {o: {"age_s": round(a, 3)}
                            for o, a in self.ages().items()},
                "families": out}


def render_fleet_prometheus(reg: MetricsRegistry, store: FleetMetricsStore,
                            member_label: str = "member") -> str:
    """The coordinator's ``/metrics`` superset: every LOCAL family
    rendered exactly as ``MetricsRegistry.render_prometheus`` would
    (same order, same bytes — pre-existing series survive verbatim),
    with member children appended under the same family (one ``# TYPE``
    per name, strict-exposition clean) carrying an extra
    ``member=<origin>`` label; member-only families follow at the end."""
    members = store.members()
    # family name -> list of (labels, extra, value) member sample rows,
    # plus family metadata for names the local registry doesn't have
    rows: Dict[str, List[str]] = {}
    meta: Dict[str, Tuple[str, str]] = {}
    for origin, snap in members.items():
        for fam in snap.get("families", []):
            name = fam.get("name")
            meta.setdefault(name, (fam.get("kind", "gauge"),
                                   fam.get("help", "")))
            out = rows.setdefault(name, [])
            for child in fam.get("children", []):
                labels = dict(child.get("labels", {}))
                labels[member_label] = origin
                if "window" in child or "count" in child:
                    win = np.asarray(child.get("window", []), np.float64)
                    if win.size:
                        qs = np.quantile(win, EXPORT_QUANTILES)
                        for q, v in zip(EXPORT_QUANTILES, qs):
                            out.append(
                                f"{name}"
                                f"{_render_labels(labels, {'quantile': repr(float(q))})} "
                                f"{_format_value(float(v))}")
                    lbl = _render_labels(labels)
                    out.append(f"{name}_sum{lbl} "
                               f"{_format_value(child.get('sum', 0.0))}")
                    out.append(f"{name}_count{lbl} "
                               f"{_format_value(child.get('count', 0))}")
                else:
                    out.append(f"{name}{_render_labels(labels)} "
                               f"{_format_value(child.get('value', 0))}")
    out: List[str] = []
    seen: set = set()
    for fam, children in reg.collect():
        seen.add(fam.name)
        if fam.help:
            out.append(f"# HELP {fam.name} {fam.help}")
        kind = "summary" if fam.kind == "histogram" else fam.kind
        out.append(f"# TYPE {fam.name} {kind}")
        for m in children:
            if isinstance(m, Histogram):
                for extra, v in m.samples():
                    out.append(f"{fam.name}"
                               f"{_render_labels(m.labels, extra)} "
                               f"{_format_value(v)}")
                lbl = _render_labels(m.labels)
                out.append(f"{fam.name}_sum{lbl} {_format_value(m.sum)}")
                out.append(f"{fam.name}_count{lbl} "
                           f"{_format_value(m.count)}")
            else:
                for extra, v in m.samples():
                    out.append(f"{fam.name}"
                               f"{_render_labels(m.labels, extra)} "
                               f"{_format_value(v)}")
        out.extend(rows.get(fam.name, ()))
    for name in sorted(rows):
        if name in seen:
            continue
        kind, help_ = meta.get(name, ("gauge", ""))
        if help_:
            out.append(f"# HELP {name} {help_}")
        out.append(f"# TYPE {name} "
                   f"{'summary' if kind == 'histogram' else kind}")
        out.extend(rows[name])
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# SLO tracking
# ---------------------------------------------------------------------------

class SloTracker:
    """Multi-window burn-rate tracking over config-declared objectives.

    Each objective is a success-ratio target (``target=0.99`` ⇒ 1%
    error budget); latency objectives declare a ``threshold`` in
    seconds and feed through :meth:`record_latency` (good ⇔ under
    threshold).  Observations land in coarse time buckets; burn rate
    over a window is ``bad_fraction / (1 - target)`` — 1.0 means the
    error budget is being consumed exactly at the sustainable rate,
    higher means it will exhaust early.  State: ``warn`` when the fast
    window burns, ``burning`` when fast AND slow do (the classic
    multi-window alert shape, immune to single-bucket blips).

    The tracker is ADVISORY by contract: ``/readyz`` carries its state
    as a new field and never gates on it.
    """

    def __init__(self, plane: str,
                 window_fast_s: float = 60.0,
                 window_slow_s: float = 600.0,
                 bucket_s: float = 5.0,
                 clock=time.time) -> None:
        self.plane = str(plane)
        self.window_fast_s = float(window_fast_s)
        self.window_slow_s = float(window_slow_s)
        self.bucket_s = max(0.001, float(bucket_s))
        self._clock = clock
        self._obj: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def add_objective(self, name: str, target: float,
                      threshold: Optional[float] = None,
                      unit: str = "") -> None:
        target = min(max(float(target), 0.0), 0.999999)
        with self._lock:
            self._obj[str(name)] = {
                "target": target, "threshold": threshold, "unit": unit,
                "buckets": deque(), "good": 0, "bad": 0}

    def objectives(self) -> List[str]:
        with self._lock:
            return list(self._obj)

    # -- feeding -------------------------------------------------------------

    def record(self, name: str, ok: bool, n: int = 1,
               now: Optional[float] = None) -> None:
        if now is None:
            now = self._clock()
        idx = int(now / self.bucket_s)
        with self._lock:
            obj = self._obj.get(str(name))
            if obj is None:
                return
            buckets = obj["buckets"]
            if buckets and buckets[-1][0] == idx:
                slot = buckets[-1]
            else:
                slot = [idx, 0, 0]
                buckets.append(slot)
                horizon = idx - int(self.window_slow_s / self.bucket_s) - 1
                while buckets and buckets[0][0] < horizon:
                    buckets.popleft()
            if ok:
                slot[1] += int(n)
                obj["good"] += int(n)
            else:
                slot[2] += int(n)
                obj["bad"] += int(n)

    def record_latency(self, name: str, seconds: float,
                       now: Optional[float] = None) -> None:
        with self._lock:
            obj = self._obj.get(str(name))
            thr = None if obj is None else obj.get("threshold")
        if thr is None:
            return
        self.record(name, float(seconds) <= float(thr), now=now)

    # -- reading -------------------------------------------------------------

    def _window_counts(self, obj: Dict[str, Any], window_s: float,
                       now: float) -> Tuple[int, int]:
        lo = int((now - window_s) / self.bucket_s)
        good = bad = 0
        for idx, g, b in obj["buckets"]:
            if idx > lo:
                good += g
                bad += b
        return good, bad

    def burn_rate(self, name: str, window_s: float,
                  now: Optional[float] = None) -> Optional[float]:
        """``bad_fraction / error_budget`` over the window; None while
        the window holds no observations."""
        if now is None:
            now = self._clock()
        with self._lock:
            obj = self._obj.get(str(name))
            if obj is None:
                return None
            good, bad = self._window_counts(obj, float(window_s), now)
            budget = 1.0 - obj["target"]
        total = good + bad
        if total == 0:
            return None
        return (bad / total) / budget

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        if now is None:
            now = self._clock()
        out: Dict[str, Any] = {}
        with self._lock:
            items = list(self._obj.items())
        for name, obj in items:
            with self._lock:
                fast = self._window_counts(obj, self.window_fast_s, now)
                slow = self._window_counts(obj, self.window_slow_s, now)
                target = obj["target"]
                good, bad = obj["good"], obj["bad"]
                thr = obj["threshold"]
            budget = 1.0 - target

            def _burn(counts):
                total = counts[0] + counts[1]
                if total == 0:
                    return None
                return (counts[1] / total) / budget

            fast_burn, slow_burn = _burn(fast), _burn(slow)
            if fast_burn is not None and fast_burn >= 1.0 \
                    and slow_burn is not None and slow_burn >= 1.0:
                state = "burning"
            elif fast_burn is not None and fast_burn >= 1.0:
                state = "warn"
            else:
                state = "ok"
            slow_total = slow[0] + slow[1]
            remaining = (1.0 - (slow[1] / slow_total) / budget
                         if slow_total else 1.0)
            out[name] = {"target": target, "threshold": thr,
                         "unit": obj.get("unit", ""),
                         "fast_burn": fast_burn, "slow_burn": slow_burn,
                         "state": state,
                         "budget_remaining": max(-1.0, min(1.0, remaining)),
                         "good": good, "bad": bad}
        states = [o["state"] for o in out.values()]
        overall = ("burning" if "burning" in states
                   else "warn" if "warn" in states else "ok")
        return {"plane": self.plane, "state": overall,
                "window_fast_s": self.window_fast_s,
                "window_slow_s": self.window_slow_s,
                "objectives": out}
