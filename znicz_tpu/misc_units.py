"""Misc NN units (rebuild of the reference's assorted ``znicz/*.py`` —
SURVEY.md §2.2 "Misc units").

  - ``MeanDispNormalizerUnit`` — in-graph input normalization: subtracts a
    fitted mean and divides by dispersion on the fly (the reference's
    ``MeanDispNormalizer`` unit form, distinct from the loader-side
    normalizers in znicz_tpu/normalization.py);
  - ``ZeroFiller`` — keeps a boolean mask of zeroed weight positions and
    re-applies it after every update (the reference's sparsity mask);
  - ``NNRollback`` — watches the loss and restores the last good parameter
    snapshot when divergence is detected (loss > factor × best).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from znicz_tpu.core.units import Unit
from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase


class MeanDispNormalizerUnit(ForwardBase):
    """output = (input - mean) / disp, with mean/disp Arrays linked or set
    (fit them with normalization.MeanDispNormalizer on the train split)."""

    has_weights = False

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.mean = Array()
        self.disp = Array()

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    @staticmethod
    def _normalize(mean, disp, x):
        flat = x.reshape(x.shape[0], -1)
        return ((flat - mean) / disp).reshape(x.shape)

    def apply(self, params, x):
        # mean/disp are runtime state, not compile-time constants — the
        # closure form would bake the first-seen values into the jit cache
        raise NotImplementedError(
            "stateful normalizer; use run() (mean/disp are traced args)")

    def initialize(self, device=None, **kwargs):
        assert self.mean.mem is not None and self.disp.mem is not None, \
            f"{self.name}: set mean/disp before initialize"
        self.create_output()
        for arr in (self.mean, self.disp):
            arr.initialize(device)
        super().initialize(device=device, **kwargs)

    def run(self):
        if self._compiled is None:
            import jax

            self._compiled = jax.jit(self._normalize)
        self.output.devmem = self._compiled(
            self.mean.devmem, self.disp.devmem, self.input.devmem)


class ZeroFiller(Unit):
    """Re-zeroes masked weight positions after each update.  Bind forwards
    with ``add_mask(forward_unit, mask)`` (mask: bool array, True = keep)."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self._masks = []                    # (forward, bool ndarray)

    def add_mask(self, forward, mask: np.ndarray) -> None:
        mask = np.asarray(mask, bool)
        assert mask.shape == tuple(forward.weights.shape)
        self._masks.append((forward, mask))

    def run(self):
        for fwd, mask in self._masks:
            w = fwd.weights.map_write()
            w[~mask] = 0.0


class NNRollback(Unit):
    """Divergence guard: keeps the best-loss parameter copy; when the
    observed loss exceeds ``rollback_factor`` x best (or is non-finite),
    restores it and reports via ``rollbacks``."""

    def __init__(self, workflow=None, name=None, rollback_factor=4.0,
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.rollback_factor = float(rollback_factor)
        self.loss = 0.0                      # link from evaluator/decision
        self.best_loss = np.inf
        self.rollbacks = 0
        self._forwards = []
        self._best: Optional[Dict] = None

    def watch(self, *forwards) -> None:
        self._forwards.extend(forwards)

    def _snapshot(self) -> Dict:
        return {f.name: {k: np.array(a.map_read())
                         for k, a in f.params().items()}
                for f in self._forwards}

    def run(self):
        loss = float(self.loss)
        diverged = (not np.isfinite(loss)
                    or (self._best is not None
                        and loss > self.rollback_factor * self.best_loss))
        if diverged:
            if self._best is None:
                # diverged before any good state existed — nothing to
                # restore; report loudly and let the caller decide
                self.warning("loss %.4g diverged with no good snapshot yet "
                             "(nothing to roll back to)", loss)
                return
            for f in self._forwards:
                for k, a in f.params().items():
                    a.mem = self._best[f.name][k].copy()
            self.rollbacks += 1
            self.warning("loss %.4g diverged (best %.4g) -> rolled back",
                         loss, self.best_loss)
            return
        if loss < self.best_loss:
            self.best_loss = loss
            self._best = self._snapshot()
