"""znicz-tpu: a TPU-native neural-network framework with the capabilities of
degerli/veles.znicz (Samsung VELES core + Znicz NN plugin), re-designed
TPU-first on JAX / XLA / pjit / Pallas.

Layer map (mirrors SURVEY.md §1, rebuilt for TPU):

  - ``znicz_tpu.core``      — config tree, Unit/Workflow dataflow-graph engine,
                              mutable Bool gates, seeded PRNG service, logging.
  - ``znicz_tpu.memory``    — Array: host/device paired tensor over jax arrays
                              with the reference's map/unmap protocol.
  - ``znicz_tpu.backends``  — Device abstraction (TPU / CPU / virtual mesh).
  - ``znicz_tpu.ops``       — pure-functional jnp/lax/Pallas ops (the analogue
                              of the reference's .cl/.cu kernel trees).
  - NN unit modules (top level) — forwards (``all2all``, ``conv``,
                              ``pooling``, ``activation``, ``lrn``,
                              ``dropout``, ``kohonen``, ``rbm``,
                              ``attention``, ...) and their
                              GradientDescent* twins, ``evaluator``,
                              ``decision``, ``lr_adjust``,
                              ``standard_workflow``.
  - ``znicz_tpu.loader``    — Loader state machine (shuffling, balancing),
                              FullBatch/image/pickles/HDF5/LMDB loaders,
                              normalizers.
  - ``znicz_tpu.engine``    — engine selection: unit graph vs the fused
                              SPMD fast path vs master/slave roles
                              (launcher --fused/--master/--slave).
  - ``znicz_tpu.parallel``  — mesh construction, sharding rules, and
                              ``FusedTrainer`` (one jitted, mesh-sharded
                              scan step); replaces the reference's ZeroMQ
                              master-slave DP with SPMD psum over ICI.
                              The async master/slave mode survives in
                              ``server``/``client``/``network_common``.
  - ``znicz_tpu.serving``   — dynamic-batching inference service: frozen
                              snapshot params behind a ZMQ ROUTER on the
                              wire-v3 codec, request coalescing with a
                              bucket-ladder jit cache and donated
                              ping-pong staging (launcher --serve).
  - ``znicz_tpu.samples``   — MNIST, CIFAR10, MnistAE, Kohonen, AlexNet
                              (BASELINE.json configs 0-4) + Wine,
                              YaleFaces, Kanji, VideoAE.

Reference provenance: /root/reference was empty when this framework was
written (see SURVEY.md §0); component parity targets come from
/root/repo/BASELINE.json and SURVEY.md's reconstructed inventory.
"""

__version__ = "0.1.0"

from znicz_tpu.core.config import root, Config  # noqa: F401
from znicz_tpu.core.mutable import Bool  # noqa: F401
from znicz_tpu.core.units import Unit, TrivialUnit  # noqa: F401
from znicz_tpu.core.workflow import Workflow, Repeater  # noqa: F401
from znicz_tpu.memory import Array  # noqa: F401
