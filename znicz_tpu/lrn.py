"""Local response normalization fwd+bwd (rebuild of ``znicz/normalization.py``
— the AlexNet-style across-channel LRN; the input-data normalizers live in
``znicz_tpu/normalization.py`` matching the reference's core-vs-znicz split).

Forward: ``y = x / (k + alpha * sum_{j in window(c)} x_j^2) ^ beta`` with the
window of ``n`` adjacent channels centered on c.  Backward is the vjp.
Defaults follow the reference kernels: alpha=1e-4, beta=0.75, n=5, k=2.
"""

from __future__ import annotations

from znicz_tpu.nn_units import ForwardBase, GradientDescentBase


class LRNormalizerForward(ForwardBase):
    has_weights = False

    def __init__(self, workflow=None, name=None, alpha=1e-4, beta=0.75,
                 n=5, k=2.0, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.n = int(n)
        self.k = float(k)

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, x):
        import jax.numpy as jnp

        from znicz_tpu.core.config import root

        if bool(root.common.engine.get("pallas_lrn", False)):
            from znicz_tpu.ops.lrn_pallas import lrn

            return lrn(x, self.n, self.alpha, self.beta, self.k)
        half = self.n // 2
        sq = jnp.square(x)
        # sum over a window of n adjacent channels (zero-padded at the ends)
        padded = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        acc = jnp.zeros_like(x)
        for j in range(self.n):                      # n is tiny & static
            acc = acc + padded[..., j:j + x.shape[-1]]
        return x / jnp.power(self.k + self.alpha * acc, self.beta)

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)


class LRNormalizerBackward(GradientDescentBase):
    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)
