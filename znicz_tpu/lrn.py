"""Local response normalization fwd+bwd (rebuild of ``znicz/normalization.py``
— the AlexNet-style across-channel LRN; the input-data normalizers live in
``znicz_tpu/normalization.py`` matching the reference's core-vs-znicz split).

Forward: ``y = x / (k + alpha * sum_{j in window(c)} x_j^2) ^ beta`` with the
window of ``n`` adjacent channels centered on c.  Backward is a CLOSED-FORM
custom vjp (below) — autodiff through pow+window-sum materializes several
extra activation-sized tensors per step, and on AlexNet's conv1/conv2
activations that HBM traffic was ~20% of the whole train step (r4 profile).
Defaults follow the reference kernels: alpha=1e-4, beta=0.75, n=5, k=2.

The closed form: with ``s = k + alpha*winsum(x^2)`` and ``y = x*s^-beta``,

    dx = dy*s^-beta - 2*alpha*beta * x * winsum(dy * x * s^(-beta-1))

i.e. backward = 2 elementwise passes + 2 channel-window sums, with only
``(x,)`` saved from the forward (``s`` is recomputed — bitwise identical,
measured neutral, smaller residual).  ``s^-beta`` for the default beta=0.75
is computed as ``rsqrt(s)*sqrt(rsqrt(s))`` — two pipelined VPU ops instead
of the exp/log ``pow`` expansion.
"""

from __future__ import annotations

from functools import partial

from znicz_tpu.nn_units import ForwardBase, GradientDescentBase


def _winsum(t, n: int):
    """Sum over a window of n adjacent channels (zero-padded ends), via
    reduce_window: the pad+shifted-slices formulation materializes a
    channel-padded copy whose slices fall off the sublane tiling (96 -> 100
    channels), and the resulting relayout traffic capped the big LRN
    fusions at ~320 GB/s of the chip's 819 (r4 profile).  ODD n only —
    the closed-form vjp relies on the window being self-adjoint."""
    import jax

    assert n % 2 == 1, n
    half = n // 2
    return jax.lax.reduce_window(
        t, jax.numpy.zeros((), t.dtype), jax.lax.add,
        window_dimensions=(1,) * (t.ndim - 1) + (n,),
        window_strides=(1,) * t.ndim,
        padding=[(0, 0)] * (t.ndim - 1) + [(half, half)])


def _inv_pow(s, beta: float):
    """s ** -beta; beta=0.75 (the reference default) via rsqrt/sqrt.

    ``root.common.engine.lrn_pow = True`` forces the plain ``pow``
    expansion — kept so the r4 rsqrt change stays RE-RUNNABLE against
    the anchor protocol (VERDICT r4 weak #4: an anchor moved by a math
    change must be defensible side-by-side, not just re-recorded).
    Read at trace time: flip it only before the first compile of a
    process (the bench's --samples comparison uses subprocesses)."""
    import jax.numpy as jnp

    from znicz_tpu.core.config import root
    from znicz_tpu.ops.lrn_pallas import inv_pow_rsqrt

    if beta == 0.75 and not bool(root.common.engine.get("lrn_pow",
                                                        False)):
        return inv_pow_rsqrt(s, beta)
    return jnp.power(s, -beta)


@partial(__import__("jax").custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def lrn_ref(x, n: int, alpha: float, beta: float, k: float):
    s = k + alpha * _winsum(x * x, n)
    return x * _inv_pow(s, beta)


def _lrn_ref_fwd(x, n, alpha, beta, k):
    s = k + alpha * _winsum(x * x, n)
    return x * _inv_pow(s, beta), (x,)


def _lrn_ref_bwd(n, alpha, beta, k, res, dy):
    # recompute s from x instead of saving it (same expression, same
    # reduction order -> bitwise-identical).  Measured NEUTRAL on the
    # bench headline (11,306 vs 11,296 img/s, r5): fwd and bwd live in
    # ONE jitted step, so XLA already schedules the residual optimally —
    # kept because the smaller residual helps remat/memory at larger
    # batches and is never worse.
    (x,) = res
    s = k + alpha * _winsum(x * x, n)
    r = _inv_pow(s, beta)
    t = dy * x * (r / s)
    dx = dy * r - (2.0 * alpha * beta) * x * _winsum(t, n)
    return (dx,)


lrn_ref.defvjp(_lrn_ref_fwd, _lrn_ref_bwd)


class LRNormalizerForward(ForwardBase):
    has_weights = False

    def __init__(self, workflow=None, name=None, alpha=1e-4, beta=0.75,
                 n=5, k=2.0, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.n = int(n)
        self.k = float(k)

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    @property
    def fused_block_hypers(self):
        """(n, alpha, beta, k) when this unit's config is expressible by
        the single-pass conv-block kernel (odd windows only — the kernel
        shares the closed-form vjp's self-adjoint-window assumption), else
        None.  Consumed by pallas_fused_block.match_fused_block."""
        if self.n % 2 == 1:
            return (self.n, self.alpha, self.beta, self.k)
        return None

    def apply(self, params, x):
        from znicz_tpu.core.config import root

        if bool(root.common.engine.get("pallas_lrn", False)):
            from znicz_tpu.ops.lrn_pallas import lrn

            return lrn(x, self.n, self.alpha, self.beta, self.k)
        # lrn_autodiff=True re-runs the r3 formulation (plain autodiff
        # through pow + shifted-slices) — kept so the r4 closed-form-vjp
        # change stays defensible side-by-side at the anchors (VERDICT
        # r4 weak #4), same as the lrn_pow knob above
        if self.n % 2 == 1 and not bool(
                root.common.engine.get("lrn_autodiff", False)):
            return lrn_ref(x, self.n, self.alpha, self.beta, self.k)
        # even windows are asymmetric (not self-adjoint): plain autodiff
        # through the shifted-slices formulation instead of the
        # closed-form vjp
        import jax.numpy as jnp

        half = self.n // 2
        padded = jnp.pad(jnp.square(x),
                         [(0, 0)] * (x.ndim - 1) + [(half, half)])
        acc = jnp.zeros_like(x)
        for j in range(self.n):
            acc = acc + padded[..., j:j + x.shape[-1]]
        return x / jnp.power(self.k + self.alpha * acc, self.beta)

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)


class LRNormalizerBackward(GradientDescentBase):
    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)
