"""Dynamic-batching inference serving layer (ISSUE 4).

The training side of this repo got three PRs of optimization; this
package opens the INFERENCE workload the ROADMAP north star ("serves
heavy traffic from millions of users") requires: load a snapshot,
freeze params into an inference-only jitted forward, and serve
concurrent clients over ZeroMQ with the same wire-v3 zero-copy tensor
codec the master/slave stack speaks.

    serving/batcher.py   BucketLadder + DynamicBatcher — request
                         coalescing under (max_batch, max_delay_ms),
                         padding to a fixed bucket ladder (bounded jit
                         cache), bounded-queue backpressure
    serving/model.py     ModelRunner — frozen params, bucketed jit
                         cache with compile counters, donated
                         ping-pong stage/infer halves; mesh-native
                         (ISSUE 13): root.common.serving.mesh.* builds
                         a NamedSharding mesh, params replicate or
                         column-shard per FusedTrainer.param_sharding,
                         request batches split rows/dp over the data
                         axis directly from the host
    serving/frontend.py  InferenceServer — ZMQ ROUTER + codec + the
                         overlap compute loop; stats for web_status
    serving/client.py    InferenceClient — DEALER peer, pipelined
                         submits, resend-on-loss, req_id dedup,
                         per-endpoint breaker behind a balancer
    serving/balancer.py  ReplicaBalancer — fleet-grade front over N
                         replica processes (ISSUE 12): TTL'd heartbeat
                         membership, least-loaded dispatch,
                         exactly-once failover, hedged retries, canary
                         rollover with auto-rollback + healing

Overload safety + live operation (ISSUE 6): per-client token-bucket
rate limits and deficit-round-robin fair queueing in the batcher
(``root.common.serving.admission.*``), end-to-end deadline budgets
(client ships ``deadline_ms``, the frontend refuses expired work at
ingress/assemble/post-compute), a rolling-window circuit breaker in
the client, and zero-downtime snapshot rollover (``swap`` control
command / SIGHUP; every reply carries its snapshot ``gen``) with
``/healthz``/``/readyz`` on web_status.

Generation serving (ISSUE 16, paged in ISSUE 19): with
``root.common.serving.generate.enabled`` the frontend also speaks a
``generate`` request kind — prompt in, autoregressive tokens out.
Prompts prefill in fixed ``prefill_chunk`` token chunks into a
block-paged KV pool (full pages content-addressed and shared across
requests via the prefix cache, copy-on-write on divergence), then
O(cache) decode steps emit one token each with sampling fused
in-graph; decode steps from DIFFERENT requests coalesce every tick
(continuous batching) and finished sequences release their pages
mid-batch — the zero-recompile contract extended to the
(batch rung x page rung) prefill/decode families.

Config home: ``root.common.serving.{max_batch, max_delay_ms,
queue_bound, request_ttl_s}`` + ``root.common.serving.admission.*``
+ ``root.common.serving.mesh.*`` (pod-slice sharding, ISSUE 13)
+ ``root.common.serving.generate.*`` (ISSUE 16);
CLI: ``python -m znicz_tpu <workflow> --serve [BIND] --snapshot FILE``;
bench gate: ``python bench.py --serve`` (see README "Serving" and
"Serving robustness").
"""

from .balancer import ReplicaBalancer                       # noqa: F401
from .batcher import (AdmissionPolicy, BucketLadder,        # noqa: F401
                      DynamicBatcher, GenerationScheduler, GenSeq,
                      Refusal, Request, TokenBucket)
from .client import (CircuitOpenError, InferenceClient,     # noqa: F401
                     InferenceError)
from .frontend import InferenceServer                       # noqa: F401
from .model import GenerationRunner, ModelRunner            # noqa: F401
