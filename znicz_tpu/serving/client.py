"""Inference client (ISSUE 4): a DEALER peer of the serving frontend.

DEALER (not REQ) on purpose: many requests may be in flight at once
(the pipelined load that makes dynamic batching pay), replies arrive in
completion order, and — unlike REQ — a DEALER socket has no lockstep
EFSM to wedge, so a dropped frame needs no reconnect dance: the client
just re-sends the SAME already-encoded frames after ``resend_after_s``
(inference is pure, so a duplicate compute is wasted work, not a
correctness problem; duplicate replies are deduplicated by ``req_id``).

Messages ride the wire-v3 codec (parallel/wire.py): the request tensor
and the result tensor are zero-copy buffer frames.
"""

from __future__ import annotations

import itertools
import time
from typing import Dict, List, Optional

import numpy as np

from znicz_tpu.telemetry.metrics import registered_property


class InferenceError(RuntimeError):
    """The service answered, but with a refusal (bad frame / shed /
    timed out / shape mismatch); the reply dict is ``.reply``."""

    def __init__(self, reply: dict):
        super().__init__(str(reply.get("error") or reply))
        self.reply = reply


class InferenceClient:
    """One-thread client.  ``infer(x)`` is the synchronous call;
    ``submit(x)``/``result(req_id)`` expose the pipelined form (keep W
    requests in flight, collect in any order) the bench's offered-load
    driver uses.  NOT thread-safe — one instance per thread."""

    def __init__(self, endpoint: str, timeout: float = 10.0,
                 resend_after_s: float = 1.0, max_resends: int = 8):
        import uuid

        import zmq

        #: prefix for this client's trace_ids (ISSUE 5 correlation —
        #: the server echoes them in replies and tags its spans)
        self._tag = uuid.uuid4().hex[:6]
        self.endpoint = endpoint
        self.timeout = float(timeout)
        self.resend_after_s = float(resend_after_s)
        self.max_resends = int(max_resends)
        # telemetry (ISSUE 5): client-side accounting in the registry;
        # historical attribute names preserved by generated properties
        from znicz_tpu import telemetry

        _sc = telemetry.scope("serving_client")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self._ids = itertools.count(1)
        #: req_id -> [frames, t_last_sent, resends]
        self._pending: Dict[int, List] = {}
        self._results: Dict[int, dict] = {}
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(endpoint)

    #: client counters registered under component="serving_client"
    #: (ISSUE 5): name -> HELP text; properties generated after the
    #: class body
    COUNTERS = {
        "resends": "re-sent requests (lost/ignored)",
        "bad_replies": "undecodable replies",  # shared family
        "errors": "service refusals received",
    }

    # -- pipelined API ---------------------------------------------------------

    def _send(self, msg: dict) -> int:
        """Encode + send one request; returns its req_id.  The payload
        rides behind a REQ-style EMPTY DELIMITER frame: the server (and
        any chaos proxy between) splits the envelope at the delimiter,
        so even a request whose METADATA frame is corrupted in flight
        keeps a routable envelope — the refusal reply still finds its
        way back instead of being silently unroutable."""
        from znicz_tpu.parallel import wire

        rid = next(self._ids)
        msg["req_id"] = rid
        # optional correlation key in the v3 metadata frame (ISSUE 5):
        # old servers ignore it, new ones echo it and tag their spans
        msg.setdefault("trace_id", f"{self._tag}-{rid}")
        payload, _ = wire.encode_message(msg)
        frames = [b""] + payload
        self._sock.send_multipart(frames, copy=False)
        self._pending[rid] = [frames, time.perf_counter(), 0]
        return rid

    def submit(self, x: np.ndarray) -> int:
        """Send one inference request; returns its ``req_id``."""
        return self._send({"cmd": "infer", "x": np.ascontiguousarray(x)})

    def _command(self, cmd: str, timeout: Optional[float] = None) -> dict:
        return self.result(self._send({"cmd": cmd}), timeout=timeout)

    def ping(self, timeout: Optional[float] = None) -> dict:
        return self._command("ping", timeout)

    def stats(self, timeout: Optional[float] = None) -> dict:
        """The server's live stats() dict (the serving panel payload)."""
        return self._command("stats", timeout)["stats"]

    def _pump(self, wait_s: float) -> None:
        """Receive every reply available (waiting up to ``wait_s`` for
        the first) and file each under its req_id; undecodable stacks
        are counted and dropped (the resend timer recovers the
        request)."""
        import zmq

        from znicz_tpu.parallel import wire

        if not self._sock.poll(max(0, int(wait_s * 1000))):
            return
        while True:
            try:
                raw = self._sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            try:
                # strip the delimiter the request's envelope carried
                _, payload = wire.split_envelope(raw)
                rep, _ = wire.decode_message(payload or raw)
                if not isinstance(rep, dict):
                    raise wire.WireError(
                        f"reply decodes to {type(rep).__name__}")
            except Exception:
                self._m["bad_replies"].inc()
                continue
            rid = rep.get("req_id")
            if rid in self._pending:
                del self._pending[rid]
                self._results[rid] = rep
            # else: duplicate (our resend raced the original) — dropped

    def _maybe_resend(self) -> None:
        now = time.perf_counter()
        for rid, entry in self._pending.items():
            frames, t_sent, n = entry
            if now - t_sent < self.resend_after_s:
                continue
            if n >= self.max_resends:
                raise TimeoutError(
                    f"req {rid}: no reply after {n} resends over "
                    f"{now - t_sent + n * self.resend_after_s:.1f}s — "
                    f"service at {self.endpoint} unreachable?")
            # the SAME encoded frames: bytes, not re-serialization
            self._sock.send_multipart(frames, copy=False)
            entry[1] = now
            entry[2] = n + 1
            self._m["resends"].inc()

    def result(self, req_id: int, timeout: Optional[float] = None) -> dict:
        """Block until ``req_id``'s reply lands (resending past the
        resend timer); raises :class:`InferenceError` on a service
        refusal, TimeoutError when the service never answers."""
        deadline = time.perf_counter() + (self.timeout if timeout is None
                                          else float(timeout))
        while req_id not in self._results:
            if time.perf_counter() > deadline:
                raise TimeoutError(f"req {req_id}: no reply within "
                                   f"{self.timeout:g}s")
            self._pump(0.05)
            self._maybe_resend()
        rep = self._results.pop(req_id)
        if not rep.get("ok"):
            self._m["errors"].inc()
            raise InferenceError(rep)
        return rep

    def collect(self, wait_s: float = 0.0) -> List[dict]:
        """Drain whatever replies are available right now (offered-load
        driver); refusal replies are returned, not raised."""
        self._pump(wait_s)
        self._maybe_resend()
        out = list(self._results.values())
        self._results.clear()
        return out

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    # -- synchronous API -------------------------------------------------------

    def infer(self, x: np.ndarray,
              timeout: Optional[float] = None) -> np.ndarray:
        """One request, one result: the (n, *out) result rows for the
        (n, *sample) input (a bare sample comes back with its leading
        1-row axis)."""
        return self.result(self.submit(x), timeout=timeout)["y"]

    def close(self) -> None:
        self._sock.close(0)


for _name, _help in InferenceClient.COUNTERS.items():
    setattr(InferenceClient, _name, registered_property(_name, _help))
del _name, _help
