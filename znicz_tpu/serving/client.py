"""Inference client (ISSUE 4): a DEALER peer of the serving frontend.

DEALER (not REQ) on purpose: many requests may be in flight at once
(the pipelined load that makes dynamic batching pay), replies arrive in
completion order, and — unlike REQ — a DEALER socket has no lockstep
EFSM to wedge, so a dropped frame needs no reconnect dance: the client
just re-sends the SAME already-encoded frames after ``resend_after_s``
(inference is pure, so a duplicate compute is wasted work, not a
correctness problem; duplicate replies are deduplicated by ``req_id``).

Overload safety (ISSUE 6):

  - every request ships a ``deadline_ms`` BUDGET in the wire-v3
    metadata (old servers ignore it, like ``trace_id``); the server
    refuses/abandons the request once the budget is spent, so a slow
    service never computes or ships answers nobody waits for;
  - the resend loop is CAPPED (``max_resends``; a counted, readable
    give-up — mirrors the master client's ``connect_retries``);
  - a rolling-window CIRCUIT BREAKER: enough failures (give-ups, shed
    refusals, bad frames) in the recent window OPEN the breaker and
    ``submit`` fails fast with :class:`CircuitOpenError` instead of
    feeding resend traffic to a dead/overloaded service; after a
    capped-exponential backoff (PR 2's reconnect idiom) ONE half-open
    probe is let through — success closes the breaker, failure
    re-opens it with doubled backoff.  Per-client refusals
    (``rate_limited`` / ``oversized`` / ``deadline``, and a shed whose
    reply says ``scope: client`` — the caller's own fair-share queue
    bound) do NOT trip the breaker: the service is alive and
    answering, backing off everyone over one caller's quota would be
    self-inflicted downtime.  Only the SERVICE-scoped shed (global
    queue at bound) counts as overload.

Behind a balancer the breaker is PER-ENDPOINT (ISSUE 12): a reply that
carries the balancer's ``lb`` stamp attributes its outcome to the
``replica_id`` stamped on it — filed into that replica's own rolling
window (``replica_breakers()``; opens counted) and NOT into the whole-
service breaker, so one sick replica behind a healthy balancer can
never fail-fast the client against the whole fleet (the balancer is
already routing around it).  Unstamped failures — give-ups, timeouts,
bad frames, and anything from a direct (non-balancer) runner — keep
feeding the service breaker exactly as before.

Messages ride the wire-v3 codec (parallel/wire.py): the request tensor
and the result tensor are zero-copy buffer frames.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Dict, List, Optional

import numpy as np

from znicz_tpu.telemetry.metrics import registered_property
# the breaker now lives in the transport core (ISSUE 14) — ONE fault
# model for every plane; re-exported here under the historical name
from znicz_tpu.transport import (CircuitBreaker,            # noqa: F401
                                 CircuitOpenError, RetryPolicy)


class InferenceError(RuntimeError):
    """The service answered, but with a refusal (bad frame / shed /
    rate_limited / deadline / shape mismatch); the reply dict is
    ``.reply`` (``.reply.get("policy")`` names the refusing policy)."""

    def __init__(self, reply: dict):
        super().__init__(str(reply.get("error") or reply))
        self.reply = reply


class InferenceClient:
    """One-thread client.  ``infer(x)`` is the synchronous call;
    ``submit(x)``/``result(req_id)`` expose the pipelined form (keep W
    requests in flight, collect in any order) the bench's offered-load
    driver uses.  NOT thread-safe — one instance per thread."""

    def __init__(self, endpoint: str, timeout: float = 10.0,
                 resend_after_s: float = 1.0, max_resends: int = 8,
                 deadline_s: Optional[float] = None,
                 client_id: Optional[str] = None,
                 breaker_window: int = 16, breaker_failures: int = 8,
                 breaker_reset_s: float = 0.5,
                 breaker_backoff_cap_s: float = 30.0):
        import uuid

        import zmq

        #: prefix for this client's trace_ids (ISSUE 5 correlation —
        #: the server echoes them in replies and tags its spans)
        self._tag = uuid.uuid4().hex[:6]
        #: admission identity shipped as ``client`` metadata (ISSUE 6):
        #: the server's rate limit / fair queue keys on it
        self.client_id = client_id or self._tag
        self.endpoint = endpoint
        self.timeout = float(timeout)
        self.resend_after_s = float(resend_after_s)
        self.max_resends = int(max_resends)
        #: per-request deadline budget shipped on the wire; defaults to
        #: ``timeout`` (by the client's own deadline the answer is
        #: worthless anyway); per-call ``deadline_s`` overrides
        self.deadline_s = (float(timeout) if deadline_s is None
                           else float(deadline_s))
        # telemetry (ISSUE 5): client-side accounting in the registry;
        # historical attribute names preserved by generated properties
        from znicz_tpu import telemetry

        _sc = telemetry.scope("serving_client")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        # -- circuit breaker: the transport core's (ISSUE 14 — the PR 6
        # machinery, extracted to znicz_tpu/transport/retry.py so every
        # plane rides ONE implementation); breaker_failures=0 disables.
        # Constants preserved: reset_s doubling to backoff_cap_s, no
        # jitter; transition events feed the historical counters.
        _brk_events = {"open": self._m["breaker_opens"],
                       "short_circuit": self._m["breaker_short_circuits"],
                       "probe": self._m["breaker_probes"]}
        self._breaker = CircuitBreaker(
            window=int(breaker_window), threshold=int(breaker_failures),
            backoff=RetryPolicy.for_breaker(float(breaker_reset_s),
                                            float(breaker_backoff_cap_s)),
            on_event=lambda name: _brk_events[name].inc(), peer=endpoint)
        # per-endpoint windows behind a balancer (ISSUE 12): outcome
        # deques keyed by the reply's replica_id stamp; same window/
        # threshold as the service breaker, bounded oldest-first
        self._brk_replicas: "collections.OrderedDict[str, collections.deque]" \
            = collections.OrderedDict()
        self._brk_replica_open: Dict[str, bool] = {}
        _sc.gauge("breaker_open",
                  "circuit breaker state (0 closed, 0.5 half-open, 1 open)",
                  fn=telemetry.weak_fn(
                      self, lambda c: {"closed": 0.0, "half_open": 0.5,
                                       "open": 1.0}[c._breaker.state]))
        self._tracer = telemetry.tracer()
        #: req_id -> (trace_id, t_submitted) for the client-side
        #: request span (ISSUE 20 fleet stitching); popped wherever
        #: _pending is, so it stays bounded by requests in flight
        self._obs_req: Dict[int, tuple] = {}
        self._ids = itertools.count(1)
        #: req_id -> [frames, t_last_sent, resends]
        self._pending: Dict[int, List] = {}
        self._results: Dict[int, dict] = {}
        #: req_id -> callback for streamed generation tokens (ISSUE 16)
        self._on_token: Dict[int, object] = {}
        self._ctx = zmq.Context.instance()
        self._sock = self._ctx.socket(zmq.DEALER)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.connect(endpoint)

    #: client counters registered under component="serving_client"
    #: (ISSUE 5): name -> HELP text; properties generated after the
    #: class body
    COUNTERS = {
        "resends": "re-sent requests (lost/ignored)",
        "bad_replies": "undecodable replies",  # shared family
        "errors": "service refusals received",
        "give_ups": "requests abandoned at max_resends/timeout",
        "breaker_opens": "circuit breaker transitions to open",
        "breaker_short_circuits": "requests refused locally: breaker open",
        "breaker_probes": "half-open probe requests sent",
        "replica_breaker_opens": "per-endpoint breaker windows opened "
                                 "(balancer replies, keyed replica_id)",
    }

    #: per-endpoint breaker table bound: oldest-first eviction past
    #: this many distinct replica_id stamps
    MAX_REPLICA_BREAKERS = 64

    # -- pipelined API ---------------------------------------------------------

    def _send(self, msg: dict) -> int:
        """Encode + send one request; returns its req_id.  The payload
        rides behind a REQ-style EMPTY DELIMITER frame: the server (and
        any chaos proxy between) splits the envelope at the delimiter,
        so even a request whose METADATA frame is corrupted in flight
        keeps a routable envelope — the refusal reply still finds its
        way back instead of being silently unroutable."""
        from znicz_tpu.parallel import wire

        rid = next(self._ids)
        msg["req_id"] = rid
        # optional correlation key in the v3 metadata frame (ISSUE 5):
        # old servers ignore it, new ones echo it and tag their spans
        msg.setdefault("trace_id", f"{self._tag}-{rid}")
        # admission identity (ISSUE 6): keys the server's per-client
        # rate limit and fair subqueue, proxy-transparent
        msg.setdefault("client", self.client_id)
        payload, _ = wire.encode_message(msg)
        frames = [b""] + payload
        self._sock.send_multipart(frames, copy=False)
        now = time.perf_counter()
        self._pending[rid] = [frames, now, 0]
        self._obs_req[rid] = (msg.get("trace_id"), now)
        return rid

    # -- circuit breaker -------------------------------------------------------

    @property
    def breaker_state(self) -> str:
        """``closed`` / ``open`` / ``half_open`` (open flips to
        half_open lazily, at the first post-backoff submit)."""
        return self._breaker.state

    def _breaker_admit(self) -> None:
        """Submit-side gate: fail fast while open; after the backoff,
        let exactly ONE probe through (half-open) — the shared
        transport-core breaker (ISSUE 14)."""
        self._breaker.admit()

    def _replica_record(self, replica: str, ok: bool) -> None:
        """File one lb-stamped outcome into ``replica``'s own window
        (ISSUE 12).  Purely observational — the balancer routes around
        a sick replica; the client just must not open its whole-service
        breaker over it — so there is no admit gate or backoff, only
        state + an opens counter for the panel."""
        if not self._breaker.enabled:
            return
        win = self._brk_replicas.get(replica)
        if win is None:
            while len(self._brk_replicas) >= self.MAX_REPLICA_BREAKERS:
                evicted, _ = self._brk_replicas.popitem(last=False)
                self._brk_replica_open.pop(evicted, None)
            win = self._brk_replicas[replica] = collections.deque(
                maxlen=self._breaker.window)
        win.append(bool(ok))
        was_open = self._brk_replica_open.get(replica, False)
        now_open = (len(win) >= self._breaker.threshold
                    and win.count(False) >= self._breaker.threshold)
        self._brk_replica_open[replica] = now_open
        if now_open and not was_open:
            self._m["replica_breaker_opens"].inc()

    def breaker_state_for(self, replica: str) -> str:
        """``open``/``closed`` of one replica's per-endpoint window."""
        return "open" if self._brk_replica_open.get(replica, False) \
            else "closed"

    def replica_breakers(self) -> Dict[str, Dict]:
        """Panel snapshot: per-replica window state behind a balancer."""
        return {r: {"state": "open" if self._brk_replica_open.get(r)
                    else "closed",
                    "failures": win.count(False), "window": len(win)}
                for r, win in self._brk_replicas.items()}

    def _breaker_record(self, rid, ok: bool) -> None:
        """File one request OUTCOME.  Breaker failures are service-
        health signals only: give-ups and shed/bad-frame refusals —
        never per-client refusals (module docstring)."""
        self._breaker.record(rid, ok)

    def submit(self, x: np.ndarray,
               deadline_s: Optional[float] = None) -> int:
        """Send one inference request; returns its ``req_id``.
        ``deadline_s`` overrides the client's default budget for this
        request (<= 0: ship no deadline — the server's TTL governs).
        Raises :class:`CircuitOpenError` without touching the wire
        while the breaker is open."""
        self._breaker_admit()
        msg = {"cmd": "infer", "x": np.ascontiguousarray(x)}
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        if budget > 0:
            msg["deadline_ms"] = budget * 1e3
        try:
            rid = self._send(msg)
        except Exception:
            # no probe ever hit the wire: the admit() reservation must
            # not stay wedged
            self._breaker.release_probe()
            raise
        self._breaker.arm_probe(rid)
        return rid

    def _command(self, cmd: str, timeout: Optional[float] = None) -> dict:
        return self.result(self._send({"cmd": cmd}), timeout=timeout)

    def ping(self, timeout: Optional[float] = None) -> dict:
        return self._command("ping", timeout)

    def stats(self, timeout: Optional[float] = None) -> dict:
        """The server's live stats() dict (the serving panel payload)."""
        return self._command("stats", timeout)["stats"]

    def swap(self, path: str, timeout: Optional[float] = None) -> dict:
        """Trigger a zero-downtime snapshot rollover (ISSUE 6); the
        reply acknowledges the START (``swap_started`` + the still-live
        generation) — poll ``stats()["generation"]`` for the flip.
        Control command: bypasses the breaker, like ping/stats."""
        return self.result(self._send({"cmd": "swap", "path": path}),
                           timeout=timeout)

    def _pump(self, wait_s: float) -> None:
        """Receive every reply available (waiting up to ``wait_s`` for
        the first) and file each under its req_id; undecodable stacks
        are counted and dropped (the resend timer recovers the
        request)."""
        import zmq

        from znicz_tpu.parallel import wire

        if not self._sock.poll(max(0, int(wait_s * 1000))):
            return
        while True:
            try:
                raw = self._sock.recv_multipart(zmq.NOBLOCK)
            except zmq.Again:
                return
            try:
                # strip the delimiter the request's envelope carried
                _, payload = wire.split_envelope(raw)
                rep, _ = wire.decode_message(payload or raw)
                if not isinstance(rep, dict):
                    raise wire.WireError(
                        f"reply decodes to {type(rep).__name__}")
            except Exception:
                self._m["bad_replies"].inc()
                continue
            rid = rep.get("req_id")
            if rep.get("partial"):
                # streamed generation token (ISSUE 16): progress, not
                # the answer — refresh the resend timer (the service is
                # plainly alive and working THIS request; re-shipping
                # the prompt would only burn dedup work) and hand the
                # token to the caller's callback
                entry = self._pending.get(rid)
                if entry is not None:
                    entry[1] = time.perf_counter()
                    entry[2] = 0
                    cb = self._on_token.get(rid)
                    # dedup heartbeats carry no token — timer-only
                    if cb is not None and "token" in rep:
                        cb(rep.get("token"), rep.get("i"))
                continue
            if rid in self._pending:
                del self._pending[rid]
                self._on_token.pop(rid, None)
                self._results[rid] = rep
                self._note_reply(rid, rep)
                # breaker outcome: ok replies and PER-CLIENT refusals
                # count as healthy; only a SERVICE-scoped shed (global
                # queue at bound) means the service itself is
                # overloaded — a client-scoped shed (this caller's own
                # fair-share bound) is the caller's problem (module
                # docstring)
                # breaker failures: service-scoped sheds and the
                # balancer's terminal failover give-up (every replica
                # tried and none answered — the fleet is unservable,
                # exactly what fail-fast exists for); everything else —
                # ok replies and per-client refusals — is healthy
                ok = bool(rep.get("ok")) or not (
                    (rep.get("policy") == "shed"
                     and rep.get("scope") != "client")
                    or rep.get("policy") == "failover")
                replica = rep.get("replica_id")
                if rep.get("lb") and isinstance(replica, str) \
                        and rid != self._breaker.probe:
                    # balancer reply: a FAILURE belongs to the stamped
                    # replica's window, never the whole-service breaker
                    # (module docstring; the half-open probe is exempt —
                    # its whole purpose is service reachability).
                    # Successes ALSO feed the service window: without
                    # them it would hold only unstamped failures
                    # (give-ups, bad frames) and a trickle of those over
                    # hours would open the breaker against a healthy
                    # fleet that answers everything else fine.
                    self._replica_record(replica, ok)
                    if ok:
                        self._breaker_record(rid, True)
                else:
                    self._breaker_record(rid, ok)
            elif rep.get("bad_frame"):
                # the service could not decode one of OUR requests
                # (corrupted in flight): a service-path failure for the
                # breaker window.  The refusal carries no req_id, so it
                # clears no pending entry — the resend timer re-ships
                # the same bytes
                self._breaker_record(None, False)
            # else: duplicate (our resend raced the original) — dropped

    def _note_reply(self, rid, rep: dict) -> None:
        """Close out one request's client-side observability (ISSUE
        20): a ``client/request`` span covering submit→reply, plus
        ingestion of the server-side span summary the reply may carry —
        the caller's process (often the fleet coordinator's) gets the
        remote half of the stitched timeline for free."""
        tid, t0 = self._obs_req.pop(rid, (None, None))
        if not self._tracer.enabled:
            return
        if tid is not None and t0 is not None:
            self._tracer.add("client", "request", t0,
                             time.perf_counter() - t0,
                             {"trace_id": tid, "req_id": rid,
                              "ok": bool(rep.get("ok"))})
        if rep.get("spans") and rep.get("origin"):
            from znicz_tpu import telemetry

            telemetry.fleet_trace().ingest(str(rep["origin"]),
                                           rep["spans"])

    def _maybe_resend(self) -> None:
        now = time.perf_counter()
        for rid, entry in list(self._pending.items()):
            frames, t_sent, n = entry
            if now - t_sent < self.resend_after_s:
                continue
            if n >= self.max_resends:
                # capped resend loop (ISSUE 6 satellite): abandon the
                # request with a counted, readable give-up — the master
                # client's connect_retries fail-fast, mirrored.  Filed
                # as the request's OWN (synthetic) reply, not raised:
                # this runs inside whatever result()/collect() call
                # happened to be pumping, and raising here would
                # misattribute request A's death to a caller waiting
                # on request B (and silently lose A's outcome)
                del self._pending[rid]
                self._on_token.pop(rid, None)
                self._m["give_ups"].inc()
                self._breaker_record(rid, False)
                self._results[rid] = {
                    "ok": False, "gave_up": True, "req_id": rid,
                    "error": f"req {rid}: no reply after {n} resends "
                             f"over {now - t_sent + n * self.resend_after_s:.1f}s "
                             f"— giving up (max_resends="
                             f"{self.max_resends}); service at "
                             f"{self.endpoint} unreachable?"}
                self._note_reply(rid, self._results[rid])
                continue
            # the SAME encoded frames: bytes, not re-serialization
            self._sock.send_multipart(frames, copy=False)
            entry[1] = now
            entry[2] = n + 1
            self._m["resends"].inc()

    def result(self, req_id: int, timeout: Optional[float] = None) -> dict:
        """Block until ``req_id``'s reply lands (resending past the
        resend timer); raises :class:`InferenceError` on a service
        refusal, TimeoutError when the service never answers."""
        deadline = time.perf_counter() + (self.timeout if timeout is None
                                          else float(timeout))
        while req_id not in self._results:
            if time.perf_counter() > deadline:
                self._pending.pop(req_id, None)
                self._obs_req.pop(req_id, None)
                self._m["give_ups"].inc()
                self._breaker_record(req_id, False)
                raise TimeoutError(f"req {req_id}: no reply within "
                                   f"{self.timeout:g}s")
            self._pump(0.05)
            self._maybe_resend()
        rep = self._results.pop(req_id)
        if rep.get("gave_up"):
            # THIS request's capped-resend give-up (synthetic reply
            # from _maybe_resend) — still a timeout to the caller
            raise TimeoutError(str(rep.get("error")))
        if not rep.get("ok"):
            self._m["errors"].inc()
            raise InferenceError(rep)
        return rep

    def collect(self, wait_s: float = 0.0) -> List[dict]:
        """Drain whatever replies are available right now (offered-load
        driver); refusal replies are returned, not raised."""
        self._pump(wait_s)
        self._maybe_resend()
        out = list(self._results.values())
        self._results.clear()
        return out

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    # -- synchronous API -------------------------------------------------------

    def infer(self, x: np.ndarray, timeout: Optional[float] = None,
              deadline_s: Optional[float] = None) -> np.ndarray:
        """One request, one result: the (n, *out) result rows for the
        (n, *sample) input (a bare sample comes back with its leading
        1-row axis)."""
        return self.result(self.submit(x, deadline_s=deadline_s),
                           timeout=timeout)["y"]

    # -- generation (ISSUE 16) -------------------------------------------------

    def submit_generate(self, prompt: np.ndarray, max_new_tokens: int,
                        temperature: float = 0.0, top_k: int = 0,
                        seed: Optional[int] = None, stream: bool = False,
                        return_logits: bool = False,
                        return_logprobs: bool = False,
                        deadline_s: Optional[float] = None,
                        on_token=None) -> int:
        """Send one ``generate`` request (pipelined form); returns its
        ``req_id``.  With ``stream=True`` the service ships every
        decoded token as it lands and ``on_token(token, i)`` fires from
        whichever pump happens to be draining — the final reply (the
        whole token array) still arrives through ``result()``.
        ``return_logprobs`` asks for each emitted token's log-
        probability (a (max_new_tokens,) float32 array in the final
        reply — token-sized, unlike ``return_logits``).  Ship a
        ``seed`` with ``temperature > 0`` if a resend must reproduce
        the same stream (sampling is seeded per sequence)."""
        self._breaker_admit()
        msg = {"cmd": "generate",
               "x": np.ascontiguousarray(np.asarray(prompt).reshape(-1)),
               "max_new_tokens": int(max_new_tokens)}
        if temperature:
            msg["temperature"] = float(temperature)
        if top_k:
            msg["top_k"] = int(top_k)
        if seed is not None:
            msg["seed"] = int(seed)
        if stream:
            msg["stream"] = True
        if return_logits:
            msg["return_logits"] = True
        if return_logprobs:
            msg["return_logprobs"] = True
        budget = self.deadline_s if deadline_s is None else float(deadline_s)
        if budget > 0:
            msg["deadline_ms"] = budget * 1e3
        try:
            rid = self._send(msg)
        except Exception:
            self._breaker.release_probe()
            raise
        self._breaker.arm_probe(rid)
        if on_token is not None:
            self._on_token[rid] = on_token
        return rid

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: Optional[int] = None, stream: bool = False,
                 return_logits: bool = False,
                 return_logprobs: bool = False,
                 timeout: Optional[float] = None,
                 deadline_s: Optional[float] = None, on_token=None) -> dict:
        """One generation, synchronously: the final reply dict —
        ``tokens`` (the (max_new_tokens,) int32 stream), ``gen`` (the
        snapshot generation that produced them), ``prompt_len``, plus
        ``logits`` / ``logprobs`` when requested.  Size ``timeout`` to
        the whole generation, not one token."""
        return self.result(
            self.submit_generate(prompt, max_new_tokens,
                                 temperature=temperature, top_k=top_k,
                                 seed=seed, stream=stream,
                                 return_logits=return_logits,
                                 return_logprobs=return_logprobs,
                                 deadline_s=deadline_s,
                                 on_token=on_token),
            timeout=timeout)

    def close(self) -> None:
        self._sock.close(0)


for _name, _help in InferenceClient.COUNTERS.items():
    setattr(InferenceClient, _name, registered_property(_name, _help))
del _name, _help
