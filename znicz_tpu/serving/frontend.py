"""Inference service frontend: ZMQ ROUTER + dynamic batcher + model
runner (ISSUE 4).

Transport is the SAME wire-v3 codec the master/slave stack speaks
(parallel/wire.py): every request/reply is multipart — one metadata
frame plus one raw zero-copy buffer frame per tensor — so request
payloads and result tensors never pass through pickle.  Clients connect
DEALER sockets (many requests in flight, no REQ lockstep); the ROUTER
envelope is carried through the batcher untouched and prepended to the
reply, so replies route regardless of arrival order.

Threading:

  - the ROUTER thread owns the socket AND the codec: it decodes
    requests, enqueues them on the batcher, answers control commands
    (``ping``/``stats``) inline, refuses undecodable frames
    (``bad_frames`` — the master's fault model extends to serving), and
    drains the outbound reply queue;
  - ONE compute thread drives the donated ping-pong: it coalesces a
    batch, stages it (async H2D), dispatches the jitted forward
    (donating the staged buffer), then — while the device computes —
    coalesces AND stages the NEXT batch before materializing the
    result, so staging of batch N+1 overlaps compute of batch N (the
    ``loader/ingest.py`` overlap discipline).

Fault model (README "Serving" + "Serving robustness"): an undecodable
or corrupted request frame is refused with an error reply and counted,
never fatal; every ADMISSION refusal (shed / oversized / rate_limited /
deadline) is answered with a readable reason AND the ``policy`` slug
that refused it; a request whose deadline (client-shipped budget, else
``request_ttl_s``) passes is answered ``timed_out`` at assemble time —
and a computed result that misses the deadline is dropped, never
shipped.  The service survives a ChaosProxy soak (tests/
test_serving.py) and swaps snapshots live (``swap`` control command /
SIGHUP) without losing a single accepted request.
"""

from __future__ import annotations

import logging
import math
import queue
import threading
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

from znicz_tpu.core.config import root

from znicz_tpu.telemetry.metrics import registered_property

from .batcher import (AdmissionPolicy, BucketLadder, DynamicBatcher,
                      GenerationScheduler, GenSeq, Request)
from .model import ModelRunner

#: serving config home: ``root.common.serving.*`` (CLI dotted overrides
#: reach it like every other knob).  EVERY ``root.common.serving.*``
#: key the codebase reads must appear here — tests/
#: test_no_adhoc_counters.py lints for silently-ignored config.
DEFAULTS = {"max_batch": 32, "max_delay_ms": 5.0, "queue_bound": 256,
            "request_ttl_s": 5.0, "max_requests": None, "web_port": None,
            # variable-length workloads (ISSUE 15): with max_len > 0 the
            # bucket ladder grows a SECOND (sequence) axis — requests of
            # any length 1..max_len are padded up to power-of-two seq
            # rungs (or the explicit ``rungs`` list, which must end at
            # max_len), coalesced only with same-rung neighbors, and
            # replies are sliced back to each request's own length.
            # Importing a sequence sample (charlm) defaults max_len to
            # its trained window.
            "seq": {"max_len": 0, "rungs": None},
            # generation serving (ISSUE 16, paged in ISSUE 19):
            # prefill/decode split over a block-paged KV pool with
            # continuous batching, prefix reuse, and fused sampling.
            # Off by default — scoring-only services pay nothing.
            # With enabled=True: ``max_new_tokens`` caps any one
            # request's decode budget, ``page_size`` sets the KV page
            # (tokens per page — the sharing/COW granularity),
            # ``num_pages`` sizes the pool (0 = auto: slots x pages
            # per full context), ``prefill_chunk`` bounds the prompt
            # tokens one tick may prefill per request (the inter-token
            # p99 shield; defaults to page_size, which also makes
            # prefix hits bit-exact vs cold prefills),
            # ``prefix_cache`` arms content-addressed prefix-page
            # sharing, ``on_device_sampling`` ships (b,) sampled
            # tokens per tick instead of (b, vocab) logits, ``slots``
            # bounds concurrent generations, ``decode_tick_ms`` paces
            # the decode cadence (0 = free-running), and
            # ``pending_bound`` sheds prompt arrivals past it
            "generate": {"enabled": False, "max_new_tokens": 256,
                         "page_size": 16, "num_pages": 0,
                         "prefill_chunk": 0, "prefix_cache": True,
                         "on_device_sampling": True, "slots": 8,
                         "decode_tick_ms": 0.0, "pending_bound": 64},
            # serving mesh (ISSUE 13; serving/model.py reads it through
            # a local alias): NamedSharding axis sizes — requests split
            # over ``data``, wide FC tails column-shard over ``model``.
            # 1x1 = the single-device path, bit-exact
            "mesh": {"data": 1, "model": 1},
            # AOT executable cache (ISSUE 17; serving/aot_cache.py): with
            # enabled=True warmed executables are serialized into a
            # content-addressed cache next to the snapshot (``dir``
            # overrides the location) and a restarted replica LOADS its
            # whole family instead of compiling it — the zero-cold-start
            # lever bench.py --elastic gates (>= 3x faster boot-to-
            # /readyz on this host).  Off by default: long-lived
            # replicas pay nothing
            "aot_cache": {"enabled": False, "dir": ""},
            # fleet observability (ISSUE 20; read through a local alias
            # like the admission subtree): slow-request exemplar window
            # (the N slowest requests with their span breakdown on
            # /status.json), the heartbeat metrics-snapshot cadence
            # (every Nth beat carries the full registry snapshot), and
            # the serving-plane SLO objectives — ADVISORY burn rates on
            # /slo.json and a new /readyz field, never a gate flip
            "obs": {"exemplars": 8, "exemplar_window_s": 60.0,
                    "metrics_every_beats": 8,
                    "slo_availability": 0.999, "slo_p99_ms": 250.0,
                    "slo_ttft_ms": 500.0, "slo_inter_token_ms": 100.0,
                    "slo_fast_window_s": 60.0,
                    "slo_slow_window_s": 600.0},
            "admission": {"enabled": True, "rate_limit": 0.0,
                          "rate_burst": 0.0, "fair": True, "quantum": 0,
                          "client_queue_bound": 0},
            # replica-fleet balancer knobs (ISSUE 12; serving/
            # balancer.py reads them through a local alias, like the
            # admission subtree above): heartbeat cadence + TTL'd
            # membership, hedged-retry timing, exactly-once failover
            # budgets, and the canary-rollover verdict thresholds
            "balance": {"heartbeat_s": 0.25, "replica_ttl_s": 1.5,
                        "min_replicas": 1, "hedge": True,
                        "hedge_floor_s": 0.05, "hedge_cap_s": 2.0,
                        "hedge_p99_mult": 1.5,
                        "failover_timeout_s": 1.0, "failover_tries": 3,
                        "park_bound": 256, "canary_fraction": 0.34,
                        "canary_requests": 30, "canary_p99_mult": 3.0,
                        "canary_timeout_s": 30.0, "parity_every": 4,
                        "heal_backoff_s": 30.0,
                        # autoscaler (ISSUE 17; armed by ReplicaBalancer.
                        # enable_autoscale): a control loop over the
                        # per-replica capacity-weighted load — spawn when
                        # the fleet-mean (queue_depth + in_flight)/
                        # device_count sits above ``autoscale_high_load``
                        # (or requests park) for ``autoscale_up_after``
                        # consecutive evals, drain-then-retire the
                        # least-loaded SERVABLE replica when below
                        # ``autoscale_low_load`` for ``autoscale_down_
                        # after`` evals — hysteresis both ways, one
                        # action per ``autoscale_cooldown_s``, never
                        # below the ``min_replicas`` quorum, never past
                        # ``autoscale_max``
                        "autoscale": False, "autoscale_max": 8,
                        "autoscale_high_load": 4.0,
                        "autoscale_low_load": 0.5,
                        "autoscale_up_after": 2,
                        "autoscale_down_after": 8,
                        "autoscale_eval_s": 0.5,
                        "autoscale_cooldown_s": 5.0,
                        "autoscale_drain_timeout_s": 10.0,
                        "autoscale_boot_deadline_s": 60.0}}


def _cfg(name: str, override):
    if override is not None:
        return override
    return root.common.serving.get(name, DEFAULTS[name])


def _admission_from_config() -> AdmissionPolicy:
    # the admission subtree is bound to a local alias: znicz-lint's
    # config-knob checker (znicz_tpu/analysis/config_knob.py) resolves
    # every .get() read THROUGH the alias against the DEFAULTS table,
    # so the old "spell the literal chain at each read site" workaround
    # (the regex lint was blind to aliasing) is retired
    d = DEFAULTS["admission"]
    adm = root.common.serving.admission
    return AdmissionPolicy(
        rate_limit=float(adm.get("rate_limit", d["rate_limit"])),
        rate_burst=float(adm.get("rate_burst", d["rate_burst"])),
        fair=bool(adm.get("fair", d["fair"])),
        quantum=int(adm.get("quantum", d["quantum"])),
        client_queue_bound=int(adm.get("client_queue_bound",
                                       d["client_queue_bound"])),
        enabled=bool(adm.get("enabled", d["enabled"])))


class InferenceServer:
    """Serve a workflow's frozen forward over ZMQ.

    ``bind`` may use a wildcard port (``tcp://127.0.0.1:*``); the
    resolved address is in ``endpoint`` once serving starts.  Drive
    blocking (``serve()``) or on a background thread (``start()`` /
    ``stop()``).  ``max_requests`` makes serve() return after answering
    that many inference requests (bench/launcher tests)."""

    def __init__(self, workflow, bind: str = "tcp://127.0.0.1:*",
                 snapshot: str = "", max_batch: Optional[int] = None,
                 max_delay_ms: Optional[float] = None,
                 queue_bound: Optional[int] = None,
                 request_ttl_s: Optional[float] = None,
                 ladder: Optional[BucketLadder] = None,
                 max_requests: Optional[int] = None,
                 admission: Optional[AdmissionPolicy] = None,
                 warmup: bool = True,
                 announce: Optional[str] = None,
                 replica_id: Optional[str] = None):
        import uuid

        from znicz_tpu.parallel import wire

        self.bind = bind
        #: fleet membership (ISSUE 12): when set, the router thread
        #: heartbeats this balancer endpoint with readiness + queue
        #: depth + per-bucket p99 piggybacked, and every reply carries
        #: the ``replica_id`` stamp the client's per-endpoint breaker
        #: keys on
        self.announce = announce
        self.replica_id = replica_id or f"replica-{uuid.uuid4().hex[:6]}"
        self.endpoint: Optional[str] = None      # resolved at serve()
        self.runner = ModelRunner(workflow, snapshot=snapshot)
        max_batch = int(_cfg("max_batch", max_batch))
        # mesh-aware ladder (ISSUE 13): default rungs snap to multiples
        # of the data-axis size so every batch splits evenly; an
        # explicit ladder that cannot split is refused HERE, readably,
        # not as an XLA sharding error at the first request
        dp = self.runner.data_parallel
        # 2-D seq ladder config (ISSUE 15; read through a local alias
        # like the admission subtree, so the config-knob lint resolves
        # the keys against DEFAULTS)
        d_seq = DEFAULTS["seq"]
        sq = root.common.serving.seq
        # a sequence workflow DECLARES its serving window
        # (workflow.serving_seq_len — charlm sets it to the trained
        # seq_len); explicit root.common.serving.seq.max_len config
        # wins, including an explicit 0 to force fixed-shape serving
        declared = int(getattr(workflow, "serving_seq_len", 0) or 0)
        seq_max_len = int(sq.get("max_len",
                                 declared or d_seq["max_len"]) or 0)
        seq_rungs = sq.get("rungs", d_seq["rungs"])
        if ladder is None:
            ladder = BucketLadder(max_batch, dp=dp, max_len=seq_max_len,
                                  seq_rungs=seq_rungs)
        elif dp > 1 and ladder.dp != dp:
            # re-validate an explicit ladder against THIS runner's mesh
            # through the one home of the divisibility check/message
            ladder = BucketLadder(ladder.max_batch, ladder.rungs, dp=dp,
                                  max_len=ladder.max_len,
                                  seq_rungs=ladder.seq_rungs)
        #: variable-length mode: requests carry (n, len, *tail) arrays,
        #: len <= seq_max_len; the trained sample shape's axis 0 is the
        #: max sequence length
        self.seq_max_len = ladder.max_len or None
        #: resolved lazily by _resolve_seq_out(): True when the model's
        #: output carries the SEQ axis (replies sliced to each
        #: request's own length), False for seq-reducing heads
        self._seq_out: Optional[bool] = None
        if self.seq_max_len is not None:
            trained = int(self.runner.sample_shape[0]) \
                if self.runner.sample_shape else 0
            if trained and self.seq_max_len > trained:
                raise ValueError(
                    f"root.common.serving.seq.max_len={self.seq_max_len} "
                    f"exceeds the model's trained sequence length "
                    f"{trained} (positions past the trained window "
                    f"have no embedding)")
            # the masked-parity contract rides the CAUSAL mask: a real
            # position never attends its row's padded tail.  A
            # non-causal attention unit would hand PAD keys softmax
            # mass and make replies a function of the co-batched rung
            # — refuse at startup, not as silently-wrong answers
            from znicz_tpu.attention import MultiHeadAttention

            non_causal = [f.name for f in workflow.forwards
                          if isinstance(f, MultiHeadAttention)
                          and not f.causal]
            if non_causal:
                raise ValueError(
                    f"variable-length serving needs causal attention, "
                    f"but unit(s) {non_causal} attend bidirectionally — "
                    f"padded tails would leak probability mass into "
                    f"real positions.  Make the unit causal (mask pad "
                    f"keys via ops.attention k_valid in a custom "
                    f"apply), or serve fixed-shape "
                    f"(root.common.serving.seq.max_len=0)")
        self.batcher = DynamicBatcher(
            max_batch=max_batch,
            max_delay_ms=float(_cfg("max_delay_ms", max_delay_ms)),
            queue_bound=int(_cfg("queue_bound", queue_bound)),
            ladder=ladder,
            admission=admission or _admission_from_config())
        self.request_ttl_s = float(_cfg("request_ttl_s", request_ttl_s))
        # generation serving (ISSUE 16, paged in ISSUE 19; knobs read
        # through a local alias like the admission subtree): a paged
        # GenerationRunner (block-paged KV pool + prefix cache +
        # chunked-prefill/decode executables with fused sampling)
        # under a continuous-batching scheduler, driven by the SAME
        # compute thread
        d_gen = DEFAULTS["generate"]
        gn = root.common.serving.generate
        self.gen_sched: Optional[GenerationScheduler] = None
        if bool(gn.get("enabled", d_gen["enabled"])):
            if self.seq_max_len is None:
                raise ValueError(
                    "generation serving rides the variable-length "
                    "plane (the context window IS the seq window) — "
                    "set root.common.serving.seq.max_len alongside "
                    "root.common.serving.generate.enabled")
            page_size = int(gn.get("page_size", d_gen["page_size"]))
            slots = int(gn.get("slots", d_gen["slots"]))
            num_pages = int(gn.get("num_pages", d_gen["num_pages"]))
            if num_pages <= 0:
                # auto pool: every slot can hold one full context —
                # admission (slots) and allocation can't deadlock
                num_pages = slots * (-(-self.seq_max_len // page_size))
            chunk = int(gn.get("prefill_chunk", d_gen["prefill_chunk"]))
            if chunk <= 0:
                # chunk == page_size keeps prefill grids aligned with
                # page boundaries — prefix hits replay the exact
                # executables a cold prefill runs (bit-exact reuse)
                chunk = page_size
            gr = self.runner.enable_generation(
                page_size=page_size, num_pages=num_pages, slots=slots,
                prefill_chunk=chunk,
                prefix_cache=bool(gn.get("prefix_cache",
                                         d_gen["prefix_cache"])))
            self.gen_sched = GenerationScheduler(
                gr,
                max_new_cap=int(gn.get("max_new_tokens",
                                       d_gen["max_new_tokens"])),
                pending_bound=int(gn.get("pending_bound",
                                         d_gen["pending_bound"])),
                decode_tick_ms=float(gn.get("decode_tick_ms",
                                            d_gen["decode_tick_ms"])),
                on_device_sampling=bool(
                    gn.get("on_device_sampling",
                           d_gen["on_device_sampling"])),
                replica_id=self.replica_id)
        self.max_requests = max_requests
        self._warmup = warmup
        # AOT executable cache (ISSUE 17; read through a local alias
        # like the admission subtree): resolved here, armed at serve()
        # right before warmup so a bad directory fails start() readably
        d_aot = DEFAULTS["aot_cache"]
        aot = root.common.serving.aot_cache
        self._aot_enabled = bool(aot.get("enabled", d_aot["enabled"]))
        self._aot_dir = str(aot.get("dir", d_aot["dir"]) or "")
        #: the boot-time warm proof (ModelRunner.warm_proof) recorded
        #: once warmup finished — in AOT mode /readyz GATES on it
        self.warm_report: Optional[Dict] = None
        self.boot_to_ready_s: Optional[float] = None
        self.codec = wire.Codec(owner="serving")    # router-thread only
        # -- telemetry (ISSUE 5): serving counters + the request-latency
        # ring histogram live in the registry (component="serving");
        # the class-level properties preserve the historical names
        from znicz_tpu import telemetry

        _sc = telemetry.scope("serving")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        # boot-to-/readyz distribution (ISSUE 17): cold compiles vs
        # cache-warm loads land in visibly different buckets here —
        # the fleet's elasticity latency on /metrics
        self._m_boot = telemetry.scope("warmup").histogram(
            "warmup_boot_to_ready_seconds",
            "serve() entry -> /readyz true (warmup included)", size=64)
        self._m_latency = _sc.histogram(
            "request_latency_seconds",
            "e2e request latency (enqueue -> reply handoff)", size=8192)
        # per-rung latency rings (ISSUE 12): the heartbeat's
        # p99-by-bucket payload — what the balancer's least-loaded
        # dispatch and hedge-delay derivation feed on
        self._m_lat_bucket = {
            r: _sc.histogram("bucket_latency_seconds",
                             "request latency per ladder rung "
                             "(enqueue -> compute done)", size=512,
                             bucket=str(r))
            for r in self.batcher.ladder}
        d_bal = DEFAULTS["balance"]
        bal = root.common.serving.balance
        self.heartbeat_s = float(bal.get("heartbeat_s",
                                         d_bal["heartbeat_s"]))
        self._tracer = telemetry.tracer()
        # -- fleet observability (ISSUE 20; knobs read through a local
        # alias like the admission subtree): this replica's fleet
        # identity, the span exporter the heartbeat/reply carriers
        # drain, the slow-request exemplar window, and the serving SLO
        # tracker (advisory burn rates — /readyz reports, never gates)
        d_obs = DEFAULTS["obs"]
        obs = root.common.serving.obs
        telemetry.set_identity(self.replica_id)
        self._exporter = telemetry.exporter()
        self._exemplar_cap = int(obs.get("exemplars", d_obs["exemplars"]))
        self._exemplar_window_s = float(obs.get(
            "exemplar_window_s", d_obs["exemplar_window_s"]))
        self._metrics_every = max(1, int(obs.get(
            "metrics_every_beats", d_obs["metrics_every_beats"])))
        self._exemplars: List[Dict] = []    # N slowest, newest window
        self._exemplar_lock = threading.Lock()
        self._hb_beats = 0
        self._hb_ev_seq = 0                 # journal piggyback cursor
        self.slo = telemetry.register_slo(telemetry.SloTracker(
            "serving",
            window_fast_s=float(obs.get("slo_fast_window_s",
                                        d_obs["slo_fast_window_s"])),
            window_slow_s=float(obs.get("slo_slow_window_s",
                                        d_obs["slo_slow_window_s"]))))
        self.slo.add_objective(
            "availability",
            target=float(obs.get("slo_availability",
                                 d_obs["slo_availability"])))
        self.slo.add_objective(
            "latency_p99", target=0.99, unit="s",
            threshold=float(obs.get("slo_p99_ms",
                                    d_obs["slo_p99_ms"])) / 1e3)
        self.slo.add_objective(
            "ttft", target=0.99, unit="s",
            threshold=float(obs.get("slo_ttft_ms",
                                    d_obs["slo_ttft_ms"])) / 1e3)
        self.slo.add_objective(
            "inter_token", target=0.99, unit="s",
            threshold=float(obs.get("slo_inter_token_ms",
                                    d_obs["slo_inter_token_ms"])) / 1e3)
        self.started_at: Optional[float] = None
        #: optional FaultSchedule for the router loop's built-in
        #: ingress fault hook (ISSUE 14 cross-plane soak); the live
        #: TransportLoop sits on ``_transport`` while serving
        self.transport_chaos = None
        self._transport = None
        self._outbound: "queue.Queue" = queue.Queue()
        self._wake_addr: Optional[str] = None    # set at serve() bind
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._serve_error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._compute_thread: Optional[threading.Thread] = None
        self._swap_thread: Optional[threading.Thread] = None
        self._swap_gate = threading.Lock()  # one swap_async admit at a time
        self.log = logging.getLogger("znicz.serving")

    # -- counters shorthand ----------------------------------------------------

    #: serving counters registered under component="serving" (ISSUE 5):
    #: name -> HELP text
    COUNTERS = {
        "requests_in": "decoded infer/generate requests",
        "served": "answered with a result",
        "timed_out": "answered timed_out (deadline/TTL)",
        "rejected": "answered shed/oversized/rate_limited",
        "expired_results": "computed results dropped: deadline passed "
                           "post-compute",
        "serve_errors": "fatal serve-loop failures surfaced to start()",
        "heartbeats_out": "balancer heartbeats sent (fleet membership)",
    }

    # (the historical attribute properties are generated from COUNTERS
    # right after the class body)

    @property
    def bad_frames(self) -> int:
        return self.codec.bad_frames

    def qps(self) -> Optional[float]:
        if self.started_at is None or not self.served:
            return None
        return self.served / max(time.perf_counter() - self.started_at,
                                 1e-9)

    def latency_quantiles(self) -> Dict[str, Optional[float]]:
        lat = self._m_latency.window()      # the last <=8192 requests
        if lat.size == 0:
            return {"p50_ms": None, "p99_ms": None, "mean_ms": None}
        a = lat * 1e3
        return {"p50_ms": round(float(np.percentile(a, 50)), 3),
                "p99_ms": round(float(np.percentile(a, 99)), 3),
                "mean_ms": round(float(np.mean(a)), 3)}

    def p99_ms_by_bucket(self) -> Dict[int, float]:
        """``{ladder rung: p99 ms}`` over each rung's recent window —
        the telemetry the replica piggybacks on every heartbeat."""
        out: Dict[int, float] = {}
        for rung, hist in self._m_lat_bucket.items():
            w = hist.window()
            if w.size:
                out[rung] = round(float(np.percentile(w, 99)) * 1e3, 3)
        return out

    def heartbeat_payload(self) -> Dict:
        """One heartbeat message (ISSUE 12): membership identity plus
        the piggybacked ``/readyz`` state, queue depth and per-bucket
        p99 the balancer's least-loaded dispatch keys on.

        Fleet observability (ISSUE 20) rides the same beat: a bounded
        batch of exported spans and fresh journal events on EVERY beat,
        the full registry snapshot every ``metrics_every_beats``-th —
        the balancer merges all three into the fleet plane.  The extra
        keys are additive; a pre-ISSUE-20 balancer ignores them."""
        from znicz_tpu import telemetry

        hb = self._heartbeat_base()
        hb["origin"] = telemetry.identity()
        spans = self._exporter.drain(telemetry.span_export_batch())
        if spans:
            hb["spans"] = spans
        ev = telemetry.journal().since(self._hb_ev_seq,
                                       limit=telemetry.span_export_batch())
        if ev:
            self._hb_ev_seq = ev[-1]["seq"]
            hb["events"] = ev
        self._hb_beats += 1
        if self._hb_beats % self._metrics_every == 1 \
                or self._metrics_every == 1:
            hb["metrics"] = telemetry.registry_snapshot(
                telemetry.registry())
        return hb

    def _heartbeat_base(self) -> Dict:
        return {"cmd": "heartbeat",
                "replica_id": self.replica_id,
                "endpoint": self.endpoint,
                "ready": self.ready(),
                "draining": self.draining,
                "swapping": self.runner.swapping,
                "gen": self.runner.generation,
                "snapshot_path": self.runner.snapshot_path,
                "queue_depth": self.batcher.queue_depth,
                "served": self.served,
                # capacity (ISSUE 13): the balancer normalizes its
                # least-loaded score by device_count so a 1-chip and an
                # 8-chip replica stop drawing equal traffic
                "device_count": self.runner.device_count,
                "mesh": self.runner.mesh_shape,
                # warmup provenance (ISSUE 17): the fleet panel's warm
                # columns + the autoscaler's boot visibility
                "warm_source": self.runner.warm_source,
                "warm_hits": int(self.runner._warm["hits"]),
                "warm_misses": int(self.runner._warm["misses"]),
                "boot_s": self.boot_to_ready_s,
                "p99_ms_by_bucket": self.p99_ms_by_bucket()}

    def _note_request(self, ok: bool, latency_s: float, req_id,
                      trace_id, bucket=None, kind: str = "infer",
                      breakdown: Optional[Dict] = None) -> None:
        """Feed one finished request into the SLO tracker and (when it
        ranks) the slow-request exemplar window (ISSUE 20).  The span
        peek runs ONLY for requests slow enough to keep — the hot loop
        pays one lock + one float compare."""
        self.slo.record("availability", ok)
        self.slo.record_latency("latency_p99", latency_s)
        latency_ms = round(latency_s * 1e3, 3)
        with self._exemplar_lock:
            now = time.time()
            horizon = now - self._exemplar_window_s
            self._exemplars = [e for e in self._exemplars
                               if e["t"] >= horizon]
            if len(self._exemplars) >= self._exemplar_cap \
                    and latency_ms <= self._exemplars[-1]["latency_ms"]:
                return
            ex = {"req_id": req_id, "trace_id": trace_id,
                  "latency_ms": latency_ms, "bucket": bucket,
                  "kind": kind, "ok": ok, "t": now}
            if breakdown:
                ex["breakdown_ms"] = dict(breakdown)
            if trace_id and self._tracer.enabled:
                spans = self._exporter.peek_trace(str(trace_id), limit=8)
                if spans:
                    ex["spans"] = [{"cat": s.get("cat"),
                                    "name": s.get("name"),
                                    "dur_ms": round(
                                        s.get("dur", 0) / 1e3, 3)}
                                   for s in spans]
            self._exemplars.append(ex)
            self._exemplars.sort(key=lambda e: -e["latency_ms"])
            del self._exemplars[self._exemplar_cap:]

    def slow_requests(self) -> List[Dict]:
        """The current exemplar window, slowest first (ISSUE 20
        satellite — the ``/status.json`` serving panel row)."""
        horizon = time.time() - self._exemplar_window_s
        with self._exemplar_lock:
            return [dict(e) for e in self._exemplars
                    if e["t"] >= horizon]

    def stats(self) -> Dict:
        """The serving panel / bench record, one dict."""
        out = {"endpoint": self.endpoint,
               "replica_id": self.replica_id,
               "requests_in": self.requests_in,
               "served": self.served,
               "rejected": self.rejected,
               "timed_out": self.timed_out,
               "expired_results": self.expired_results,
               "ready": self.ready(),
               "draining": self.draining,
               "generation": self.runner.generation,
               "bad_frames": self.codec.bad_frames,
               "bytes_in": self.codec.bytes_in,
               "bytes_out": self.codec.bytes_out,
               "qps": None if self.qps() is None
               else round(self.qps(), 2)}
        out.update(self.latency_quantiles())
        out["p99_ms_by_bucket"] = self.p99_ms_by_bucket()
        out["announce"] = self.announce
        out["heartbeats_out"] = self.heartbeats_out
        out["boot_to_ready_s"] = self.boot_to_ready_s
        out["warm_report"] = self.warm_report
        out["slow_requests"] = self.slow_requests()
        out["batcher"] = self.batcher.stats()
        out["model"] = self.runner.stats()
        if self.gen_sched is not None:
            out["generate"] = self.gen_sched.stats()
        return out

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "InferenceServer":
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="znicz-serve")
        self._thread.start()
        if not self._ready.wait(timeout=120):
            raise RuntimeError(f"inference server failed to come up on "
                               f"{self.bind} within 120s")
        if self._serve_error is not None:
            # bind conflict / bad snapshot / warmup failure: surface the
            # REAL cause immediately instead of a generic bind message
            raise RuntimeError(
                f"inference server failed on {self.bind}: "
                f"{self._serve_error!r}") from self._serve_error
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        """Block until a ``start()``ed server exits (``max_requests``
        reached, ``stop()`` called, or a fatal serve error)."""
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        self._stop.set()
        self.batcher.close()
        if self.gen_sched is not None:
            self.gen_sched.close()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    # -- health/readiness + rollover (ISSUE 6) ---------------------------------

    @property
    def draining(self) -> bool:
        """True once stop() (or a fatal serve error) began winding the
        service down — queued work still drains, new work is refused."""
        return self._stop.is_set()

    def alive(self) -> bool:
        """Liveness (the ``/healthz`` answer): the serve loop has not
        died on an error and its thread (when ``start()``-driven) is
        still running."""
        return self._serve_error is None and (
            self._thread is None or self._thread.is_alive())

    def ready(self) -> bool:
        """Readiness (the ``/readyz`` answer): up, not draining, and
        not mid-rollover — False exactly while warming or draining, the
        membership signal a replica tier's health checks need."""
        return (self._ready.is_set() and self._serve_error is None
                and not self._stop.is_set() and not self.runner.swapping)

    def swap_async(self, path: str) -> threading.Thread:
        """Start a zero-downtime snapshot rollover on a background
        thread (the wire ``swap`` command and the launcher's SIGHUP
        land here); serving continues on the old generation until the
        warmed flip.  Raises RuntimeError while another swap runs —
        atomically: the wire command (router thread) and SIGHUP (main
        thread) can race here, and a check-then-start race would ack
        both callers while one swap dies in the background."""
        with self._swap_gate:
            if (self._swap_thread is not None
                    and self._swap_thread.is_alive()):
                raise RuntimeError("swap already in progress")
            t = threading.Thread(target=self._swap, args=(path,),
                                 daemon=True, name="znicz-swap")
            self._swap_thread = t
            t.start()
        return t

    def _swap(self, path: str) -> None:
        try:
            meta = self.runner.swap(path, self.batcher.ladder)
            self.log.info("snapshot rollover -> generation %d (%s, "
                          "epoch %s)", self.runner.generation, path,
                          meta.get("epoch"))
        except Exception:
            # counted by the runner (swap_failures); the old generation
            # keeps serving — a broken snapshot must never take the
            # service down
            self.log.exception(
                "snapshot swap from %r failed; generation %d unchanged",
                path, self.runner.generation)

    # -- the ROUTER loop -------------------------------------------------------

    def serve(self) -> None:
        """Blocking serve; any failure (bind conflict, warmup compile
        error) is recorded for ``start()`` to re-raise with its real
        cause, and always unblocks a waiting ``start()``."""
        try:
            self._serve()
        except BaseException as exc:
            self._serve_error = exc
            self._m["serve_errors"].inc()
            raise
        finally:
            self._ready.set()

    def _serve(self) -> None:
        from znicz_tpu.transport import TransportLoop

        t_boot = time.perf_counter()    # boot-to-/readyz clock (ISSUE 17)
        loop = self._transport = TransportLoop(
            "serving", stop=self._stop, instance=self.replica_id)
        if self.transport_chaos is not None:
            loop.inject_faults(self.transport_chaos)
        sock = None
        state = {"next_hb": 0.0}
        try:
            sock = loop.bind_router(self.bind)
            self.endpoint = loop.resolved_endpoint(sock)
            # outbound wake-up: the compute thread pokes this inproc
            # pair when it enqueues replies, so a finished batch ships
            # on the NEXT poll wake instead of waiting out the poll
            # timeout (the reply tax was the whole sequential-baseline
            # RTT otherwise)
            self._wake_addr = f"inproc://znicz-serve-wake-{id(self)}"
            wake_r = loop.bind_pull(self._wake_addr)
            # fleet membership (ISSUE 12): a DEALER to the balancer,
            # owned by THIS router thread like the codec — heartbeats
            # ride the tick cadence, acks are drained and discarded
            hb = loop.connect_dealer(self.announce) if self.announce \
                else None
            if self._aot_enabled:
                # arm the AOT executable cache (ISSUE 17) BEFORE any
                # warmup dispatch: warmup then loads cached executables
                # where they exist and serializes the ones it compiles.
                # A jax build without serialize support degrades to
                # plain compile-every-boot (enable returns False)
                self.runner.enable_aot_cache(self._aot_dir)
            if self._warmup:
                # compile every rung BEFORE taking traffic: first-
                # request latency must not eat a compile, and the
                # zero-recompile gate needs its baseline
                self.runner.warmup(self.batcher.ladder)
            if self.seq_max_len is not None:
                # resolve the output-shape probe now (cache hits after
                # warmup), never on the compute thread mid-traffic
                self._resolve_seq_out()
            if self.gen_sched is not None and self._warmup:
                # the generation executable families (prefill x prompt
                # rungs, decode x cache rungs, migrations) compile
                # up-front too — the zero-recompile gate's baseline
                self.gen_sched.gen.warmup()
            if self._warmup:
                # the strict warm-family proof (ISSUE 17, the PR-15
                # jit-cache-equality discipline): in AOT mode /readyz
                # must NOT flip true on a partially loaded family —
                # raising here lands in _serve_error, so ready() stays
                # False and start() surfaces the real cause
                expected = len(self.batcher.ladder.buckets())
                if self.gen_sched is not None:
                    expected += self.gen_sched.gen.executables()
                self.warm_report = self.runner.warm_proof(expected)
                if self.runner.aot_enabled \
                        and not self.warm_report["ok"]:
                    raise RuntimeError(
                        f"AOT warmup proof failed — refusing to flip "
                        f"/readyz on a partial executable family: "
                        f"{self.warm_report}")
            self.started_at = time.perf_counter()
            self._compute_thread = threading.Thread(
                target=self._compute_loop, daemon=True,
                name="znicz-infer")
            self._compute_thread.start()
            loop.register(sock,
                          lambda frames: self._handle(sock, frames),
                          drain=True)
            loop.register(wake_r, lambda _token: None, drain=True)
            if hb is not None:
                loop.register(hb, lambda _ack: None, drain=True)

            def tick() -> None:
                if self.max_requests is not None and \
                        self.served + self.timed_out + self.rejected \
                        >= self.max_requests:
                    loop.stop()
                    return
                if hb is not None:
                    now = time.perf_counter()
                    if now >= state["next_hb"]:
                        state["next_hb"] = now + self.heartbeat_s
                        hb.send_multipart(
                            [b""] + self.codec.encode(
                                self.heartbeat_payload()), copy=False)
                        self._m["heartbeats_out"].inc()
                self._drain_outbound(sock)

            loop.add_tick(tick)
            self.boot_to_ready_s = time.perf_counter() - t_boot
            self._m_boot.observe(self.boot_to_ready_s)
            tick()                      # first heartbeat pre-poll
            self._ready.set()
            loop.run(poll_ms=5)
        finally:
            self._stop.set()
            self.batcher.close()
            if self.gen_sched is not None:
                self.gen_sched.close()
            if self._compute_thread is not None:
                self._compute_thread.join(timeout=30)
            if sock is not None:
                self._drain_outbound(sock)  # flush final replies
            loop.close()

    def _drain_outbound(self, sock) -> None:
        n = 0
        t0 = time.perf_counter()
        while True:
            try:
                envelope, rep, t_enqueued = self._outbound.get_nowait()
            except queue.Empty:
                break
            if t_enqueued is not None:
                self._m_latency.observe(time.perf_counter() - t_enqueued)
            # copy=False: result frames are memoryviews of arrays owned
            # by the reply dicts, never mutated after encode
            sock.send_multipart(
                list(envelope) + self.codec.encode(rep), copy=False)
            n += 1
        if n and self._tracer.enabled:
            self._tracer.add("serving", "reply", t0,
                             time.perf_counter() - t0, {"replies": n})

    def _handle(self, sock, frames: List[bytes]) -> None:
        from znicz_tpu.parallel import wire

        envelope, payload = wire.split_envelope(frames)
        if not envelope and frames:
            # a bare-DEALER peer whose metadata frame is garbage: no
            # delimiter, no magic — but this socket is a ROUTER, so the
            # FIRST frame is always the peer identity; peel it so the
            # refusal below stays routable
            envelope, payload = list(frames[:1]), list(frames[1:])
        try:
            req, _ = self.codec.decode(payload)
            if not isinstance(req, dict):
                raise wire.WireError(
                    f"decodes to {type(req).__name__}, not a request dict")
        except Exception as exc:
            self.log.warning("refused undecodable request (%d frames): %s "
                             "— bad_frames=%d", len(frames), exc,
                             self.codec.bad_frames + 1)
            sock.send_multipart(
                list(envelope)
                + self.codec.refusal(exc, legacy=False,
                                     replica_id=self.replica_id))
            return
        cmd = req.get("cmd")
        rid = req.get("req_id")
        if cmd == "ping":
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": True, "pong": True, "req_id": rid,
                 "replica_id": self.replica_id}))
            return
        if cmd == "stats":
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": True, "stats": self.stats(), "req_id": rid,
                 "replica_id": self.replica_id}))
            return
        if cmd == "swap":
            # zero-downtime rollover trigger (ISSUE 6): load+warm runs
            # on a background thread, this reply ships immediately; the
            # caller polls stats()["generation"] for completion
            path = req.get("path")
            if not isinstance(path, str) or not path:
                sock.send_multipart(list(envelope) + self.codec.encode(
                    {"ok": False, "req_id": rid,
                     "replica_id": self.replica_id,
                     "error": "swap needs a snapshot 'path'"}))
                return
            try:
                self.swap_async(path)
            except RuntimeError as exc:
                sock.send_multipart(list(envelope) + self.codec.encode(
                    {"ok": False, "req_id": rid,
                     "replica_id": self.replica_id,
                     "error": str(exc)}))
                return
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": True, "swap_started": True, "req_id": rid,
                 "replica_id": self.replica_id,
                 "generation": self.runner.generation}))
            return
        if cmd == "rollback":
            # fleet canary auto-rollback (ISSUE 12): restore the
            # retained previous generation — instant and disk-free, so
            # it runs inline on this router thread (no load, no warm)
            try:
                gen = self.runner.rollback()
            except RuntimeError as exc:
                sock.send_multipart(list(envelope) + self.codec.encode(
                    {"ok": False, "req_id": rid,
                     "replica_id": self.replica_id, "error": str(exc)}))
                return
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": True, "rolled_back": True, "req_id": rid,
                 "replica_id": self.replica_id, "generation": gen}))
            return
        if cmd == "generate":
            self._handle_generate(sock, envelope, req, rid)
            return
        if cmd != "infer":
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": f"unknown cmd {cmd!r}"}))
            return
        x = req.get("x")
        if not isinstance(x, np.ndarray) or x.ndim < 1:
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": "infer request carries no tensor 'x'"}))
            return
        if x.ndim == len(self.runner.sample_shape):
            x = x[None]                     # single sample shorthand
        seq_len = None
        if self.seq_max_len is not None:
            # variable-length mode (ISSUE 15): axis 1 is the request's
            # OWN sequence length (any 1..max_len — over-long requests
            # fall through to the batcher's readable oversized
            # refusal); trailing dims must still match the model
            if x.ndim != 1 + len(self.runner.sample_shape) or \
                    tuple(x.shape[2:]) != self.runner.sample_shape[1:]:
                sock.send_multipart(list(envelope) + self.codec.encode(
                    {"ok": False, "req_id": rid,
                     "replica_id": self.replica_id,
                     "error": f"sequence request shape {x.shape} does "
                              f"not match (n, len<= {self.seq_max_len}"
                              f", *{self.runner.sample_shape[1:]})"}))
                return
            seq_len = int(x.shape[1])
        elif tuple(x.shape[1:]) != self.runner.sample_shape:
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": f"sample shape {tuple(x.shape[1:])} != model "
                          f"input {self.runner.sample_shape}"}))
            return
        if not np.can_cast(x.dtype, self.runner.dtype,
                           casting="same_kind"):
            # e.g. float samples sent to a u8-storage model: the
            # assemble cast would silently wrap/truncate them into
            # garbage bytes and the service would answer confidently
            # wrong — refuse readably like a wrong shape instead
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": f"sample dtype {x.dtype} cannot safely cast "
                          f"to the model's storage dtype "
                          f"{self.runner.dtype}"}))
            return
        self._m["requests_in"].inc()
        client = self._client_id(req, envelope)
        # deadline ingress (ISSUE 6): the client's shipped budget
        # becomes a LOCAL absolute deadline here (budgets, not
        # timestamps, cross the wire — clocks differ); the server's
        # request_ttl_s stays the cap.  Re-checked at assemble time and
        # post-compute: expired work is never computed, never shipped.
        deadline_s = self._deadline_s(req)
        if deadline_s <= 0:
            self._m["timed_out"].inc()
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "timed_out": True, "req_id": rid,
                 "replica_id": self.replica_id,
                 "policy": "deadline", "trace_id": req.get("trace_id"),
                 "error": f"deadline budget "
                          f"{req.get('deadline_ms')}ms already "
                          f"expended — refused at ingress"}))
            return
        reason = self.batcher.submit(
            Request(x, x.shape[0], reply_to=list(envelope), req_id=rid,
                    trace_id=req.get("trace_id"), client=client,
                    deadline_s=deadline_s, seq_len=seq_len))
        if reason is not None:
            self._m["rejected"].inc()
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "rejected": True, "req_id": rid,
                 "replica_id": self.replica_id,
                 "policy": getattr(reason, "policy", "refused"),
                 "scope": getattr(reason, "scope", "service"),
                 "trace_id": req.get("trace_id"), "error": str(reason)}))

    def _client_id(self, req, envelope) -> str:
        """Admission identity: explicit ``client`` metadata when the
        peer ships one (the InferenceClient does), else a digest of the
        ROUTER envelope — still distinct per client through a proxy,
        because the client's own identity frame rides inside."""
        client = req.get("client")
        if isinstance(client, str) and client:
            return client
        return "peer-%08x" % (zlib.crc32(
            b"".join(bytes(f) for f in envelope)) & 0xFFFFFFFF)

    def _deadline_s(self, req) -> float:
        """Relative deadline budget for one request: the client-shipped
        ``deadline_ms`` capped by ``request_ttl_s``.  Non-finite
        budgets are garbage: min(nan, ttl) is nan, and a nan deadline
        fails every later expiry check — a client could disable the
        TTL outright with one bad float."""
        deadline_s = self.request_ttl_s
        budget_ms = req.get("deadline_ms")
        if budget_ms is not None:
            try:
                budget_s = float(budget_ms) / 1e3
            except (TypeError, ValueError):
                budget_s = float("nan")
            if math.isfinite(budget_s):
                deadline_s = min(budget_s, deadline_s)
        return deadline_s

    def _handle_generate(self, sock, envelope, req, rid) -> None:
        """The ``generate`` request kind (ISSUE 16): a 1-D token
        prompt in, ``max_new_tokens`` autoregressive tokens out —
        streamed per-token (``stream``) or returned whole.  Queued on
        the continuous-batching scheduler; the final reply ships from
        the compute loop."""
        if self.gen_sched is None:
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": "generation serving is disabled — start the "
                          "service with root.common.serving.generate."
                          "enabled=True"}))
            return
        x = req.get("x")
        if not isinstance(x, np.ndarray) or x.ndim != 1 or x.size < 1 \
                or not np.issubdtype(x.dtype, np.number):
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": "generate request needs a non-empty 1-D "
                          "numeric token prompt 'x'"}))
            return
        self._m["requests_in"].inc()
        deadline_s = self._deadline_s(req)
        if deadline_s <= 0:
            self._m["timed_out"].inc()
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "timed_out": True, "req_id": rid,
                 "replica_id": self.replica_id,
                 "policy": "deadline", "trace_id": req.get("trace_id"),
                 "error": f"deadline budget "
                          f"{req.get('deadline_ms')}ms already "
                          f"expended — refused at ingress"}))
            return
        client = self._client_id(req, envelope)
        dup = rid is not None and self.gen_sched.in_flight(client, rid)
        try:
            seq = GenSeq(
                x, max_new=int(req.get("max_new_tokens", 0) or 0),
                temperature=float(req.get("temperature", 0.0) or 0.0),
                top_k=int(req.get("top_k", 0) or 0),
                seed=req.get("seed"),
                stream=bool(req.get("stream", False)),
                return_logits=bool(req.get("return_logits", False)),
                return_logprobs=bool(req.get("return_logprobs", False)),
                reply_to=list(envelope), req_id=rid,
                trace_id=req.get("trace_id"),
                client=client,
                deadline_s=deadline_s)
        except (TypeError, ValueError) as exc:
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "req_id": rid,
                 "replica_id": self.replica_id,
                 "error": f"bad generate parameters: {exc}"}))
            return
        if self._tracer.enabled:
            # zero-duration arrival marker: the replica frontend's hop
            # in the stitched fleet trace (ISSUE 20)
            self._tracer.add("serving", "generate_rx",
                             time.perf_counter(), 0.0,
                             {"trace_id": req.get("trace_id"),
                              "req_id": rid})
        reason = self.gen_sched.submit(seq)
        if reason is None and dup:
            # a resend matched an in-flight generation: answer with a
            # heartbeat partial — refreshes the client's resend timer
            # (generations outlive the resend window routinely; a
            # silent dedup would let a healthy long generation burn the
            # client's resend cap into a give-up)
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": True, "partial": True, "heartbeat": True,
                 "req_id": rid, "replica_id": self.replica_id,
                 "trace_id": req.get("trace_id")}))
            return
        if reason is not None:
            self._m["rejected"].inc()
            sock.send_multipart(list(envelope) + self.codec.encode(
                {"ok": False, "rejected": True, "req_id": rid,
                 "replica_id": self.replica_id,
                 "policy": getattr(reason, "policy", "refused"),
                 "scope": getattr(reason, "scope", "service"),
                 "trace_id": req.get("trace_id"),
                 "error": str(reason)}))
        # accepted (or deduplicated onto an in-flight generation):
        # tokens arrive from the compute loop's scheduler rounds

    # -- the compute loop (donated ping-pong) ----------------------------------

    def _resolve_seq_out(self) -> bool:
        """Does the model's output carry the SEQ axis?  Probed ONCE by
        comparing output axis 1 across two different seq rungs (a class
        axis cannot track the rung) — never per batch, where a class
        count colliding with one rung would truncate logits.  A
        single-rung seq ladder whose one rung equals the output width
        cannot be disambiguated — refused readably rather than
        guessed (slicing a class axis answers confidently wrong)."""
        if self._seq_out is None:
            lad = self.batcher.ladder
            r0 = lad.rungs[0]
            shapes = []
            for s in lad.seq_rungs[:2]:
                y = self.runner.infer(np.zeros(
                    self.runner.bucket_shape((r0, s)), self.runner.dtype))
                shapes.append(y.shape[1] if y.ndim >= 2 else None)
            matched = [shapes[i] == lad.seq_rungs[i]
                       for i in range(len(shapes))]
            if len(matched) == 1 and matched[0]:
                raise ValueError(
                    f"cannot tell whether the model output's axis 1 "
                    f"({shapes[0]}) is the sequence axis or a class "
                    f"axis that happens to equal the single seq rung "
                    f"{lad.seq_rungs[0]} — give the seq ladder a "
                    f"second rung (root.common.serving.seq.rungs) so "
                    f"the probe can disambiguate")
            self._seq_out = all(matched)
        return self._seq_out

    def _assemble(self, batch: List[Request]):
        """Coalesced requests -> (live requests, staged device buffer).
        Deadline-expired requests (client budget, else the TTL) are
        answered ``timed_out`` here — computing them would waste a
        batch slot on an answer nobody is waiting for.  Returns None
        when the whole batch expired."""
        now = time.perf_counter()
        live = []
        for r in batch:
            deadline = (r.t_enqueued + self.request_ttl_s
                        if r.t_deadline is None else r.t_deadline)
            if now > deadline:
                self._m["timed_out"].inc()
                self._outbound.put((r.reply_to, {
                    "ok": False, "timed_out": True, "req_id": r.req_id,
                    "replica_id": self.replica_id,
                    "policy": "deadline", "trace_id": r.trace_id,
                    "error": f"request expired before compute (deadline "
                             f"budget spent queueing; ttl cap "
                             f"{self.request_ttl_s:g}s)"}, None))
                self._note_request(False, now - r.t_enqueued, r.req_id,
                                   r.trace_id)
                continue
            live.append(r)
        if not live:
            return None
        rows = sum(r.n for r in live)
        bucket = self.batcher.ladder.bucket_for(rows)
        # 2-D mode: the batcher pinned ONE seq rung for this batch; the
        # assemble buffer is (rows_rung, seq_rung, *tail), zero-filled —
        # the padded tail of every row is PAD id 0, and each request's
        # own length (its padding mask) rides the Request to reply time
        seq = live[0].seq_rung
        shape = ((bucket,) + self.runner.sample_shape if seq is None
                 else (bucket, seq) + self.runner.sample_shape[1:])
        with self._tracer.span("serving", "assemble", rows=rows,
                               bucket=bucket, requests=len(live),
                               seq=seq or 0):
            x = np.zeros(shape, self.runner.dtype)
            off = 0
            for r in live:
                if seq is None:
                    x[off:off + r.n] = np.asarray(r.x, self.runner.dtype) \
                        .reshape((r.n,) + self.runner.sample_shape)
                else:
                    x[off:off + r.n, :r.seq_len] = \
                        np.asarray(r.x, self.runner.dtype).reshape(
                            (r.n, r.seq_len) + self.runner.sample_shape[1:])
                off += r.n
            staged = self.runner.stage(x)
        return live, staged

    def _finish(self, live: List[Request], y_dev, gen: int,
                t_dispatch: Optional[float] = None) -> None:
        y = np.asarray(y_dev)               # the sync point
        if t_dispatch is not None and self._tracer.enabled:
            # dispatch -> materialized: the batch's device-compute span
            # (staging of batch N+1 overlaps inside it by design)
            self._tracer.add(
                "serving", "batch_compute", t_dispatch,
                time.perf_counter() - t_dispatch,
                {"rows": sum(r.n for r in live), "requests": len(live),
                 "trace_id": live[0].trace_id if live else None})
        now = time.perf_counter()
        # per-rung latency ring (ISSUE 12): enqueue -> compute done for
        # this batch's ladder rung — the heartbeat's p99-by-bucket feed
        # (histograms carry their own locks; this runs on the compute
        # thread while the router thread reads)
        rung = self.batcher.ladder.bucket_for(sum(r.n for r in live)) \
            if live else None
        off = 0
        for r in live:
            if rung is not None:
                self._m_lat_bucket[rung].observe(now - r.t_enqueued)
            if r.t_deadline is not None and now > r.t_deadline:
                # the post-compute deadline check: a late result is
                # DROPPED, never shipped — the client already moved on,
                # and shipping it would spend reply bandwidth on an
                # answer nobody is waiting for
                self._m["timed_out"].inc()
                self._m["expired_results"].inc()
                self._outbound.put((r.reply_to, {
                    "ok": False, "timed_out": True, "req_id": r.req_id,
                    "replica_id": self.replica_id,
                    "policy": "deadline", "trace_id": r.trace_id,
                    "error": "result ready past the deadline — dropped, "
                             "not shipped"}, None))
                self._note_request(False, now - r.t_enqueued, r.req_id,
                                   r.trace_id, bucket=rung)
                off += r.n
                continue
            # slice-copy: each reply owns its rows (the padded tail is
            # dropped here — pad rows never leave the server; on a seq
            # output the padded TOKEN positions are sliced off too, back
            # to the request's own length).  ``gen`` names the snapshot
            # generation that answered — ONE per batch by construction
            # (the runner reads (params, gen) atomically), the rollover
            # proof's per-reply assertion.
            yr = y[off:off + r.n]
            # seq-shaped outputs only (probed once at startup — a
            # seq-REDUCING model ships its rows whole; per-batch shape
            # comparison would truncate logits whenever a class count
            # collides with the pinned rung): cut the reply back to
            # the request's own length
            if r.seq_rung is not None and self._resolve_seq_out() \
                    and yr.ndim >= 2:
                yr = yr[:, :r.seq_len]
            self._outbound.put((r.reply_to, {
                "ok": True, "req_id": r.req_id, "trace_id": r.trace_id,
                "gen": gen, "replica_id": self.replica_id,
                "y": np.array(yr)}, r.t_enqueued))
            off += r.n
            self._m["served"].inc()
            self._note_request(True, now - r.t_enqueued, r.req_id,
                               r.trace_id, bucket=rung)

    def _compute_loop(self) -> None:
        import zmq

        wake = zmq.Context.instance().socket(zmq.PUSH)
        wake.setsockopt(zmq.LINGER, 0)
        wake.connect(self._wake_addr)

        def poke():
            try:
                wake.send(b"", zmq.NOBLOCK)
            except zmq.Again:           # router already has wakes queued
                pass

        gs = self.gen_sched

        def gen_step() -> bool:
            # one continuous-batching round (migrate / decode tick /
            # prefill batch); its replies queue for the router thread
            worked, replies = gs.step()
            self._ship_gen(replies, poke)
            return worked or bool(replies)

        staged = None
        try:
            while True:
                if staged is None:
                    # with generation work ready RIGHT NOW the classic
                    # queue gets a zero-wait poll (decode cadence must
                    # not wait out the coalescing window)
                    timeout = 0.0 if (gs is not None
                                      and gs.work_ready()) else 0.05
                    batch = self.batcher.next_batch(timeout=timeout)
                    if batch is None:
                        if self._stop.is_set():
                            if gs is not None:
                                # abandon queued/live generations with
                                # readable draining replies
                                self._ship_gen(gs.drain(), poke)
                            return
                        if gs is not None and not gen_step() \
                                and timeout == 0.0:
                            # ready-but-stalled edge (every active
                            # sequence waiting on a migration slot):
                            # don't spin hot against the pool
                            time.sleep(0.001)
                        continue
                    staged = self._assemble(batch)
                    if staged is None:
                        poke()          # TTL refusals queued: ship them
                        continue
                live, x_dev = staged
                # dispatch is async; the staged buffer is DONATED into
                # the step (ping-pong half 1)
                t_dispatch = time.perf_counter()
                y_dev, gen = self.runner.infer_staged(x_dev)
                staged = None
                # while the device computes batch N, grab-and-stage what
                # is ALREADY queued as batch N+1 (ping-pong half 2: at
                # most two input buffers ever exist — the donated one
                # and this one).  wait_fill=False: a coalescing window
                # here would hold batch N's finished replies hostage
                nxt = self.batcher.next_batch(timeout=0.0,
                                              wait_fill=False)
                if nxt is not None:
                    staged = self._assemble(nxt)
                self._finish(live, y_dev, gen, t_dispatch)
                poke()                  # replies queued: wake the router
                if gs is not None and gs.work_ready():
                    gen_step()          # interleave under mixed traffic
        except Exception:
            # a compute-thread death must not strand clients silently
            self.log.exception("inference compute loop died")
            self._stop.set()
            self.batcher.close()
            if self.gen_sched is not None:
                self.gen_sched.close()
        finally:
            wake.close(0)

    def _note_gen_final(self, rep) -> None:
        """Generation final bookkeeping (ISSUE 20): SLO feeds
        (availability, TTFT, inter-token from the scheduler's timing
        breakdown), the slow-request exemplar window, and the
        stitched-trace reply summary — the replica's spans for this
        trace ride the final back so the client/balancer can stitch
        without waiting for the next heartbeat.  Finals only: the
        infer hot loop and streamed partials never pay this."""
        from znicz_tpu import telemetry

        if rep.get("rejected"):
            return              # intentional refusal: not a miss
        ok = bool(rep.get("ok"))
        t = rep.get("timing_ms") or {}
        total = t.get("total")
        if total is not None:
            self._note_request(ok, total / 1e3, rep.get("req_id"),
                               rep.get("trace_id"), kind="generate",
                               breakdown=t)
        else:
            self.slo.record("availability", ok)
        if ok:
            ttft = t.get("ttft")
            if ttft is not None:
                self.slo.record_latency("ttft", ttft / 1e3)
                toks = rep.get("tokens")
                n = int(getattr(toks, "size", 0) or 0)
                if n > 1 and total is not None and total > ttft:
                    self.slo.record_latency(
                        "inter_token",
                        (total - ttft) / 1e3 / (n - 1))
        tid = rep.get("trace_id")
        if ok and tid and self._tracer.enabled:
            spans = self._exporter.peek_trace(str(tid))
            if spans:
                rep["spans"] = spans
                rep["origin"] = telemetry.identity()

    def _ship_gen(self, replies, poke=None) -> None:
        """Queue generation replies for the router thread.  Finals
        count into served/timed_out/rejected (and so toward
        ``max_requests``); streamed partials are progress, not
        answers."""
        for env, rep in replies:
            if env is None:
                continue
            if not rep.get("partial"):
                if rep.get("ok"):
                    self._m["served"].inc()
                elif rep.get("timed_out"):
                    self._m["timed_out"].inc()
                else:
                    self._m["rejected"].inc()
                self._note_gen_final(rep)
            self._outbound.put((env, rep, None))
        if replies and poke is not None:
            poke()


for _name, _help in InferenceServer.COUNTERS.items():
    setattr(InferenceServer, _name, registered_property(_name, _help))
del _name, _help
