"""AOT executable cache (ISSUE 17): serialize every warmed executable
into a content-addressed on-disk cache next to the snapshot, so a
restarted replica LOADS its executable family instead of compiling it.

The paper's economics assume workers die and return constantly; after
PR 15/16 one replica's family is 12 scoring buckets + 22 generation
executables, and a cold compile of that family dominates
boot-to-/readyz.  This cache turns a heal/preemption/canary reboot
into a deserialize pass: measured on this host a cached executable
loads ~20x faster than it compiles (see BASELINE.md r22).

**Mechanism** — ``jax.experimental.serialize_executable``:
``serialize(compiled)`` captures a lowered+compiled executable (XLA
binary + in/out tree defs) and ``deserialize_and_load`` rebuilds a
callable WITHOUT recompiling.  This is deliberately NOT ``jax.export``:
an exported StableHLO module re-runs XLA compilation on load, which
pays the exact cost the cache exists to skip (measured: export-load ~=
cold compile; serialize-load ~3 orders faster on larger families).

**Key design** — one cache file per executable, filename =
``sha256(canonical-JSON({family, entry}))``:

  - the FAMILY key fingerprints everything that determines lowering:
    every unit's param shapes+dtypes (a structural digest — a canary
    snapshot with new weights but the same architecture still hits),
    sample shape, staging dtype, mesh shape, donation flag, and the
    jax/jaxlib/backend/platform versions (an XLA upgrade silently
    invalidates the whole family — different digest, clean miss);
  - the ENTRY key names one executable within the family: the scoring
    bucket shape, or the generation (kind, rungs) tuple — paged
    generation entries (ISSUE 19: ``prefill``/``decode`` keyed (batch
    rung, page rung), plus the ``copy`` COW move) also carry the
    (page_size, num_pages, prefill_chunk) geometry, so two boots with
    different paging never share an entry.

A version bump, mesh change, or architecture change can therefore
never load a stale executable — the filename itself diverges.  Entries
that DO resolve but fail to decode (truncated file, foreign pickle,
tampered key, deserialize error, or — on backends where execution
validates — a first-call failure) are REFUSED readably: counted,
logged with the reason, and recompiled; a refused entry is overwritten
by the fresh store.  The cache is advisory, never trusted.

Wire-in: ``ModelRunner.enable_aot_cache`` (model.py) builds one
``ExecutableCache`` per runner and routes every warmup/dispatch miss
through ``_aot_exec``; counters land in the ``warmup`` telemetry scope
(``znicz_warmup_cache_{hits,misses,stores,refusals}_total``) — the
fleet panel's warm columns and bench.py --elastic's boot gate read
them.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
from typing import Dict, Optional

log = logging.getLogger("znicz.serving")


def available() -> bool:
    """True when this jax build ships ``serialize_executable`` (the
    cache degrades to plain compile-every-boot when absent — serving
    still works, elasticity is just slower)."""
    try:
        from jax.experimental import serialize_executable  # noqa: F401

        return True
    except Exception:                   # pragma: no cover - jax-version dep
        return False


def dir_for_snapshot(snapshot_path: str) -> str:
    """The cache directory for a snapshot: ``aot_cache/`` NEXT TO the
    snapshot file, so the cache travels with the weights it warms (a
    fleet pulling one promoted snapshot path shares one warm cache)."""
    return os.path.join(
        os.path.dirname(os.path.abspath(snapshot_path)), "aot_cache")


def family_key(runner) -> Dict:
    """The structural fingerprint of one runner's executable family.

    Structural, not byte-content: param SHAPES/dtypes per unit, never
    the weights — swapping in a retrained canary of the same
    architecture keeps hitting (the executable is a pure function of
    avals), while any shape/dtype/mesh/version drift changes the
    digest and misses cleanly."""
    import jax
    import jaxlib

    units = {name: {k: [list(map(int, a.shape)), str(a.dtype)]
                    for k, a in sorted(layer.items())}
             for name, layer in sorted(runner.params.items())}
    try:
        platform_version = str(jax.devices()[0].client.platform_version)
    except Exception:                   # pragma: no cover - backend dep
        platform_version = ""
    return {"units": units,
            "sample_shape": list(map(int, runner.sample_shape)),
            "dtype": str(runner.dtype),
            "mesh": runner.mesh_shape,
            "donate": bool(runner.donate),
            "jax": str(jax.__version__),
            "jaxlib": str(jaxlib.__version__),
            "backend": str(jax.default_backend()),
            "platform_version": platform_version}


class ExecutableCache:
    """One snapshot directory's executable cache for one family.

    ``load``/``store`` move single executables; ``hit``/``miss`` are
    ticked by the runner's dispatch once an entry is VALIDATED (a
    loaded executable that fails its first call is refused, not hit),
    so ``hits + misses == family size`` after warmup and ``misses ==
    compiles`` is the cache half of the boot proof."""

    COUNTERS = {
        "warmup_cache_hits": "executables loaded from the AOT cache "
                             "instead of compiled",
        "warmup_cache_misses": "executables compiled (absent or refused "
                               "cache entry)",
        "warmup_cache_stores": "freshly compiled executables serialized "
                               "into the cache",
        "warmup_cache_refusals": "cache entries refused (corrupt/stale/"
                                 "version-mismatched/failed validation) "
                                 "— recompiled, never trusted",
        "warmup_cache_store_failures": "serialize/write failures (cache "
                                       "stays cold for that entry; "
                                       "serving unaffected)",
    }

    def __init__(self, directory: str, family: Dict):
        from znicz_tpu import telemetry

        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.family = family
        _sc = telemetry.scope("warmup")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        #: per-instance tallies (the registry counters are process-wide
        #: and latest-wins; proofs read THIS cache's own numbers)
        self._n = {"hits": 0, "misses": 0, "stores": 0, "refusals": 0,
                   "store_failures": 0}

    def _key(self, entry: Dict) -> Dict:
        return {"family": self.family, "entry": entry}

    def _path(self, entry: Dict) -> str:
        digest = hashlib.sha256(
            json.dumps(self._key(entry), sort_keys=True,
                       separators=(",", ":")).encode()).hexdigest()
        return os.path.join(self.directory, digest[:32] + ".aot")

    def load(self, entry: Dict):
        """Deserialize one entry's executable, or None (absent, or
        refused — corrupt pickle, key mismatch from a digest collision
        or tamper, deserialize failure).  The caller validates and
        ticks hit/miss; refusals are counted HERE so every unreadable
        entry surfaces in ``znicz_warmup_cache_refusals_total``."""
        path = self._path(entry)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
            if blob.get("key") != self._key(entry):
                raise ValueError("cached key does not match the "
                                 "requested entry (stale or tampered)")
            from jax.experimental import serialize_executable as se

            return se.deserialize_and_load(*blob["payload"])
        except Exception as exc:
            self.refuse(entry, exc)
            return None

    def store(self, entry: Dict, compiled) -> bool:
        """Serialize one freshly compiled executable (atomic write —
        a half-written entry must never survive a crash to be refused
        on every boot after).  A failure leaves the cache cold for
        this entry and serving untouched."""
        from znicz_tpu.snapshotter import atomic_write_bytes

        try:
            from jax.experimental import serialize_executable as se

            payload = se.serialize(compiled)
            atomic_write_bytes(self._path(entry), pickle.dumps(
                {"key": self._key(entry), "payload": payload},
                protocol=pickle.HIGHEST_PROTOCOL))
        except Exception as exc:
            self._n["store_failures"] += 1
            self._m["warmup_cache_store_failures"].inc()
            log.warning("aot cache: store failed for %s: %s", entry, exc)
            return False
        self._n["stores"] += 1
        self._m["warmup_cache_stores"].inc()
        return True

    def hit(self) -> None:
        self._n["hits"] += 1
        self._m["warmup_cache_hits"].inc()

    def miss(self) -> None:
        self._n["misses"] += 1
        self._m["warmup_cache_misses"].inc()

    def refuse(self, entry: Dict, exc: BaseException) -> None:
        """A readable refusal: the entry exists but cannot be trusted —
        log WHY (the heal/preemption postmortem reads this), count it,
        and let the caller recompile + overwrite."""
        self._n["refusals"] += 1
        self._m["warmup_cache_refusals"].inc()
        log.warning("aot cache: refused entry %s (%s: %s) — recompiling",
                    entry, type(exc).__name__, exc)

    @property
    def counts(self) -> Dict[str, int]:
        return dict(self._n)

    def stats(self) -> Dict:
        return {"directory": self.directory, **self._n}
