"""ModelRunner: a trained workflow frozen into an inference-only jitted
forward (ISSUE 4).

The forward IS ``FusedTrainer.forward_pass`` with ``train=False`` — the
same pure composition of the units' own ``apply`` code the training fast
path differentiates, so serving computes exactly the function training
optimized (the batched-vs-unbatched 0-ULP parity test in
tests/test_serving.py rides on the row-independence of that graph).
Params are extracted once at construction and pinned on device; every
call passes them as an un-donated operand, so one params tree serves
every bucket's executable.

**Bucketed jit cache**: the runner jits ONE function of ``(params, x)``;
each distinct padded batch shape (a ladder rung) compiles exactly once
and is a cache hit forever after.  ``compiles`` counts TRACES — the
counter ticks inside the traced function body, which Python only runs
when jax actually (re)traces, i.e. once per cache entry — and
``jit_cache_size()`` cross-checks it against jax's own pjit cache, so
"zero recompiles after warmup" is provable from the outside
(bench.py --serve's gate).

**Donated ping-pong staging**: ``stage`` starts an async host->device
put and ``infer_staged`` DONATES that buffer into the jitted call
(``donate_argnums``), so at any moment at most two input buffers exist —
the one the device is consuming (its memory reusable for activations
the instant the gather reads it) and the one the next batch is staging
into.  The frontend's compute loop overlaps stage(N+1) with compute(N),
the same overlap discipline as ``loader/ingest.py``'s prefetch.

**Zero-downtime snapshot rollover** (ISSUE 6): :meth:`swap` loads a new
snapshot's params, bucket-warms them through every ladder rung, then
flips ``(params, generation)`` as ONE atomic tuple — serving continues
on the old generation throughout, and because every dispatch reads the
tuple exactly once, every request is answered entirely by one snapshot
generation (the ``gen`` id in each reply proves it).  A failed load or
warm leaves the served generation untouched.

**Autoregressive generation** (ISSUE 16, block-paged since ISSUE 19):
:meth:`enable_generation` builds a :class:`GenerationRunner` — a
block-paged KV pool with content-addressed prefix reuse
(:class:`PrefixCache`) plus three more jitted functions
(prefill-chunk, decode, page-copy; greedy/top-k sampling fused into
the first two) that share the runner's ``compiles`` counter, so the
zero-recompile contract extends over the whole generation executable
family: ``(prefill_rungs + decode_rungs) x page_rungs + 1``
executables, warmed up front, zero traces after.

**Pod-scale sharding** (ISSUE 13): with ``root.common.serving.mesh.*``
set (``data``/``model`` axis sizes; default 1x1 = exactly the
single-device path above), the runner goes mesh-native: params are
replicated (or column-sharded over ``model`` for wide FC layers) via
``FusedTrainer.param_sharding`` + ``mesh.global_put``, the forward is
jitted with explicit ``in_shardings``/``out_shardings``, and every
staged batch is split along the ``data`` axis — each device holds
exactly ``rows/dp`` rows, placed DIRECTLY from the host (one transfer
per device shard, never a gather through device 0).  The bucket
ladder's rungs are snapped to multiples of ``dp`` so every executable
splits evenly, which keeps the jit cache bounded and the
zero-recompile contract intact on the sharded path.  The 0-ULP
batch-independence contract extends UNCHANGED to a fixed mesh (a
request's rows are a pure function of its rows + the rung executable,
wherever its rows land across devices); across DIFFERENT mesh layouts
results agree only numerically — reduction tiling is layout-dependent,
the same reason PR 4 pinned parity per bucket executable
(bench.py --shard gates the band; tests/test_shard_serving.py).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from znicz_tpu.telemetry.metrics import registered_property


def mesh_from_config():
    """The serving mesh, or None for the default 1x1 — which keeps the
    runner on the exact single-device code path (bit-for-bit the
    pre-mesh behavior).  Kept under its historical name; the config
    read and every other piece of placement machinery live in the ONE
    shared home, ``parallel/mesh.py`` (ISSUE 18 extraction)."""
    from znicz_tpu.parallel.mesh import serving_mesh_from_config

    return serving_mesh_from_config()


class ModelRunner:
    """Freeze a built+initialized workflow's params into a jitted
    inference forward.  ``snapshot`` restores params first (the
    snapshotter's inference-load path — no velocities, no trainer
    state).  The output is the last unit's output: LOGITS for a softmax
    head (clients softmax if they want probabilities), the raw
    reconstruction for MSE heads."""

    def __init__(self, workflow, snapshot: str = "",
                 donate: Optional[bool] = None, mesh=None):
        import jax

        from znicz_tpu.parallel.fused import FusedTrainer

        if donate is None:
            # donation is a TPU/GPU lever; the CPU runtime ignores it
            # (and warns per compile), so auto-resolve by backend — the
            # serving STRUCTURE (stage N+1 while N computes) is identical
            # either way, only the buffer reuse is backend-dependent
            donate = jax.default_backend() != "cpu"
        self.donate = bool(donate)

        if snapshot:
            from znicz_tpu import snapshotter

            snapshotter.load_inference(workflow, snapshot)
        self.workflow = workflow
        #: the serving mesh (ISSUE 13): explicit arg wins, else
        #: ``root.common.serving.mesh.*``; None = single-device (the
        #: pre-mesh code path, bit-exact)
        self.mesh = mesh if mesh is not None else mesh_from_config()
        self._trainer = FusedTrainer(workflow, mesh=self.mesh)
        if self.mesh is not None:
            from znicz_tpu.parallel.mesh import data_sharding

            self._data_sharding = data_sharding(self.mesh)
        else:
            self._data_sharding = None
        #: (params tree, generation id) — read ONCE per dispatch, flipped
        #: as one tuple by swap(): per-request snapshot atomicity
        self._active = (self._place_params(
            self._trainer.extract_params()), 1)
        #: the snapshot file the LIVE generation came from (boot
        #: ``snapshot`` arg, updated by swap/rollback) — heartbeats
        #: carry it so a fleet balancer can heal a restarted replica
        #: back onto the promoted snapshot
        self.snapshot_path: str = snapshot or ""
        #: the RETAINED previous generation ``(params, gen, path)`` —
        #: set by a successful swap(), consumed by rollback(); costs one
        #: extra params tree in memory, which is what buys an instant,
        #: bit-exact, disk-free fleet rollback
        self._previous: Optional[Tuple] = None
        #: generation high-water mark: swap always allocates hwm+1, so
        #: a rollback-then-retry cycle can never hand two DIFFERENT
        #: param sets the same generation stamp
        self._gen_hwm = 1
        self._swap_lock = threading.Lock()  # one rollover at a time
        #: True while swap() loads/warms (the /readyz "warming" signal)
        self.swapping = False
        self._dispatch_no = 0               # compute-fault stream cursor
        self._dispatch_lock = threading.Lock()  # cursor is shared by the
        #                                     compute thread AND swap()'s
        #                                     warmup dispatches
        self._chaos = None                  # FaultSchedule, or None
        self._m_stalls = None
        #: GenerationRunner once enable_generation() ran (ISSUE 16)
        self.gen_runner: Optional["GenerationRunner"] = None
        #: per-sample input shape the service accepts (requests carry
        #: (n, *sample_shape) arrays)
        self.sample_shape: Tuple[int, ...] = tuple(
            int(d) for d in workflow.forwards[0].input.shape[1:])
        mem = getattr(workflow.loader.original_data, "mem", None)
        #: staging dtype — u8 datasets keep their 1-byte wire/HBM form,
        #: the in-graph decode (trainer._decode) widens on device
        self.dtype = np.dtype(mem.dtype) if mem is not None \
            else np.dtype(np.float32)
        from znicz_tpu import telemetry

        _sc = telemetry.scope("model")
        #: traces of _fwd == cache entries (registry counter; the
        #: ``compiles`` property preserves the historical name)
        self._m = {"compiles": _sc.counter(
            "compiles",
            "traces of the jitted forward == jit cache entries"),
            "swaps": _sc.counter(
                "swaps", "completed snapshot rollovers"),
            "swap_failures": _sc.counter(
                "swap_failures",
                "rollovers refused/failed (old generation kept serving)"),
            "rollbacks": _sc.counter(
                "rollbacks",
                "retained-previous generation restored (fleet canary "
                "auto-rollback path)"),
            "stage_copies": _sc.counter(
                "stage_copies",
                "host batches copied before staging (non-contiguous or "
                "wrong-dtype input; the frontend's assemble path never "
                "pays this)")}
        _sc.gauge("generation", "live snapshot generation id",
                  fn=telemetry.weak_fn(self, lambda r: r.generation))
        _sc.gauge("mesh_devices", "devices in the serving mesh (1 = "
                  "single-device)",
                  fn=telemetry.weak_fn(self, lambda r: r.device_count))
        self._tracer = telemetry.tracer()
        compiles = self._m["compiles"]
        key = self._trainer._key0       # eval path never consumes it

        def fwd(params, x):
            # trace-time tick: Python runs this body once per compile
            # (cache hits replay the compiled executable only)
            compiles.inc()
            t = self._trainer
            return t.forward_pass(params, t._decode(x), key, train=False)

        donate = (1,) if self.donate else ()
        if self.mesh is None:
            self._fwd = jax.jit(fwd, donate_argnums=donate)
        else:
            # explicit shardings (SNIPPETS [3]): params pinned to their
            # param_sharding placements, the batch split over ``data``
            # in AND out — GSPMD propagates through the forward and
            # inserts the model-axis collectives where column-sharded
            # FC weights demand them
            self._fwd = jax.jit(
                fwd, donate_argnums=donate,
                in_shardings=(self._param_shardings(self.params),
                              self._data_sharding),
                out_shardings=self._data_sharding)
        # weak_fn: the process-global registry must not pin this
        # runner's jitted executables + device params after the service
        # drops it (a dead ref renders NaN)
        _sc.gauge("jit_cache_size", "jax's own executable-cache entries",
                  fn=telemetry.weak_fn(
                      self, lambda r: r.jit_cache_size()))
        #: AOT dispatch table (ISSUE 17): {(shape, dtype): executable},
        #: consulted BEFORE the jitted forward once enable_aot_cache
        #: ran.  In AOT mode every executable enters the table by
        #: deserialize or by explicit lower+compile — jax's own jit
        #: call cache stays EMPTY, which is what makes the boot proof
        #: strict: jit_cache_size() == 0 and table size == family size
        #: means NOTHING was traced through the implicit path.
        self._aot: Dict = {}
        self._aot_cache = None          # ExecutableCache, or None
        #: this runner's own warm tally (the cache's registry counters
        #: are process-wide; proofs and heartbeats read these)
        self._warm = {"hits": 0, "misses": 0}

    compiles = registered_property(
        "compiles", "traces of the jitted forward == jit cache entries")
    swaps = registered_property(
        "swaps", "completed snapshot rollovers")
    swap_failures = registered_property(
        "swap_failures", "rollovers refused/failed")
    rollbacks = registered_property(
        "rollbacks", "retained-previous generation restored")
    stage_copies = registered_property(
        "stage_copies", "host batches copied before staging")

    @property
    def params(self):
        """The LIVE generation's params tree (historical attribute)."""
        return self._active[0]

    @property
    def generation(self) -> int:
        """Snapshot generation id stamped on every reply; bumps on each
        completed :meth:`swap`."""
        return self._active[1]

    # -- mesh placement (ISSUE 13) ---------------------------------------------

    @property
    def device_count(self) -> int:
        """Devices this runner computes on (the mesh size; 1 when
        single-device) — piggybacked on fleet heartbeats so the
        balancer can weight dispatch by capacity."""
        return 1 if self.mesh is None else int(self.mesh.size)

    @property
    def data_parallel(self) -> int:
        """The mesh's ``data``-axis size (1 when single-device): every
        ladder rung must be a multiple of this."""
        return 1 if self.mesh is None else int(self.mesh.shape["data"])

    @property
    def mesh_shape(self) -> Optional[Dict[str, int]]:
        """``{"data": dp, "model": mp}`` (None when single-device) —
        the heartbeat/panel form of the mesh."""
        from znicz_tpu.parallel.mesh import mesh_shape_dict

        return mesh_shape_dict(self.mesh)

    def _param_shardings(self, params):
        """The params tree's NamedSharding tree per the shared
        ``param_sharding`` rule (wide FC weights column-shard over
        ``model``).  Mesh-mode only."""
        from znicz_tpu.parallel.mesh import tree_shardings

        return tree_shardings(self.mesh, params,
                              self._trainer.tp_threshold)

    def _place_params(self, params):
        """Distribute a params tree onto the mesh per its shardings
        (the shared ``place_tree``).  Identity when single-device: the
        tree is already placed by extraction."""
        if self.mesh is None:
            return params
        from znicz_tpu.parallel.mesh import place_tree

        return place_tree(self.mesh, params, self._trainer.tp_threshold)

    # -- the two halves of the ping-pong ---------------------------------------

    def stage(self, x: np.ndarray):
        """Host batch -> device buffer.  The put is dispatched
        asynchronously, so calling this while a previous ``infer_staged``
        is still computing overlaps the H2D copy with that compute.

        An input already contiguous in the staging dtype is handed to
        the put as-is (the frontend's assemble buffer always is); only
        mismatched inputs pay a host copy (``stage_copies``).  On a
        mesh the put places each device's ``rows/dp`` shard DIRECTLY
        from the host buffer — one transfer per shard, no gather
        through device 0 — so the batch is born in the layout the
        sharded executable consumes."""
        import jax

        if not (isinstance(x, np.ndarray) and x.dtype == self.dtype
                and x.flags["C_CONTIGUOUS"]):
            self._m["stage_copies"].inc()
            x = np.ascontiguousarray(x, self.dtype)
        if self.mesh is None:
            return jax.device_put(x)
        from znicz_tpu.parallel.mesh import require_batch_divisible

        dp = require_batch_divisible(x.shape[0], self.mesh)
        if self._tracer.enabled:
            with self._tracer.span("model", "stage_sharded",
                                   rows=int(x.shape[0]), shards=dp,
                                   rows_per_shard=int(x.shape[0]) // dp):
                return jax.device_put(x, self._data_sharding)
        return jax.device_put(x, self._data_sharding)

    def _maybe_stall(self) -> None:
        """Chaos compute-fault hook (ISSUE 6): one ``decide_compute``
        decision per dispatch; a ``stall`` sleeps here — the seeded
        slow-compute fault the rollover/fairness soaks run under.  The
        cursor advances under a lock: during a swap the background
        warmup dispatches race the compute thread, and a lost increment
        would let two dispatches replay one stream index."""
        with self._dispatch_lock:
            no = self._dispatch_no
            self._dispatch_no += 1
            chaos = self._chaos
        if chaos is None:
            return
        action, s = chaos.decide_compute(no)
        if action == "stall":
            self._m_stalls.inc()
            time.sleep(s)

    def infer_staged(self, x_dev) -> Tuple[object, int]:
        """Dispatch the forward on an already-staged (device) batch and
        return ``(un-materialized device result, generation id)`` —
        params and generation are read as one tuple, so the whole batch
        is answered by exactly one snapshot generation.  ``x_dev`` is
        DONATED (where the backend supports donation — see ``donate``);
        callers must not reuse it after this call either way."""
        self._maybe_stall()
        params, gen = self._active
        return self._fwd_call(params, x_dev), gen

    def inject_compute_faults(self, schedule) -> None:
        """Arm the seeded compute-fault hook: ``schedule`` (a chaos
        ``FaultSchedule``) decides per dispatch whether this runner
        stalls (``decide_compute``); counted in the chaos fault family
        like the proxy's wire faults."""
        from znicz_tpu import telemetry

        if self._m_stalls is None:
            self._m_stalls = telemetry.scope("chaos").counter(
                "faults", "injected proxy fault decisions",
                direction="compute", action="stall")
        self._chaos = schedule

    # -- conveniences ----------------------------------------------------------

    def infer(self, x: np.ndarray) -> np.ndarray:
        """Synchronous forward of one host batch (tests, warmup, the
        sequential baseline)."""
        y_dev, _ = self.infer_staged(self.stage(x))
        return np.asarray(y_dev)

    def pad(self, x: np.ndarray, bucket: int) -> np.ndarray:
        """Zero-pad a (n, *sample) batch up to ``bucket`` rows.  The
        forward is row-independent, so pad rows cannot perturb real
        rows; the caller slices the first n output rows back out."""
        n = x.shape[0]
        if n == bucket:
            return x
        out = np.zeros((bucket,) + tuple(x.shape[1:]), self.dtype)
        out[:n] = x
        return out

    def bucket_shape(self, bucket) -> Tuple[int, ...]:
        """The staged input shape for one ladder bucket: ``(rung,
        *sample)`` for a plain batch rung, ``(rows, seq, *sample[1:])``
        for a 2-D ``(rows, seq)`` bucket (ISSUE 15 — the seq axis
        replaces the trained max length in axis 1)."""
        if isinstance(bucket, tuple):
            rows, seq = bucket
            return (int(rows), int(seq)) + tuple(self.sample_shape[1:])
        return (int(bucket),) + tuple(self.sample_shape)

    def warmup(self, ladder) -> int:
        """Compile every ladder bucket's executable up front (the full
        rows x seq product on a 2-D ladder); returns the compile count
        afterwards — the zero-recompiles baseline the serving gates
        compare against (``compiles == len(ladder.buckets())``)."""
        for bucket in ladder.buckets():
            self.infer(np.zeros(self.bucket_shape(bucket), self.dtype))
        return self.compiles

    def swap(self, path: str, ladder=None) -> Dict:
        """Zero-downtime snapshot rollover (ISSUE 6): load ``path``
        through the inference path, bucket-warm the NEW params through
        every ``ladder`` rung, then flip ``(params, generation)``
        atomically.  Runs on the CALLING thread (the frontend drives it
        from a background thread); dispatches keep serving the OLD
        generation until the flip, so no request is lost and none mixes
        generations.  Warming costs no recompiles — the new tree has
        the same shapes/dtypes, so every rung is a jit cache hit; it
        pre-pays device transfer and catches a broken snapshot while
        the old generation still serves.  A second concurrent swap, a
        non-covering snapshot, or a warm failure raises and leaves the
        live generation untouched (``swap_failures`` counts it).
        Returns the snapshot's metadata."""
        from znicz_tpu import snapshotter

        if not self._swap_lock.acquire(blocking=False):
            self._m["swap_failures"].inc()
            raise RuntimeError("swap already in progress")
        try:
            self.swapping = True
            try:
                meta = snapshotter.load_inference(self.workflow, path)
                # the NEW tree lands in the SAME placement the live one
                # serves from (replicated/column-sharded on a mesh), so
                # the flip below swaps like for like and the warmed
                # rungs are jit cache hits on the sharded executables
                params = self._place_params(
                    self._trainer.extract_params())
                buckets = ladder.buckets() if ladder is not None else ()
                # warm through _fwd_call: on an AOT-warm boot the jit
                # call cache is EMPTY by design, and warming through
                # self._fwd directly would recompile every rung
                for bucket in buckets:
                    self._maybe_stall()
                    x = np.zeros(self.bucket_shape(bucket), self.dtype)
                    np.asarray(self._fwd_call(params, self.stage(x)))
                # retain the losing side for a disk-free rollback(); the
                # hwm (not generation+1) allocates the new id, so a
                # rolled-back-then-retried rollover never reuses a stamp
                old_params, old_gen = self._active
                self._previous = (old_params, old_gen, self.snapshot_path)
                self._gen_hwm += 1
                self._active = (params, self._gen_hwm)
                self.snapshot_path = path
                self._m["swaps"].inc()
                return meta
            except Exception:
                self._m["swap_failures"].inc()
                raise
        finally:
            self.swapping = False
            self._swap_lock.release()

    def rollback(self) -> int:
        """Restore the RETAINED previous generation (the fleet canary
        auto-rollback): an instant, disk-free ``(params, generation)``
        flip back to exactly the tuple the last :meth:`swap` displaced —
        bit-exact by construction, generation STAMP restored too, so a
        rolled-back fleet is indistinguishable from one that never
        swapped.  One-shot: the retained tuple is consumed.  Raises
        RuntimeError when nothing is retained or a swap is mid-flight
        (the live generation is never disturbed either way)."""
        if not self._swap_lock.acquire(blocking=False):
            raise RuntimeError("swap in progress — rollback refused")
        try:
            if self._previous is None:
                raise RuntimeError(
                    "no previous generation retained (nothing was "
                    "swapped, or it was already rolled back)")
            params, gen, path = self._previous
            self._previous = None
            self._active = (params, gen)
            self.snapshot_path = path
            self._m["rollbacks"].inc()
            return gen
        finally:
            self._swap_lock.release()

    def enable_generation(self, page_size: int, num_pages: int,
                          slots: int, prefill_chunk: int,
                          prefix_cache: bool = True,
                          prefill_rungs=None, decode_rungs=None
                          ) -> "GenerationRunner":
        """Build the autoregressive generation path (ISSUE 16, paged
        since ISSUE 19): a block-paged KV pool with prefix reuse plus
        jitted prefill-chunk/decode/copy functions (sampling fused)
        over this runner's live params.  Idempotent per runner; returns
        the :class:`GenerationRunner`."""
        if self.gen_runner is None:
            self.gen_runner = GenerationRunner(
                self, page_size=page_size, num_pages=num_pages,
                slots=slots, prefill_chunk=prefill_chunk,
                prefix_cache=prefix_cache, prefill_rungs=prefill_rungs,
                decode_rungs=decode_rungs)
        return self.gen_runner

    def jit_cache_size(self) -> Optional[int]:
        """jax's own executable-cache entry count for the jitted forward
        (the jax._src pjit cache behind ``_cache_size``); None where the
        jax version does not expose it.  After warmup this equals
        ``compiles`` and must stay put."""
        try:
            return int(self._fwd._cache_size())
        except Exception:               # pragma: no cover - jax-version dep
            return None

    # -- AOT executable cache (ISSUE 17) ---------------------------------------

    def enable_aot_cache(self, directory: str = "") -> bool:
        """Arm the on-disk AOT executable cache (serving/aot_cache.py):
        warmup and dispatch misses probe the cache before compiling,
        and fresh compiles are serialized back.  ``directory`` defaults
        to ``aot_cache/`` next to this runner's snapshot.  False (and
        inert) when this jax build cannot serialize executables —
        serving falls back to compile-every-boot, nothing breaks."""
        from znicz_tpu.serving import aot_cache

        if not aot_cache.available():
            return False
        if not directory:
            if not self.snapshot_path:
                raise ValueError(
                    "enable_aot_cache needs an explicit directory when "
                    "the runner was not booted from a snapshot")
            directory = aot_cache.dir_for_snapshot(self.snapshot_path)
        self._aot_cache = aot_cache.ExecutableCache(
            directory, aot_cache.family_key(self))
        return True

    @property
    def aot_enabled(self) -> bool:
        return self._aot_cache is not None

    def _aot_exec(self, table: Dict, key, entry: Dict, jitfn, args):
        """AOT-mode dispatch for one executable: replay the table,
        else deserialize from the cache (VALIDATED by executing it
        where donation allows — a loaded executable that cannot run
        this very call is refused and recompiled, never trusted), else
        ``lower().compile()`` explicitly and serialize the result.
        The explicit lower path traces (ticking ``compiles``) but
        never touches jax's implicit jit call cache — the strictness
        lever behind :meth:`warm_proof`.  Shared by the scoring
        forward and the GenerationRunner's three jits (their tables
        differ; the cache + accounting is the runner's)."""
        fn = table.get(key)
        if fn is not None:
            return fn(*args)
        cache = self._aot_cache
        fn = cache.load(entry)
        if fn is not None:
            if self.donate:
                # donated buffers would be consumed by a validation
                # call; the content digest + key check already pin the
                # aval signature, so trust the decode on this path
                table[key] = fn
                self._warm["hits"] += 1
                cache.hit()
                return fn(*args)
            try:
                out = fn(*args)
            except Exception as exc:
                cache.refuse(entry, exc)
            else:
                table[key] = fn
                self._warm["hits"] += 1
                cache.hit()
                return out
        compiled = jitfn.lower(*args).compile()
        cache.store(entry, compiled)
        table[key] = compiled
        self._warm["misses"] += 1
        cache.miss()
        return compiled(*args)

    def _fwd_call(self, params, x_dev):
        """The forward dispatch every scoring path funnels through
        (infer_staged AND swap's warm loop): plain jit call until
        :meth:`enable_aot_cache`, the AOT table after."""
        if self._aot_cache is None:
            return self._fwd(params, x_dev)
        key = (tuple(int(d) for d in x_dev.shape), str(x_dev.dtype))
        entry = {"kind": "fwd", "shape": list(key[0]), "dtype": key[1]}
        return self._aot_exec(self._aot, key, entry, self._fwd,
                              (params, x_dev))

    @property
    def warm_source(self) -> Optional[str]:
        """Where this boot's executables came from: ``cache_hit``
        (all loaded), ``compiled`` (all traced), ``mixed``, or None
        before any warmup — the per-replica heartbeat/panel label."""
        h, m = self._warm["hits"], self._warm["misses"]
        if h and m:
            return "mixed"
        if h:
            return "cache_hit"
        if m or self.compiles:
            return "compiled"
        return None

    def warm_proof(self, expected: int) -> Dict:
        """The strict warm-family proof /readyz gates on (ISSUE 17,
        same discipline as PR 15's jit-cache equality): ``expected``
        is the full executable family size (ladder buckets + the
        generation family).  AOT mode proves ``loaded == expected``
        AND jax's own jit caches are EMPTY (zero implicit traces
        slipped past the tables); jit mode proves the PR-15 equality
        ``compiles == expected == jit_cache_size``."""
        gen = self.gen_runner
        jit_total = self.jit_cache_size() or 0
        if gen is not None:
            jit_total += gen.jit_cache_size() or 0
        if self.aot_enabled:
            loaded = len(self._aot) + (len(gen._aot)
                                       if gen is not None else 0)
            ok = loaded == int(expected) and jit_total == 0
            mode = "aot"
        else:
            loaded = jit_total
            ok = self.compiles == int(expected) == jit_total
            mode = "jit"
        cache = self._aot_cache
        return {"mode": mode, "expected": int(expected),
                "loaded": int(loaded), "compiles": int(self.compiles),
                "jit_cache_size": int(jit_total),
                "cache_hits": int(self._warm["hits"]),
                "cache_misses": int(self._warm["misses"]),
                "cache_refusals": int(cache.counts["refusals"])
                if cache is not None else 0,
                "warm_source": self.warm_source, "ok": bool(ok)}

    def stats(self) -> Dict:
        return {"compiles": self.compiles,
                "aot_enabled": self.aot_enabled,
                "aot_loaded": len(self._aot),
                "warm_source": self.warm_source,
                "warm_hits": int(self._warm["hits"]),
                "warm_misses": int(self._warm["misses"]),
                "jit_cache_size": self.jit_cache_size(),
                "generation": self.generation,
                "swapping": self.swapping,
                "swaps": self.swaps,
                "swap_failures": self.swap_failures,
                "rollbacks": self.rollbacks,
                "stage_copies": self.stage_copies,
                "snapshot_path": self.snapshot_path,
                "previous_retained": self._previous is not None,
                "sample_shape": list(self.sample_shape),
                "dtype": str(self.dtype),
                "mesh": self.mesh_shape,
                "device_count": self.device_count}


def batch_rungs(max_batch: int) -> Tuple[int, ...]:
    """Power-of-two batch rungs up to and including ``max_batch`` —
    the default prefill/decode coalescing ladder."""
    n = int(max_batch)
    rungs = []
    r = 1
    while r < n:
        rungs.append(r)
        r *= 2
    rungs.append(n)
    return tuple(rungs)


def _sample_tokens(logits, temp, top_k, seeds, t):
    """Fused in-graph sampling (ISSUE 19): greedy argmax where
    ``temp <= 0`` (tie -> lowest id, matching the host sampler bit for
    bit), else seeded gumbel-max over the optional per-row top-k cut.
    ``seeds`` is (b,) uint32; each row's key is
    ``fold_in(PRNGKey(seed), t)`` — deterministic per (request seed,
    position), independent of co-batched neighbors and batch padding.
    Returns ((b,) int32 tokens, (b,) f32 logprob of the chosen token
    under the raw logits)."""
    import jax
    import jax.numpy as jnp

    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    z = logits / jnp.maximum(temp, 1e-20)[:, None]
    srt = jnp.sort(z, axis=-1)                         # ascending
    kk = jnp.clip(jnp.where(top_k > 0, top_k, v), 1, v)
    kth = srt[jnp.arange(b), v - kk]                   # kth-largest
    z = jnp.where(z < kth[:, None], -jnp.inf, z)

    def noise(seed, pos):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
        return jax.random.gumbel(key, (v,), jnp.float32)

    sampled = jnp.argmax(z + jax.vmap(noise)(seeds, t),
                         axis=-1).astype(jnp.int32)
    tok = jnp.where(temp > 0, sampled, greedy)
    logp = jax.nn.log_softmax(logits, axis=-1)[jnp.arange(b), tok]
    return tok, logp


class PrefixCache:
    """Page-granularity content-addressed prefix index (ISSUE 19).

    Pages are keyed by a CHAIN hash: page ``i`` of a prompt hashes
    (hash of pages ``[0..i)``, tokens ``[i*ps .. (i+1)*ps)``), so a
    lookup can only match a page whose ENTIRE preceding context matches
    too — content addressing over the prefix, not the page in
    isolation.  The index holds one refcount on every registered page;
    requests that hit share the page READ-ONLY (refcount++), and the
    first divergent append copy-on-writes (scheduler-driven, via
    :meth:`GenerationRunner.copy_page`).  Eviction is LRU over entries
    nobody but the index holds (refcount == 1) and runs only under
    allocation pressure — a cached page costs nothing until the pool
    actually wants it back.

    Bit-exactness: a hit replays k/v that the SAME prefill executable
    grid computed (registration indexes only canonically-computed
    pages — a COW'd recompute page is skipped because its hash is
    already indexed), so with ``prefill_chunk == page_size`` a
    prefix-hit generation decodes bit-identically to a cold one."""

    def __init__(self, gen: "GenerationRunner"):
        from collections import OrderedDict

        self.gen = gen
        #: chain-hash -> page id, in LRU order (move_to_end on hit)
        self._index = OrderedDict()
        self._by_page: Dict[int, bytes] = {}

    def __len__(self) -> int:
        return len(self._index)

    def _hashes(self, prompt):
        """Chain hashes of every FULL page of ``prompt``."""
        import hashlib

        ps = self.gen.page_size
        out = []
        h = b"znicz-prefix-v1"
        for i in range(len(prompt) // ps):
            h = hashlib.blake2b(
                h + np.asarray(prompt[i * ps:(i + 1) * ps],
                               np.int32).tobytes(),
                digest_size=16).digest()
            out.append(h)
        return out

    def lookup(self, prompt):
        """Claim the longest indexed run of ``prompt``'s full pages:
        returns ``(pages, covered_tokens)`` with one reference taken on
        each matched page (the request's own; drop via
        ``release_pages``)."""
        pages = []
        for h in self._hashes(prompt):
            page = self._index.get(h)
            if page is None:
                break
            self._index.move_to_end(h)
            self.gen.addref(page)
            pages.append(page)
        covered = len(pages) * self.gen.page_size
        m = self.gen._pm
        if pages:
            m["hits"].inc()
            m["tokens_avoided"].inc(covered)
            m["flops_avoided"].inc(covered * self.gen.flops_per_token)
        else:
            m["misses"].inc()
        return pages, covered

    def register(self, prompt, pages) -> None:
        """Index ``prompt``'s full pages once its prefill completed.
        Already-indexed hashes keep their existing page (first writer
        wins); fresh ones take one index-owned reference on the
        request's page."""
        for i, h in enumerate(self._hashes(prompt)):
            if h in self._index or pages[i] in self._by_page:
                continue
            self.gen.addref(pages[i])
            self._index[h] = pages[i]
            self._by_page[pages[i]] = h

    def evict_one(self) -> bool:
        """Drop the least-recently-used entry whose page only the index
        holds (refcount == 1) — frees exactly one page.  False when
        every indexed page is currently shared with a live request."""
        for h, page in self._index.items():
            if self.gen.page_ref[page] == 1:
                del self._index[h]
                del self._by_page[page]
                self.gen.decref(page)
                self.gen._pm["evictions"].inc()
                # structured journal (ISSUE 20): eviction with the
                # pressure numbers — the counter above only counts
                from znicz_tpu import telemetry
                telemetry.emit(
                    "prefix_evict", "serving", page=int(page),
                    indexed=len(self._index),
                    kv_occupancy=round(self.gen.occupancy(), 4))
                return True
        return False


class GenerationRunner:
    """The autoregressive generation compute plane (ISSUE 16), block-
    paged with prefix reuse and fused sampling (ISSUE 19).

    **Pool**: per attention layer, ONE ``(num_pages + 1, page_size,
    heads, head_dim)`` device array for keys and one for values —
    committed at creation (an uncommitted first-call pool leaves a
    stale lowering per shape that jax silently re-lowers under
    steady-state traffic).  Page index ``num_pages`` is SCRATCH — pad
    batch rows gather from and scatter into it, so a pad row can never
    touch a real request's page and every real row stays a pure
    function of its own pages (the per-decoded-token bit-exactness
    contract rides on this).  A request's cache is a host-side page
    list; dispatches carry it as a (batch, P) int32 page table padded
    to power-of-two page-count rungs ``P``.  Growing a request's cache
    is a host-side list append — the old per-rung slot pools and the
    rung-migration executable family are gone entirely; one whole-page
    COPY executable remains, for copy-on-write.

    **Prefix reuse**: :class:`PrefixCache` — full pages are content-
    addressed by a chain hash of the tokens they hold, shared read-only
    across requests via refcount, copy-on-write on the first divergent
    append.

    **Executables** (all tick the owning runner's ``compiles`` counter,
    so the serving gates' zero-recompile proof covers generation):

      - prefill: one per (prefill batch rung x page rung) — runs the
        forward over ONE fixed-width ``prefill_chunk`` token chunk at
        per-row global offsets ``t0`` (long prompts prefill across
        ticks, the cache carried by the page table — chunked prefill
        bounds the work any single tick can absorb), scatters the
        chunk's k/v into the pool (pad tokens -> scratch), and samples
        each row's next token at its last real position in-graph;
      - decode: one per (decode batch rung x page rung) — gathers the
        co-batched requests' pages, appends this step's k/v row at each
        row's own depth ``t``, attends the length-1 query over
        ``[0..t]``, samples in-graph.  O(t) per token;
      - copy: whole-page copy (src -> dst), the COW move.

    Sampling is FUSED into both compute executables — they return
    ``(tokens, logprobs, logits, pools)`` and transfers happen per
    FETCHED array, so the scheduler's on-device-sampling mode ships
    (b,) int32 tokens instead of (b, vocab) logits per tick.  The
    executable family is one and the same either way, which makes
    greedy bit-identity across the knob free and keeps
    ``return_logits`` costless until requested.

    Single-device only (the serving mesh and generation compose
    later); compute calls are serialized by the frontend's compute
    thread — page bookkeeping is not locked, by that contract."""

    def __init__(self, runner: ModelRunner, page_size: int,
                 num_pages: int, slots: int, prefill_chunk: int,
                 prefix_cache: bool = True, prefill_rungs=None,
                 decode_rungs=None):
        import jax
        import jax.numpy as jnp

        from znicz_tpu import telemetry
        from znicz_tpu.attention import (CharEmbedding, MultiHeadAttention,
                                         SeqAll2All)
        from znicz_tpu.ops.attention import paged_append, paged_gather
        from znicz_tpu.ops.linear import seq_linear

        if runner.mesh is not None:
            raise ValueError(
                "generation serving is single-device for now (the "
                "KV-cache pool does not shard); drop "
                "root.common.serving.mesh for this replica")
        self.runner = runner
        tr = runner._trainer
        forwards = runner.workflow.forwards
        last = forwards[-1]
        if not forwards or not isinstance(forwards[0], CharEmbedding):
            raise ValueError(
                "generation serving needs a CharEmbedding first unit "
                "(token ids in, one position per token)")
        if not isinstance(last, tr._seq_softmax_cls):
            raise ValueError(
                "generation serving needs a per-position softmax head "
                "(SeqAll2AllSoftmax) as the last unit")
        self._attn = []
        for f in forwards[1:-1]:
            if isinstance(f, MultiHeadAttention):
                if not f.causal:
                    raise ValueError(
                        f"{f.name}: generation requires causal "
                        f"attention (a KV cache IS the causal prefix)")
                self._attn.append(f)
            elif isinstance(f, (SeqAll2All, tr._dropout_cls)):
                pass                       # position-wise / eval-identity
            else:
                raise ValueError(
                    f"{f.name}: unit {type(f).__name__} has no decode "
                    f"form — generation serves CharEmbedding + causal "
                    f"MultiHeadAttention + SeqAll2All* stacks")
        if not self._attn:
            raise ValueError("generation serving needs at least one "
                             "MultiHeadAttention unit (nothing to cache)")
        self.max_len = int(forwards[0].max_len)
        self.page_size = int(page_size)
        if self.page_size < 2:
            raise ValueError(f"page_size must be >= 2, got {page_size}")
        self.num_pages = int(num_pages)
        pages_per_seq = -(-self.max_len // self.page_size)
        if self.num_pages < pages_per_seq:
            raise ValueError(
                f"num_pages={num_pages} cannot hold one full context "
                f"window ({pages_per_seq} pages of {self.page_size} "
                f"for max_len={self.max_len})")
        #: scratch page index — pad rows' page; never allocated
        self.scratch = self.num_pages
        rungs = []
        r = 1
        while r < pages_per_seq:
            rungs.append(r)
            r *= 2
        rungs.append(r)
        #: page-table width rungs: powers of two up to a full context's
        #: page count — the executable family's second axis
        self.page_rungs = tuple(rungs)
        #: the context window: positions ``[0 .. max_ctx)`` are the most
        #: any one request (prompt + generated) may occupy
        self.max_ctx = self.max_len
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.slots = int(slots)
        if self.slots < 1:
            raise ValueError("generation needs >= 1 concurrency slot")
        self.prefill_rungs = tuple(prefill_rungs) if prefill_rungs \
            else batch_rungs(4)
        self.decode_rungs = tuple(decode_rungs) if decode_rungs \
            else batch_rungs(self.slots)
        shapes = {f.name: (f.heads, f.head_dim) for f in self._attn}
        #: the pool: {layer: (num_pages+1, page_size, heads, dim)} x (k, v)
        # commit the fresh pools to an explicit device: every later pool
        # array is a COMMITTED donated jit output, and an uncommitted
        # first-call pool would leave one stale lowering that jax
        # silently re-lowers (cache growth without a retrace) the first
        # time steady-state traffic replays that shape
        dev = jax.local_devices()[0]
        self.pk = {n: jax.device_put(
                       jnp.zeros((self.num_pages + 1, self.page_size,
                                  h, d), jnp.float32), dev)
                   for n, (h, d) in shapes.items()}
        self.pv = {n: jax.device_put(
                       jnp.zeros((self.num_pages + 1, self.page_size,
                                  h, d), jnp.float32), dev)
                   for n, (h, d) in shapes.items()}
        #: host-side page allocator (compute-thread only, like the old
        #: slot free lists): free stack + per-page refcounts
        self._free_pages = list(range(self.num_pages))
        self.page_ref = np.zeros(self.num_pages, np.int32)
        #: ~2 flops per weight per token — the prefill-FLOPs-avoided
        #: counter's conversion rate
        self.flops_per_token = 2 * sum(
            int(arr.mem.size) for f in forwards
            for arr in f.params().values() if arr.mem is not None)
        _pc = telemetry.scope("prefix_cache")
        self._pm = {
            "hits": _pc.counter(
                "hits", "prompt prefix lookups that matched (>= 1 "
                "full page shared)"),
            "misses": _pc.counter(
                "misses", "prompt prefix lookups that matched nothing"),
            "evictions": _pc.counter(
                "evictions", "indexed prefix pages evicted under "
                "allocation pressure (LRU, idle entries only)"),
            "tokens_avoided": _pc.counter(
                "tokens_avoided", "prompt tokens NOT prefilled thanks "
                "to prefix-page hits"),
            "flops_avoided": _pc.counter(
                "flops_avoided", "prefill flops avoided by prefix "
                "reuse (tokens_avoided x ~2 flops/weight)"),
        }
        _pc.gauge("indexed_pages", "pages held by the prefix index",
                  fn=telemetry.weak_fn(
                      self, lambda s: float(len(s.prefix))
                      if s.prefix is not None else 0.0))
        _pc.gauge("shared_pages", "pages referenced by > 1 holder",
                  fn=telemetry.weak_fn(
                      self, lambda s: float((s.page_ref > 1).sum())))
        _pc.gauge("page_occupancy", "allocated pages / pool pages",
                  fn=telemetry.weak_fn(self, lambda s: s.occupancy()))
        self.prefix = PrefixCache(self) if prefix_cache else None
        compiles = runner._m["compiles"]
        seq_softmax = tr._seq_softmax_cls
        dropout = tr._dropout_cls
        n_pages, psz = self.num_pages, self.page_size

        def run_prefill(params, pk, pv, table, x, t0, n_new,
                        temp, top_k, seeds):
            compiles.inc()      # znicz: ignore[jit-purity] — trace tick
            toks = tr._decode(x)
            h = None
            rows = {}
            for f in forwards:
                p = params.get(f.name, {})
                if isinstance(f, CharEmbedding):
                    h = f.apply_offset(p, toks, t0)
                elif isinstance(f, MultiHeadAttention):
                    h, k_rows, v_rows = f.apply_prefill_chunk(
                        p, h, paged_gather(pk[f.name], table),
                        paged_gather(pv[f.name], table), t0)
                    rows[f.name] = (k_rows, v_rows)
                elif f is last and isinstance(f, seq_softmax):
                    h = seq_linear(h, p["weights"], p.get("bias"),
                                   weights_transposed=f.weights_transposed)
                elif isinstance(f, dropout):
                    pass
                else:
                    h = f.apply(p, h)
            b, c = x.shape[:2]
            width = table.shape[1]
            logits = h[jnp.arange(b), n_new - 1]
            # persist the chunk's k/v: token j of row i lands on page
            # table[i, (t0+j) // page_size] at offset (t0+j) %
            # page_size; pad tokens (j >= n_new) land on scratch
            pos = t0[:, None] + jnp.arange(c)
            page = table[jnp.arange(b)[:, None],
                         jnp.clip(pos // psz, 0, width - 1)]
            page = jnp.where(jnp.arange(c)[None, :] < n_new[:, None],
                             page, n_pages)
            off = pos % psz
            pk = {n: pk[n].at[page, off].set(rows[n][0]) for n in pk}
            pv = {n: pv[n].at[page, off].set(rows[n][1]) for n in pv}
            tok, logp = _sample_tokens(logits, temp, top_k, seeds,
                                       t0 + n_new - 1)
            return tok, logp, logits, pk, pv

        def run_decode(params, pk, pv, table, tokens, t,
                       temp, top_k, seeds):
            compiles.inc()      # znicz: ignore[jit-purity] — trace tick
            toks = tr._decode(tokens)
            h = None
            rows = {}
            for f in forwards:
                p = params.get(f.name, {})
                if isinstance(f, CharEmbedding):
                    h = f.apply_decode(p, toks, t)
                elif isinstance(f, MultiHeadAttention):
                    h, k_row, v_row = f.apply_decode(
                        p, h, paged_gather(pk[f.name], table),
                        paged_gather(pv[f.name], table), t)
                    rows[f.name] = (k_row, v_row)
                elif f is last and isinstance(f, seq_softmax):
                    h = seq_linear(h, p["weights"], p.get("bias"),
                                   weights_transposed=f.weights_transposed)
                elif isinstance(f, dropout):
                    pass
                else:
                    h = f.apply(p, h)
            logits = h[:, 0]
            pk = {n: paged_append(pk[n], table, rows[n][0], t)
                  for n in pk}
            pv = {n: paged_append(pv[n], table, rows[n][1], t)
                  for n in pv}
            tok, logp = _sample_tokens(logits, temp, top_k, seeds, t)
            return tok, logp, logits, pk, pv

        def run_copy(pk, pv, src, dst):
            compiles.inc()      # znicz: ignore[jit-purity] — trace tick
            pk = {n: pk[n].at[dst].set(pk[n][src]) for n in pk}
            pv = {n: pv[n].at[dst].set(pv[n][src]) for n in pv}
            return pk, pv

        dn = runner.donate
        self._prefill = jax.jit(run_prefill,
                                donate_argnums=(1, 2) if dn else ())
        self._decode = jax.jit(run_decode,
                               donate_argnums=(1, 2) if dn else ())
        self._copy = jax.jit(run_copy,
                             donate_argnums=(0, 1) if dn else ())
        #: AOT dispatch table (ISSUE 17), keyed ("prefill", b, P) /
        #: ("decode", b, P) / ("copy",) — the same grid warmup() walks,
        #: so a cache-warm boot loads the whole generation family
        #: through the owning runner's _aot_exec
        self._aot: Dict = {}

    # -- page bookkeeping (compute-thread only) --------------------------------

    def _page_rung(self, n_pages: int) -> int:
        """Smallest page-table width rung holding ``n_pages`` pages."""
        for r in self.page_rungs:
            if r >= n_pages:
                return r
        raise ValueError(
            f"{n_pages} pages exceed the top rung "
            f"{self.page_rungs[-1]} — the context window bounds this")

    def alloc_page(self) -> Optional[int]:
        """Claim one free page (refcount 1).  Under pressure, evict an
        idle prefix-index page LRU-first; None when every page is held
        by a live request (the scheduler stalls that row a tick)."""
        if not self._free_pages and self.prefix is not None:
            self.prefix.evict_one()
        if not self._free_pages:
            return None
        page = self._free_pages.pop()
        self.page_ref[page] = 1
        return page

    def addref(self, page: int) -> None:
        """One more holder of a shared (read-only) page."""
        self.page_ref[page] += 1

    def decref(self, page: int) -> None:
        """Drop one reference; the page frees at zero."""
        self.page_ref[page] -= 1
        assert self.page_ref[page] >= 0, f"page {page} over-released"
        if self.page_ref[page] == 0:
            self._free_pages.append(page)

    def release_pages(self, pages) -> None:
        """Return a finished/failed request's page references
        immediately — the continuous-batching lever: pages shared with
        the prefix index or other requests survive via their remaining
        refs; private ones are claimable this very tick."""
        for page in pages:
            self.decref(page)

    def pages_active(self) -> int:
        return self.num_pages - len(self._free_pages)

    def pages_leaked(self) -> int:
        """Invariant probe (must be 0): pages neither free nor
        referenced are lost to the allocator forever."""
        return int(self.num_pages - len(self._free_pages)
                   - int((self.page_ref > 0).sum()))

    def occupancy(self) -> float:
        """Allocated pages / pool pages, the KV-pool pressure gauge."""
        return self.pages_active() / float(self.num_pages)

    # -- compute (compute-thread only) -----------------------------------------

    def _batch_rung(self, rungs, n: int) -> int:
        for r in rungs:
            if r >= n:
                return r
        raise ValueError(f"batch of {n} exceeds top rung {rungs[-1]}"
                         f" — the scheduler chunks above this")

    def _run_jit(self, key, jitfn, args):
        """One generation dispatch: plain jit call until the owning
        runner armed its AOT cache, the shared AOT table after.  The
        key's ints are both the table key and the cache entry; the
        entry also carries the paged geometry, so cache entries from a
        differently-paged boot can never collide."""
        r = self.runner
        if r._aot_cache is None:
            return jitfn(*args)
        entry = {"kind": key[0], "key": [int(k) for k in key[1:]],
                 "paged": [self.page_size, self.num_pages,
                           self.prefill_chunk]}
        return r._aot_exec(self._aot, key, entry, jitfn, args)

    def _table(self, page_lists, b: int) -> np.ndarray:
        """Pad per-row page lists into the (b, P) int32 dispatch table:
        P is the page rung over the widest row, unused slots point at
        scratch (positions there sit past every row's fill, so masking
        never lets them matter)."""
        width = self._page_rung(max([len(p) for p in page_lists] + [1]))
        tbl = np.full((b, width), self.scratch, np.int32)
        for i, pages in enumerate(page_lists):
            tbl[i, :len(pages)] = pages
        return tbl

    def _sampling_args(self, b, temps, top_ks, seeds):
        tp = np.zeros((b,), np.float32)
        tp[:len(temps)] = temps
        tk = np.zeros((b,), np.int32)
        tk[:len(top_ks)] = top_ks
        sd = np.zeros((b,), np.uint32)
        sd[:len(seeds)] = seeds
        return tp, tk, sd

    def prefill_async(self, x: np.ndarray, t0s, n_new, page_lists,
                      temps, top_ks, seeds):
        """Dispatch one prefill CHUNK over co-batched rows — row ``i``
        holds prompt tokens ``x[i, :n_new[i]]`` at global positions
        starting ``t0s[i]``, its cache (covering ``[0 .. t0+n_new)``)
        listed in ``page_lists[i]`` — WITHOUT syncing results back:
        returns ((b,) DEVICE next tokens, (b,) DEVICE logprobs,
        (b, vocab) DEVICE logits, snapshot generation).  Rows pad to a
        prefill batch rung against the scratch page.  The sampled
        token is the row's next token only when this chunk completes
        its prompt — intermediate chunks' samples are discarded."""
        n, c = x.shape
        if c != self.prefill_chunk:
            raise ValueError(f"chunk width {c} != prefill_chunk "
                             f"{self.prefill_chunk}")
        b = self._batch_rung(self.prefill_rungs, n)
        xb = np.zeros((b, c), self.runner.dtype)
        xb[:n] = x
        t0 = np.zeros((b,), np.int32)
        t0[:n] = t0s
        nn = np.ones((b,), np.int32)
        nn[:n] = n_new
        tbl = self._table(list(page_lists) + [[]] * (b - n), b)
        tp, tk, sd = self._sampling_args(b, temps, top_ks, seeds)
        self.runner._maybe_stall()
        params, gen = self.runner._active
        tok, logp, logits, self.pk, self.pv = self._run_jit(
            ("prefill", b, tbl.shape[1]), self._prefill,
            (params, self.pk, self.pv, tbl, xb, t0, nn, tp, tk, sd))
        return tok, logp, logits, gen

    def prefill(self, x: np.ndarray, t0s, n_new, page_lists,
                temps, top_ks, seeds):
        """Synchronous :meth:`prefill_async` (host arrays, sliced to
        the real rows)."""
        tok, logp, logits, gen = self.prefill_async(
            x, t0s, n_new, page_lists, temps, top_ks, seeds)
        n = len(page_lists)
        return (np.asarray(tok)[:n], np.asarray(logp)[:n],
                np.asarray(logits)[:n], gen)

    def decode_async(self, page_lists, tokens, ts, temps, top_ks,
                     seeds):
        """Dispatch one decode step over co-batched requests — feed
        each row's ``tokens[i]`` at its own depth ``ts[i]``, append
        k/v into its paged cache — WITHOUT syncing results back:
        returns ((b,) DEVICE next tokens, (b,) DEVICE logprobs,
        (b, vocab) DEVICE logits, snapshot generation).  The scheduler
        dispatches every chunk of a tick before fetching any, so chunk
        N's compute overlaps chunk N-1's host-side emit."""
        n = len(page_lists)
        b = self._batch_rung(self.decode_rungs, n)
        tbl = self._table(list(page_lists) + [[]] * (b - n), b)
        tk_in = np.zeros((b,), self.runner.dtype)
        tk_in[:n] = tokens
        tt = np.zeros((b,), np.int32)
        tt[:n] = ts
        tp, tk, sd = self._sampling_args(b, temps, top_ks, seeds)
        self.runner._maybe_stall()
        params, gen = self.runner._active
        tok, logp, logits, self.pk, self.pv = self._run_jit(
            ("decode", b, tbl.shape[1]), self._decode,
            (params, self.pk, self.pv, tbl, tk_in, tt, tp, tk, sd))
        return tok, logp, logits, gen

    def decode(self, page_lists, tokens, ts, temps, top_ks, seeds):
        """Synchronous :meth:`decode_async` (host arrays, sliced to
        the real rows)."""
        tok, logp, logits, gen = self.decode_async(
            page_lists, tokens, ts, temps, top_ks, seeds)
        n = len(page_lists)
        return (np.asarray(tok)[:n], np.asarray(logp)[:n],
                np.asarray(logits)[:n], gen)

    def copy_page(self, src: int, dst: int) -> None:
        """Whole-page copy (the COW move): duplicate page ``src`` into
        ``dst`` across every layer's k and v pools.  Reference
        bookkeeping is the caller's."""
        self.pk, self.pv = self._run_jit(
            ("copy",), self._copy,
            (self.pk, self.pv, np.int32(src), np.int32(dst)))

    # -- contract surface ------------------------------------------------------

    def executables(self) -> int:
        """The warmed generation executable count — the zero-recompile
        gate's expected jit-cache contribution."""
        return ((len(self.prefill_rungs) + len(self.decode_rungs))
                * len(self.page_rungs) + 1)

    def warmup(self) -> int:
        """Compile the full generation executable family up front (all
        rows against the scratch page — no real page is touched);
        returns the owning runner's total ``compiles`` afterwards."""
        c = self.prefill_chunk
        for b in self.prefill_rungs:
            for width in self.page_rungs:
                self.prefill(np.zeros((b, c), self.runner.dtype),
                             np.zeros(b, np.int32),
                             np.ones(b, np.int32),
                             [[self.scratch] * width] * b,
                             np.zeros(b, np.float32),
                             np.zeros(b, np.int32),
                             np.zeros(b, np.uint32))
        for b in self.decode_rungs:
            for width in self.page_rungs:
                self.decode([[self.scratch] * width] * b,
                            np.zeros(b, np.int64),
                            np.zeros(b, np.int32),
                            np.zeros(b, np.float32),
                            np.zeros(b, np.int32),
                            np.zeros(b, np.uint32))
        self.copy_page(self.scratch, self.scratch)
        return self.runner.compiles

    def jit_cache_size(self) -> Optional[int]:
        """Sum of jax's own cache entries across the three generation
        jits (None where the jax version hides it) — after warmup this
        equals :meth:`executables` and must stay put."""
        try:
            return int(self._prefill._cache_size()
                       + self._decode._cache_size()
                       + self._copy._cache_size())
        except Exception:           # pragma: no cover - jax-version dep
            return None

    def stats(self) -> Dict:
        return {"page_size": self.page_size,
                "num_pages": self.num_pages,
                "page_rungs": list(self.page_rungs),
                "prefill_chunk": self.prefill_chunk,
                "max_ctx": self.max_ctx,
                "slots": self.slots,
                "prefill_rungs": list(self.prefill_rungs),
                "decode_rungs": list(self.decode_rungs),
                "pages_active": self.pages_active(),
                "pages_free": len(self._free_pages),
                "pages_shared": int((self.page_ref > 1).sum()),
                "pages_leaked": self.pages_leaked(),
                "prefix_enabled": self.prefix is not None,
                "prefix_pages": (len(self.prefix)
                                 if self.prefix is not None else 0),
                "prefix_hits": int(self._pm["hits"].value),
                "prefix_misses": int(self._pm["misses"].value),
                "prefix_evictions": int(self._pm["evictions"].value),
                "prefix_tokens_avoided":
                    int(self._pm["tokens_avoided"].value),
                "occupancy": self.occupancy(),
                "executables": self.executables(),
                "aot_loaded": len(self._aot),
                "jit_cache_size": self.jit_cache_size()}
