"""ModelRunner: a trained workflow frozen into an inference-only jitted
forward (ISSUE 4).

The forward IS ``FusedTrainer.forward_pass`` with ``train=False`` — the
same pure composition of the units' own ``apply`` code the training fast
path differentiates, so serving computes exactly the function training
optimized (the batched-vs-unbatched 0-ULP parity test in
tests/test_serving.py rides on the row-independence of that graph).
Params are extracted once at construction and pinned on device; every
call passes them as an un-donated operand, so one params tree serves
every bucket's executable.

**Bucketed jit cache**: the runner jits ONE function of ``(params, x)``;
each distinct padded batch shape (a ladder rung) compiles exactly once
and is a cache hit forever after.  ``compiles`` counts TRACES — the
counter ticks inside the traced function body, which Python only runs
when jax actually (re)traces, i.e. once per cache entry — and
``jit_cache_size()`` cross-checks it against jax's own pjit cache, so
"zero recompiles after warmup" is provable from the outside
(bench.py --serve's gate).

**Donated ping-pong staging**: ``stage`` starts an async host->device
put and ``infer_staged`` DONATES that buffer into the jitted call
(``donate_argnums``), so at any moment at most two input buffers exist —
the one the device is consuming (its memory reusable for activations
the instant the gather reads it) and the one the next batch is staging
into.  The frontend's compute loop overlaps stage(N+1) with compute(N),
the same overlap discipline as ``loader/ingest.py``'s prefetch.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from znicz_tpu.telemetry.metrics import registered_property


class ModelRunner:
    """Freeze a built+initialized workflow's params into a jitted
    inference forward.  ``snapshot`` restores params first (the
    snapshotter's inference-load path — no velocities, no trainer
    state).  The output is the last unit's output: LOGITS for a softmax
    head (clients softmax if they want probabilities), the raw
    reconstruction for MSE heads."""

    def __init__(self, workflow, snapshot: str = "",
                 donate: Optional[bool] = None):
        import jax

        from znicz_tpu.parallel.fused import FusedTrainer

        if donate is None:
            # donation is a TPU/GPU lever; the CPU runtime ignores it
            # (and warns per compile), so auto-resolve by backend — the
            # serving STRUCTURE (stage N+1 while N computes) is identical
            # either way, only the buffer reuse is backend-dependent
            donate = jax.default_backend() != "cpu"
        self.donate = bool(donate)

        if snapshot:
            from znicz_tpu import snapshotter

            snapshotter.load_inference(workflow, snapshot)
        self.workflow = workflow
        self._trainer = FusedTrainer(workflow)
        self.params = self._trainer.extract_params()
        #: per-sample input shape the service accepts (requests carry
        #: (n, *sample_shape) arrays)
        self.sample_shape: Tuple[int, ...] = tuple(
            int(d) for d in workflow.forwards[0].input.shape[1:])
        mem = getattr(workflow.loader.original_data, "mem", None)
        #: staging dtype — u8 datasets keep their 1-byte wire/HBM form,
        #: the in-graph decode (trainer._decode) widens on device
        self.dtype = np.dtype(mem.dtype) if mem is not None \
            else np.dtype(np.float32)
        from znicz_tpu import telemetry

        _sc = telemetry.scope("model")
        #: traces of _fwd == cache entries (registry counter; the
        #: ``compiles`` property preserves the historical name)
        self._m = {"compiles": _sc.counter(
            "compiles",
            "traces of the jitted forward == jit cache entries")}
        compiles = self._m["compiles"]
        key = self._trainer._key0       # eval path never consumes it

        def fwd(params, x):
            # trace-time tick: Python runs this body once per compile
            # (cache hits replay the compiled executable only)
            compiles.inc()
            t = self._trainer
            return t.forward_pass(params, t._decode(x), key, train=False)

        self._fwd = jax.jit(fwd, donate_argnums=(1,) if self.donate
                            else ())
        # weak_fn: the process-global registry must not pin this
        # runner's jitted executables + device params after the service
        # drops it (a dead ref renders NaN)
        _sc.gauge("jit_cache_size", "jax's own executable-cache entries",
                  fn=telemetry.weak_fn(
                      self, lambda r: r.jit_cache_size()))

    compiles = registered_property(
        "compiles", "traces of the jitted forward == jit cache entries")

    # -- the two halves of the ping-pong ---------------------------------------

    def stage(self, x: np.ndarray):
        """Host batch -> device buffer.  The put is dispatched
        asynchronously, so calling this while a previous ``infer_staged``
        is still computing overlaps the H2D copy with that compute."""
        import jax

        return jax.device_put(np.ascontiguousarray(x, self.dtype))

    def infer_staged(self, x_dev):
        """Dispatch the forward on an already-staged (device) batch and
        return the un-materialized device result.  ``x_dev`` is DONATED
        (where the backend supports donation — see ``donate``); callers
        must not reuse it after this call either way."""
        return self._fwd(self.params, x_dev)

    # -- conveniences ----------------------------------------------------------

    def infer(self, x: np.ndarray) -> np.ndarray:
        """Synchronous forward of one host batch (tests, warmup, the
        sequential baseline)."""
        return np.asarray(self.infer_staged(self.stage(x)))

    def pad(self, x: np.ndarray, bucket: int) -> np.ndarray:
        """Zero-pad a (n, *sample) batch up to ``bucket`` rows.  The
        forward is row-independent, so pad rows cannot perturb real
        rows; the caller slices the first n output rows back out."""
        n = x.shape[0]
        if n == bucket:
            return x
        out = np.zeros((bucket,) + tuple(x.shape[1:]), self.dtype)
        out[:n] = x
        return out

    def warmup(self, ladder) -> int:
        """Compile every ladder rung's executable up front; returns the
        compile count afterwards — the zero-recompiles baseline the
        serving gates compare against."""
        for rung in ladder:
            self.infer(np.zeros((rung,) + self.sample_shape, self.dtype))
        return self.compiles

    def jit_cache_size(self) -> Optional[int]:
        """jax's own executable-cache entry count for the jitted forward
        (the jax._src pjit cache behind ``_cache_size``); None where the
        jax version does not expose it.  After warmup this equals
        ``compiles`` and must stay put."""
        try:
            return int(self._fwd._cache_size())
        except Exception:               # pragma: no cover - jax-version dep
            return None

    def stats(self) -> Dict:
        return {"compiles": self.compiles,
                "jit_cache_size": self.jit_cache_size(),
                "sample_shape": list(self.sample_shape),
                "dtype": str(self.dtype)}
