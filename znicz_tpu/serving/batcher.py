"""Dynamic request batcher for the inference service (ISSUE 4).

Small-request serving throughput is dominated by two costs: the per-
dispatch overhead of running the model (a batch-1 forward pays the same
dispatch/jit-call price as a batch-32 one) and jit-cache hygiene (every
distinct batch shape is a fresh XLA compile).  The batcher attacks both:

  - **Coalescing** (clipper/triton-style): a bounded queue of requests is
    drained into batches under a ``(max_batch, max_delay_ms)`` policy —
    a batch closes as soon as it holds ``max_batch`` rows, or when
    ``max_delay_ms`` has elapsed since its first row was taken (latency
    is bounded by construction; an idle service adds no delay because
    the window only starts once a request exists).
  - **Bucket ladder**: each closed batch is padded up to the next rung
    of a fixed ladder (powers of two up to ``max_batch`` by default), so
    the jit cache holds AT MOST ``len(ladder)`` executables and a mixed-
    size request stream causes ZERO recompiles after warmup
    (``ModelRunner.compiles`` is the proof counter).
  - **Backpressure**: the queue is bounded in ROWS; a submit that would
    exceed ``queue_bound`` is shed immediately (counted, refused with a
    readable reason) instead of growing an unbounded backlog whose every
    entry would time out anyway.

Threading contract: ``submit`` may be called from the frontend's router
thread; ``next_batch`` from the single compute thread.  All state is
guarded by one condition variable.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence

from znicz_tpu.telemetry.metrics import registered_property


class BucketLadder:
    """The fixed ladder of padded batch sizes.  Default rungs are the
    powers of two up to ``max_batch`` (plus ``max_batch`` itself when it
    is not a power of two) — a ladder that over-pads by at most 2x while
    keeping the executable count logarithmic in ``max_batch``."""

    def __init__(self, max_batch: int, rungs: Optional[Sequence[int]] = None):
        self.max_batch = int(max_batch)
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if rungs is None:
            rungs = []
            r = 1
            while r < self.max_batch:
                rungs.append(r)
                r *= 2
            rungs.append(self.max_batch)
        rungs = sorted(set(int(r) for r in rungs))
        if not rungs or rungs[0] < 1 or rungs[-1] != self.max_batch:
            raise ValueError(
                f"bucket ladder {rungs} must be positive and end at "
                f"max_batch={self.max_batch}")
        self.rungs: List[int] = rungs

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n (n must be within the ladder)."""
        for r in self.rungs:
            if n <= r:
                return r
        raise ValueError(f"{n} rows exceed the ladder's top rung "
                         f"{self.rungs[-1]}")

    def __iter__(self):
        return iter(self.rungs)

    def __repr__(self):
        return f"BucketLadder({self.rungs})"


class Request:
    """One queued inference request: ``x`` is the (n_rows, *sample) host
    array, ``reply_to`` an opaque routing token the frontend uses to
    answer (the ROUTER envelope), ``req_id`` the client's correlation
    id.  ``t_enqueued`` feeds the latency stats and the TTL check."""

    __slots__ = ("x", "n", "reply_to", "req_id", "trace_id", "t_enqueued")

    def __init__(self, x, n: int, reply_to=None, req_id=None,
                 trace_id=None):
        self.x = x
        self.n = int(n)
        self.reply_to = reply_to
        self.req_id = req_id
        #: optional cross-process correlation id carried in the wire-v3
        #: metadata (ISSUE 5) — echoed in the reply, tagged on spans
        self.trace_id = trace_id
        self.t_enqueued = time.perf_counter()


class DynamicBatcher:
    """Bounded request queue + the coalescing policy (module docstring).

    ``submit`` returns None on acceptance or a human-readable refusal
    reason (shed/oversized) — the frontend ships the reason back so a
    client sees WHY it was refused instead of timing out.
    """

    #: batcher counters registered under component="batcher" (ISSUE 5):
    #: name -> HELP text
    COUNTERS = {
        "submitted": "accepted requests",
        "shed": "refused: queue at bound",
        "oversized": "refused: n > max_batch",
        "batches": "batches closed",
        "batched_requests": "requests inside closed batches",
        "batched_rows": "real rows inside closed batches",
        "padded_rows": "pad rows added by the ladder",
    }

    def __init__(self, max_batch: int = 32, max_delay_ms: float = 5.0,
                 queue_bound: int = 256,
                 ladder: Optional[BucketLadder] = None):
        from znicz_tpu import telemetry

        self.ladder = ladder or BucketLadder(max_batch)
        self.max_batch = self.ladder.max_batch
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.queue_bound = int(queue_bound)
        self._q: collections.deque = collections.deque()
        self._rows = 0                      # rows currently queued
        self._cond = threading.Condition()
        self._closed = False
        # -- accounting (the serving panel's inputs), homed in the
        # telemetry registry; historical attribute names preserved by
        # the class-level properties below
        _sc = telemetry.scope("batcher")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self._m_bucket_hits = {
            r: _sc.counter("bucket_hits", "batches closed per ladder rung",
                           bucket=str(r))
            for r in self.ladder}
        _sc.gauge("queue_depth", "rows queued, not yet batched",
                  fn=telemetry.weak_fn(self, lambda b: b._rows))

    # -- registry-backed counters under their historical names ------------
    # (properties generated from COUNTERS after the class body)

    @property
    def bucket_hits(self) -> Dict[int, int]:
        """``{rung: batches closed at that rung}`` snapshot (historical
        read shape; the counters live in the registry)."""
        return {r: c.value for r, c in self._m_bucket_hits.items()}

    # -- producer side ---------------------------------------------------------

    def submit(self, req: Request) -> Optional[str]:
        if req.n < 1 or req.n > self.max_batch:
            self._m["oversized"].inc()
            return (f"request of {req.n} rows exceeds max_batch="
                    f"{self.max_batch} (split it client-side)")
        with self._cond:
            if self._closed:
                return "service is shutting down"
            if self._rows + req.n > self.queue_bound:
                self._m["shed"].inc()
                return (f"queue at bound ({self._rows} rows queued, "
                        f"bound {self.queue_bound}) — shed")
            self._q.append(req)
            self._rows += req.n
            self._m["submitted"].inc()
            self._cond.notify()
            return None

    @property
    def queue_depth(self) -> int:
        """Rows currently queued (not yet taken into a batch)."""
        return self._rows

    def close(self) -> None:
        """Wake every waiter; ``next_batch`` drains what is queued and
        then returns None forever."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------------

    def next_batch(self, timeout: float = 0.2,
                   wait_fill: bool = True) -> Optional[List[Request]]:
        """The next coalesced batch, or None when nothing arrived within
        ``timeout``.  Blocks up to ``timeout`` for the FIRST request;
        from that moment the ``max_delay_ms`` window runs, during which
        further requests are folded in until ``max_batch`` rows are
        reached.  A request that does not fit the remaining space stays
        queued for the next batch (requests are never split).

        ``wait_fill=False`` skips the window: only already-queued
        requests are taken.  That is the PIPELINED grab — the compute
        loop calls it while the previous batch is still on the device,
        and waiting out a window there would hold the finished batch's
        replies hostage to the next batch's coalescing (measured +1
        ``max_delay`` on p99)."""
        with self._cond:
            deadline = time.perf_counter() + max(timeout, 0.0)
            while not self._q:
                if self._closed:
                    return None
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            batch = [self._q.popleft()]
            rows = batch[0].n
            self._rows -= rows
            flush_at = time.perf_counter() + self.max_delay_s
            while rows < self.max_batch:
                if self._q:
                    if self._q[0].n > self.max_batch - rows:
                        break               # would overflow: next batch
                    req = self._q.popleft()
                    self._rows -= req.n
                    batch.append(req)
                    rows += req.n
                    continue
                remaining = flush_at - time.perf_counter()
                if not wait_fill or remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
        bucket = self.ladder.bucket_for(rows)
        self._m["batches"].inc()
        self._m["batched_requests"].inc(len(batch))
        self._m["batched_rows"].inc(rows)
        self._m["padded_rows"].inc(bucket - rows)
        self._m_bucket_hits[bucket].inc()
        return batch

    # -- stats -----------------------------------------------------------------

    def occupancy(self) -> Optional[float]:
        """Mean real rows per closed batch / max_batch (None before the
        first batch) — 1.0 means every batch left full."""
        if not self.batches:
            return None
        return self.batched_rows / (self.batches * self.max_batch)

    def stats(self) -> Dict:
        occ = self.occupancy()
        return {
            "max_batch": self.max_batch,
            "max_delay_ms": self.max_delay_s * 1e3,
            "queue_bound": self.queue_bound,
            "queue_depth": self.queue_depth,
            "submitted": self.submitted,
            "shed": self.shed,
            "oversized": self.oversized,
            "batches": self.batches,
            "batched_requests": self.batched_requests,
            "batched_rows": self.batched_rows,
            "padded_rows": self.padded_rows,
            "mean_occupancy": None if occ is None else round(occ, 4),
            "bucket_hits": dict(self.bucket_hits),
        }


# historical counter attributes, generated from COUNTERS (name + HELP
# defined exactly once)
for _name, _help in DynamicBatcher.COUNTERS.items():
    setattr(DynamicBatcher, _name, registered_property(_name, _help))
del _name, _help
