"""Dynamic request batcher for the inference service (ISSUE 4).

Small-request serving throughput is dominated by two costs: the per-
dispatch overhead of running the model (a batch-1 forward pays the same
dispatch/jit-call price as a batch-32 one) and jit-cache hygiene (every
distinct batch shape is a fresh XLA compile).  The batcher attacks both:

  - **Coalescing** (clipper/triton-style): a bounded queue of requests is
    drained into batches under a ``(max_batch, max_delay_ms)`` policy —
    a batch closes as soon as it holds ``max_batch`` rows, or when
    ``max_delay_ms`` has elapsed since its first row was taken (latency
    is bounded by construction; an idle service adds no delay because
    the window only starts once a request exists).
  - **Bucket ladder**: each closed batch is padded up to the next rung
    of a fixed ladder (powers of two up to ``max_batch`` by default), so
    the jit cache holds AT MOST ``len(ladder)`` executables and a mixed-
    size request stream causes ZERO recompiles after warmup
    (``ModelRunner.compiles`` is the proof counter).
  - **Backpressure**: the queue is bounded in ROWS; a submit that would
    exceed ``queue_bound`` is shed immediately (counted, refused with a
    readable reason) instead of growing an unbounded backlog whose every
    entry would time out anyway.
  - **Admission control** (ISSUE 6): per-client token-bucket rate
    limits and weighted fair queueing.  Each client gets its own
    subqueue; ``next_batch`` drains them with deficit round robin
    (rows-weighted: each visit banks ``quantum`` rows, a request is
    taken when its client's deficit covers it), so one flooding client
    degrades only itself — its excess is refused ``rate_limited`` at
    submit, and whatever it does get queued cannot starve other
    clients' drain share.  Every refusal is a :class:`Refusal`: still
    the readable string the frontend always shipped, now carrying the
    ``policy`` name (``shed`` / ``oversized`` / ``rate_limited`` /
    ``deadline`` / ``draining``) so a client can tell WHICH policy
    refused it.  Config home: ``root.common.serving.admission.*``.

**Continuous batching** (ISSUE 16, paged in ISSUE 19):
:class:`GenerationScheduler` runs the autoregressive generation plane
next to the classic batcher.  Prefill and decode dispatch as SEPARATE
bucket families: every tick, the decode steps of ALL live generations
sharing a page-table rung coalesce into one (decode-rung x page-rung)
executable — requests join mid-batch as their prefill lands and leave
mid-batch the tick they finish (their KV pages release immediately,
claimable the same tick).  Long prompts prefill in fixed
``prefill_chunk`` token chunks co-scheduled with decode ticks, so a
prompt's length bounds how MANY ticks it spans, never how long one
tick runs; prompts sharing indexed prefix pages skip them outright
(prefix cache, copy-on-write on divergence).  Sampling (greedy, or
seeded temperature/top-k) is fused into the executables, so a token
stream is a deterministic pure function of its own prompt + sampling
params + the pinned executables — co-batched neighbors are invisible
— and a tick's reply is token-sized, not vocab-sized.

Threading contract: ``submit`` may be called from the frontend's router
thread; ``next_batch`` from the single compute thread.  All state is
guarded by one condition variable.  The scheduler's ``submit`` is
router-thread too; ``step`` (all compute + slot bookkeeping) runs ONLY
on the compute thread — one lock guards the handoff queue.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence

from znicz_tpu.telemetry.metrics import registered_property


class BucketLadder:
    """The fixed ladder of padded batch sizes.  Default rungs are the
    powers of two up to ``max_batch`` (plus ``max_batch`` itself when it
    is not a power of two) — a ladder that over-pads by at most 2x while
    keeping the executable count logarithmic in ``max_batch``.

    ``dp`` is the serving mesh's data-axis size (ISSUE 13): every rung
    must split evenly across the data-parallel devices, so default
    rungs are SNAPPED UP to the next multiple of ``dp`` (then deduped —
    the ladder only ever gets shorter) and explicit rungs that do not
    divide are refused readably rather than discovered as an XLA
    sharding error at the first request.

    **2-D (batch x seq) mode** (ISSUE 15): with ``max_len > 0`` the
    ladder grows a SECOND axis of sequence rungs (powers of two up to
    ``max_len``, or explicit ``seq_rungs``) for variable-length
    workloads: a request is padded UP on both axes — its batch lands on
    ``bucket_for(rows)`` and its OWN sequence length on
    ``seq_bucket_for(len)`` — so the jit cache holds at most
    ``len(rungs) * len(seq_rungs)`` executables (``buckets()``
    enumerates them for warmup) and a mixed-length stream still causes
    ZERO recompiles after warmup.  A request's seq rung depends only on
    its OWN length, never on co-batched neighbors — that is what keeps
    the 0-ULP batch-independence contract a per-(rows, seq)-executable
    property under variable length.  dp snapping applies to the batch
    axis only (devices shard rows, never tokens)."""

    def __init__(self, max_batch: int, rungs: Optional[Sequence[int]] = None,
                 dp: int = 1, max_len: int = 0,
                 seq_rungs: Optional[Sequence[int]] = None):
        self.max_batch = int(max_batch)
        self.dp = int(dp)
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if self.dp < 1:
            raise ValueError(f"dp must be >= 1, got {dp}")
        if self.max_batch % self.dp:
            raise ValueError(
                f"max_batch={self.max_batch} does not divide across the "
                f"mesh's data axis (dp={self.dp}); pick a max_batch "
                f"that is a multiple of dp")
        snapped = rungs is None
        if rungs is None:
            rungs = []
            r = 1
            while r < self.max_batch:
                rungs.append(r)
                r *= 2
            rungs.append(self.max_batch)
            # mesh-aware snap: each rung up to the next multiple of dp
            rungs = [-(-r // self.dp) * self.dp for r in rungs]
        rungs = sorted(set(int(r) for r in rungs))
        if not rungs or rungs[0] < 1 or rungs[-1] != self.max_batch:
            raise ValueError(
                f"bucket ladder {rungs} must be positive and end at "
                f"max_batch={self.max_batch}")
        if not snapped:
            bad = [r for r in rungs if r % self.dp]
            if bad:
                raise ValueError(
                    f"bucket ladder rungs {bad} do not divide across "
                    f"the mesh's data axis (dp={self.dp}); every rung "
                    f"must be a multiple of dp so each device holds "
                    f"exactly rows/dp rows")
        self.rungs: List[int] = rungs
        self.max_len = int(max_len)
        if self.max_len < 0:
            raise ValueError(f"max_len must be >= 0, got {max_len}")
        if self.max_len == 0:
            if seq_rungs:
                raise ValueError(
                    "seq_rungs given without max_len — set "
                    "root.common.serving.seq.max_len to enable the "
                    "2-D ladder")
            self.seq_rungs: Optional[List[int]] = None
        else:
            if seq_rungs is None:
                seq_rungs = []
                s = 1
                while s < self.max_len:
                    seq_rungs.append(s)
                    s *= 2
                seq_rungs.append(self.max_len)
            seq_rungs = sorted(set(int(s) for s in seq_rungs))
            if not seq_rungs or seq_rungs[0] < 1 \
                    or seq_rungs[-1] != self.max_len:
                raise ValueError(
                    f"seq ladder {seq_rungs} must be positive and end "
                    f"at max_len={self.max_len}")
            self.seq_rungs = seq_rungs

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n (n must be within the ladder)."""
        for r in self.rungs:
            if n <= r:
                return r
        raise ValueError(f"{n} rows exceed the ladder's top rung "
                         f"{self.rungs[-1]}")

    def seq_bucket_for(self, n: int) -> int:
        """Smallest SEQ rung >= n (2-D mode only) — a function of the
        request's OWN length, so co-batched neighbors can never move a
        request to a different executable's seq axis."""
        if self.seq_rungs is None:
            raise ValueError("ladder has no seq axis (max_len unset)")
        for s in self.seq_rungs:
            if n <= s:
                return s
        raise ValueError(f"sequence of {n} tokens exceeds the ladder's "
                         f"top seq rung {self.seq_rungs[-1]}")

    def buckets(self) -> List:
        """Every executable shape the jit cache may hold: the batch
        rungs (1-D mode), or the (rows, seq) product (2-D mode) —
        the warmup set and the ``compiles == len(buckets())`` bound."""
        if self.seq_rungs is None:
            return list(self.rungs)
        return [(r, s) for r in self.rungs for s in self.seq_rungs]

    @staticmethod
    def bucket_key(rows: int, seq: Optional[int] = None):
        """The stats/telemetry key for one bucket: the plain rung int
        (1-D, the historical shape) or ``"RxS"`` (2-D — a string so
        /status.json keeps it as a JSON key verbatim)."""
        return int(rows) if seq is None else f"{int(rows)}x{int(seq)}"

    def __iter__(self):
        return iter(self.rungs)

    def __repr__(self):
        if self.seq_rungs is not None:
            return f"BucketLadder({self.rungs} x seq{self.seq_rungs})"
        return f"BucketLadder({self.rungs})"


#: "no client is mid-visit" marker for the DRR drain.  A dedicated
#: sentinel, NOT None: None is also the shared-queue KEY when fairness
#: is off, and conflating the two made the drain skip that queue's
#: quantum banking forever (an infinite loop under the queue lock the
#: first time a retired per-client queue coexisted with the shared one)
_NO_VISIT = object()


class Refusal(str):
    """A refusal reason: the plain readable string the frontend always
    shipped, additionally carrying the ``policy`` slug (``shed`` /
    ``oversized`` / ``rate_limited`` / ``deadline`` / ``draining``) the
    reply names, so a refused client can react per policy (back off on
    ``rate_limited``, split on ``oversized``, ...) without parsing
    prose.  ``scope`` says WHOSE limit refused: ``"client"`` (this
    caller's own quota/bound — the service is healthy) vs
    ``"service"`` (global overload/shutdown) — the client circuit
    breaker counts only service-scoped sheds as failures, so a caller
    bumping its own fair-share bound never opens its breaker against a
    healthy service."""

    policy = "refused"
    scope = "service"

    def __new__(cls, policy: str, reason: str, scope: str = "service"):
        self = super().__new__(cls, reason)
        self.policy = policy
        self.scope = scope
        return self


# the per-client rate limiter now lives in the transport core (ISSUE
# 14) so the MASTER's ingress meters per-slave rates with the SAME
# primitive; re-exported here under its historical home
from znicz_tpu.transport.admission import TokenBucket        # noqa: E402


class AdmissionPolicy:
    """Admission-control knobs (config home
    ``root.common.serving.admission.*``):

      - ``rate_limit``: rows/s each client may sustain (0 = unlimited);
      - ``rate_burst``: token-bucket capacity in rows (0 = auto:
        ``max(rate_limit, max_batch)``);
      - ``fair``: per-client subqueues drained deficit-round-robin
        (off = the historical single FIFO);
      - ``quantum``: DRR rows banked per visit (0 = auto:
        ``max_batch // 4``, min 1);
      - ``client_queue_bound``: queued rows ONE client may hold
        (0 = no per-client cap — the global ``queue_bound`` is the
        only backpressure);
      - ``enabled``: master switch — ``bench.py --serve`` toggles it for
        the interleaved on/off overhead gate.
    """

    __slots__ = ("rate_limit", "rate_burst", "fair", "quantum",
                 "client_queue_bound", "enabled")

    def __init__(self, rate_limit: float = 0.0, rate_burst: float = 0.0,
                 fair: bool = True, quantum: int = 0,
                 client_queue_bound: int = 0, enabled: bool = True):
        self.rate_limit = float(rate_limit)
        self.rate_burst = float(rate_burst)
        self.fair = bool(fair)
        self.quantum = int(quantum)
        self.client_queue_bound = int(client_queue_bound)
        self.enabled = bool(enabled)


class Request:
    """One queued inference request: ``x`` is the (n_rows, *sample) host
    array, ``reply_to`` an opaque routing token the frontend uses to
    answer (the ROUTER envelope), ``req_id`` the client's correlation
    id.  ``t_enqueued`` feeds the latency stats; ``t_deadline`` (ISSUE
    6) is the ABSOLUTE local deadline the frontend derived at ingress
    from the client's shipped budget (or its own TTL) — checked at
    assemble time and again post-compute, so expired work is never
    computed and never shipped.  ``client`` keys the admission
    subqueue/bucket."""

    __slots__ = ("x", "n", "reply_to", "req_id", "trace_id", "client",
                 "t_enqueued", "t_deadline", "seq_len", "seq_rung")

    def __init__(self, x, n: int, reply_to=None, req_id=None,
                 trace_id=None, client=None, deadline_s=None,
                 seq_len=None):
        self.x = x
        self.n = int(n)
        #: variable-length workloads (ISSUE 15): the request's OWN
        #: unpadded sequence length — the padding-mask information the
        #: frontend keeps per request (pad tokens are PAD-id rows it
        #: appends at assemble, and the reply is sliced back to this
        #: length).  ``seq_rung`` is assigned at submit from the
        #: ladder's seq axis; batches only ever coalesce ONE rung.
        self.seq_len = None if seq_len is None else int(seq_len)
        self.seq_rung = None
        self.reply_to = reply_to
        self.req_id = req_id
        #: optional cross-process correlation id carried in the wire-v3
        #: metadata (ISSUE 5) — echoed in the reply, tagged on spans
        self.trace_id = trace_id
        #: admission identity (frontend: explicit ``client`` metadata,
        #: else a digest of the ROUTER envelope)
        self.client = client
        self.t_enqueued = time.perf_counter()
        self.t_deadline = (None if deadline_s is None
                           else self.t_enqueued + float(deadline_s))


class DynamicBatcher:
    """Bounded request queue + the coalescing policy (module docstring).

    ``submit`` returns None on acceptance or a human-readable refusal
    reason (shed/oversized) — the frontend ships the reason back so a
    client sees WHY it was refused instead of timing out.
    """

    #: batcher counters registered under component="batcher" (ISSUE 5):
    #: name -> HELP text
    COUNTERS = {
        "submitted": "accepted requests",
        "shed": "refused: queue at bound",
        "oversized": "refused: n > max_batch",
        "rate_limited": "refused: client over its rate limit",
        "batches": "batches closed",
        "batched_requests": "requests inside closed batches",
        "batched_rows": "real rows inside closed batches",
        "padded_rows": "pad rows added by the ladder",
        "real_cells": "real cells (rows x own tokens) inside closed "
                      "batches — the pad_ratio denominator",
        "padded_cells": "pad cells added by the (2-D) ladder: bucket "
                        "area minus real cells — the padded-compute "
                        "numerator",
    }

    #: per-client accounting table bound (plain state, not registry
    #: series: client ids are ephemeral uuids — labeled families would
    #: leak a series per client forever)
    MAX_CLIENT_STATS = 32

    #: token-bucket table bound: past this, fully-refilled buckets
    #: (state == freshly built — dropping one is invisible to its
    #: client) are swept; clients churning faster than this refill are
    #: evicted oldest-first.  Without a bound the table grows one
    #: entry per ephemeral client id ever seen (uuid per
    #: InferenceClient instance) for the life of the service.
    MAX_BUCKETS = 1024

    def __init__(self, max_batch: int = 32, max_delay_ms: float = 5.0,
                 queue_bound: int = 256,
                 ladder: Optional[BucketLadder] = None,
                 admission: Optional[AdmissionPolicy] = None):
        from znicz_tpu import telemetry

        self.ladder = ladder or BucketLadder(max_batch)
        self.max_batch = self.ladder.max_batch
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.queue_bound = int(queue_bound)
        #: per-client subqueues (key None = the shared FIFO when
        #: fairness is off / admission disabled)
        self._queues: "collections.OrderedDict[object, collections.deque]" \
            = collections.OrderedDict()
        self._rr: collections.deque = collections.deque()  # DRR rotation
        self._deficit: Dict[object, float] = {}
        self._visiting = _NO_VISIT          # DRR visit marker (quantum
        #                                     banks once per visit)
        self._client_rows: Dict[object, int] = {}
        #: bounded per-client admission accounting for the panel
        self.clients: "collections.OrderedDict[str, Dict]" \
            = collections.OrderedDict()
        self._rows = 0                      # rows currently queued
        self._cond = threading.Condition()
        self._closed = False
        self.set_admission(admission or AdmissionPolicy())
        # -- accounting (the serving panel's inputs), homed in the
        # telemetry registry; historical attribute names preserved by
        # the class-level properties below
        _sc = telemetry.scope("batcher")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        # per-bucket families (ISSUE 15): keys are the ladder's
        # bucket_key form — plain rung ints in 1-D mode (the historical
        # shape), "RxS" strings on a 2-D ladder.  padded/real cells per
        # bucket make pad_ratio a measured, per-executable quantity.
        self._m_bucket_hits = {}
        self._m_real_cells = {}
        self._m_pad_cells = {}
        for b in self.ladder.buckets():
            key = (self.ladder.bucket_key(b) if isinstance(b, int)
                   else self.ladder.bucket_key(*b))
            self._m_bucket_hits[key] = _sc.counter(
                "bucket_hits", "batches closed per ladder bucket",
                bucket=str(key))
            self._m_real_cells[key] = _sc.counter(
                "bucket_real_cells",
                "real cells (rows x own tokens) per ladder bucket",
                bucket=str(key))
            self._m_pad_cells[key] = _sc.counter(
                "bucket_padded_cells",
                "pad cells (bucket area - real) per ladder bucket",
                bucket=str(key))
        _sc.gauge("queue_depth", "rows queued, not yet batched",
                  fn=telemetry.weak_fn(self, lambda b: b._rows))

    # -- registry-backed counters under their historical names ------------
    # (properties generated from COUNTERS after the class body)

    @property
    def bucket_hits(self) -> Dict:
        """``{bucket: batches closed at that bucket}`` snapshot
        (historical read shape; the counters live in the registry).
        Keys are rung ints (1-D) or ``"RxS"`` strings (2-D)."""
        return {r: c.value for r, c in self._m_bucket_hits.items()}

    def pad_ratio(self) -> Dict:
        """``{bucket: padded cells / real cells}`` — the padded-compute
        ratio per executable (ISSUE 15): how many pad cells the ladder
        computed per real cell.  Buckets that never closed a batch are
        omitted; 0.0 means every batch left exactly full."""
        out = {}
        for key, real in self._m_real_cells.items():
            r = real.value
            if r:
                out[key] = round(self._m_pad_cells[key].value / r, 4)
        return out

    # -- admission -------------------------------------------------------------

    def set_admission(self, policy: AdmissionPolicy) -> None:
        """Install (or swap — the bench's on/off overhead toggle) the
        admission policy.  Auto knobs resolve against this batcher;
        token buckets restart (new rates must not inherit old debt).
        Already-queued requests drain under the rotation regardless —
        only the submit-side keying/limits change."""
        from znicz_tpu.transport import AdmissionTable

        with self._cond:
            self.admission = policy
            self._rate_burst = policy.rate_burst or max(
                policy.rate_limit, float(self.max_batch))
            self._quantum = policy.quantum or max(1, self.max_batch // 4)
            # the bounded per-client bucket table is the transport
            # core's (ISSUE 14 — ONE home for the lazy-build /
            # lossless-sweep / oldest-first-eviction discipline, shared
            # with the master's ingress); rebuilt so new rates never
            # inherit old debt
            self._table = AdmissionTable(policy.rate_limit,
                                         self._rate_burst,
                                         max_peers=self.MAX_BUCKETS)

    @property
    def _client_bound(self) -> int:
        """The effective per-client queued-rows cap — derived LIVE (not
        cached at set_admission time) so mutating ``queue_bound`` at
        runtime cannot leave a stale fair-share bound above the whole
        queue."""
        return self.admission.client_queue_bound or self.queue_bound

    def _client_stat(self, client) -> Dict:
        key = str(client)
        st = self.clients.get(key)
        if st is None:
            while len(self.clients) >= self.MAX_CLIENT_STATS:
                self.clients.popitem(last=False)    # oldest first seen
            st = self.clients[key] = {
                "requests": 0, "rows": 0, "accepted": 0,
                "rate_limited": 0, "shed": 0}
        return st

    def admission_stats(self) -> Dict:
        adm = self.admission
        with self._cond:
            # under the lock: the router/compute threads mutate
            # _queues/clients mid-iteration otherwise (web_status
            # scrapes from its own HTTP thread)
            active = sum(1 for q in self._queues.values() if q)
            clients = {k: dict(v) for k, v in self.clients.items()}
        return {
            "enabled": adm.enabled,
            "fair": adm.fair,
            "rate_limit_rows_per_s": adm.rate_limit,
            "rate_burst_rows": self._rate_burst,
            "quantum_rows": self._quantum,
            "client_queue_bound": self._client_bound,
            "rate_limited": self.rate_limited,
            "active_clients": active,
            "clients": clients,
        }

    # -- producer side ---------------------------------------------------------

    def submit(self, req: Request) -> Optional[Refusal]:
        if req.n < 1 or req.n > self.max_batch:
            self._m["oversized"].inc()
            return Refusal(
                "oversized",
                f"request of {req.n} rows exceeds max_batch="
                f"{self.max_batch} (split it client-side)",
                scope="client")
        if self.ladder.seq_rungs is not None:
            # 2-D mode: the seq rung is a function of the request's OWN
            # length (frontend validated 1 <= len <= max_len already;
            # this is the defensive in-process-caller check)
            if req.seq_len is None or req.seq_len < 1 \
                    or req.seq_len > self.ladder.max_len:
                self._m["oversized"].inc()
                return Refusal(
                    "oversized",
                    f"sequence length {req.seq_len} outside the seq "
                    f"ladder (1..{self.ladder.max_len})", scope="client")
            req.seq_rung = self.ladder.seq_bucket_for(req.seq_len)
        adm = self.admission
        with self._cond:
            if self._closed:
                return Refusal("draining", "service is shutting down")
            key = None
            took = 0
            if adm.enabled:
                st = self._client_stat(req.client)
                st["requests"] += 1
                st["rows"] += req.n
                if adm.rate_limit > 0:
                    if not self._table.try_take(req.client, req.n):
                        self._m["rate_limited"].inc()
                        st["rate_limited"] += 1
                        return Refusal(
                            "rate_limited",
                            f"client over its rate limit "
                            f"({adm.rate_limit:g} rows/s, burst "
                            f"{self._rate_burst:g}) — rate_limited",
                            scope="client")
                    took = req.n
                if adm.fair:
                    key = req.client
                    # explicit per-client cap only: with
                    # client_queue_bound=0 the effective bound equals
                    # queue_bound and client_rows <= total rows, so the
                    # global check below already subsumes this one
                    if (adm.client_queue_bound > 0
                            and self._client_rows.get(key, 0) + req.n
                            > self._client_bound):
                        self._m["shed"].inc()
                        st["shed"] += 1
                        if took:
                            self._table.refund(req.client, took)
                        return Refusal(
                            "shed",
                            f"client queue at its fair-share bound "
                            f"({self._client_rows.get(key, 0)} rows "
                            f"queued, bound {self._client_bound}) — shed",
                            scope="client")
            if self._rows + req.n > self.queue_bound:
                self._m["shed"].inc()
                if adm.enabled:
                    st["shed"] += 1
                if took:
                    self._table.refund(req.client, took)
                return Refusal(
                    "shed",
                    f"queue at bound ({self._rows} rows queued, "
                    f"bound {self.queue_bound}) — shed")
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = collections.deque()
                self._rr.append(key)
            q.append(req)
            self._rows += req.n
            self._client_rows[key] = self._client_rows.get(key, 0) + req.n
            if adm.enabled:
                st["accepted"] += 1
            self._m["submitted"].inc()
            self._cond.notify()
            return None

    @property
    def queue_depth(self) -> int:
        """Rows currently queued (not yet taken into a batch)."""
        return self._rows

    def close(self) -> None:
        """Wake every waiter; ``next_batch`` drains what is queued and
        then returns None forever."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------------

    def _pop(self, key, idx: int = 0) -> Request:
        """Dequeue entry ``idx`` of ``key``'s subqueue (cond held).
        idx > 0 is the 2-D drain reaching past a mismatched-rung head
        (``_match``); earlier entries keep their relative order."""
        q = self._queues[key]
        if idx:
            q.rotate(-idx)
            req = q.popleft()
            q.rotate(idx)
        else:
            req = q.popleft()
        self._rows -= req.n
        if key in self._client_rows:
            self._client_rows[key] -= req.n
        return req

    @staticmethod
    def _match(q, space: int, seq_rung) -> int:
        """Index of the first queued request that fits ``space`` rows
        AND the pinned seq rung, or -1.  With no pinned rung (a 1-D
        ladder, or the FIRST take of any batch) only the HEAD is
        considered — the historical strict-FIFO drain.  With a pinned
        rung the scan reaches PAST mismatched-RUNG requests only
        (head-of-line blocking would otherwise fragment a mixed-length
        stream into 1-row batches — the dispatch-overhead regime
        coalescing exists to avoid): the first SAME-rung request is
        taken if it fits and otherwise ends the scan, so same-rung
        requests always drain in arrival order (a smaller later
        request never overtakes an older one that merely missed the
        remaining space).  Skipped requests keep their
        deadline/admission state untouched."""
        for idx, req in enumerate(q):
            if seq_rung is not None and req.seq_rung != seq_rung:
                continue                # reach past OTHER rungs only
            return idx if req.n <= space else -1
        return -1

    def _take_one(self, space: int,
                  seq_rung: Optional[int] = None) -> Optional[Request]:
        """One request under deficit round robin, or None when nothing
        queued fits ``space`` rows (requests are never split; cond
        held).  A visited client banks ``quantum`` rows once per visit
        and keeps its turn while its banked deficit covers its head —
        rows-weighted fairness across clients, plain FIFO within one.
        A client whose queue empties is retired (classic DRR: an idle
        queue banks nothing).

        ``seq_rung`` (2-D ladders, ISSUE 15) restricts the take to
        requests whose OWN seq rung matches the batch being built —
        coalescing by nearest seq rung without touching the
        deadline/admission discipline: a mismatched head simply ends
        that client's visit exactly like a head too big for the
        remaining space (FIFO within a client is preserved)."""

        rr = self._rr
        if self._rows == 0 or not rr:
            return None
        if len(rr) == 1:
            # one subqueue (single client, or fairness off): plain FIFO,
            # no deficit bookkeeping on the hot path
            idx = self._match(self._queues[rr[0]], space, seq_rung)
            if idx >= 0:
                return self._pop(rr[0], idx)
            return None
        # ONE scan per take: queues do not change under the lock until
        # _pop, so each client's matched index stays valid through
        # however many DRR rotations deficit banking needs (re-scanning
        # per visit made 2-D assembly O(batch x queued) twice over)
        matches = {key: idx for key, q in self._queues.items() if q
                   for idx in (self._match(q, space, seq_rung),)
                   if idx >= 0}
        if not matches:
            return None                     # nothing fits: close batch
        cap = float(max(self._quantum, self.max_batch))
        while True:
            key = rr[0]
            q = self._queues.get(key)
            if not q:
                rr.popleft()                # retire the idle client
                self._deficit.pop(key, None)
                self._queues.pop(key, None)
                self._client_rows.pop(key, None)
                if self._visiting == key:
                    self._visiting = _NO_VISIT
                continue
            if self._visiting != key:
                self._visiting = key
                self._deficit[key] = min(
                    self._deficit.get(key, 0.0) + self._quantum, cap)
            idx = matches.get(key, -1)
            if idx >= 0 and self._deficit.get(key, 0.0) >= q[idx].n:
                self._deficit[key] -= q[idx].n
                return self._pop(key, idx)
            # nothing fits (space/rung), or deficit not yet banked:
            # this visit ends, next client's turn
            rr.rotate(-1)
            self._visiting = _NO_VISIT

    def next_batch(self, timeout: float = 0.2,
                   wait_fill: bool = True) -> Optional[List[Request]]:
        """The next coalesced batch, or None when nothing arrived within
        ``timeout``.  Blocks up to ``timeout`` for the FIRST request;
        from that moment the ``max_delay_ms`` window runs, during which
        further requests are folded in until ``max_batch`` rows are
        reached.  A request that does not fit the remaining space stays
        queued for the next batch (requests are never split); with
        multiple clients queued, requests are drained deficit-round-
        robin across the per-client subqueues (module docstring).

        ``wait_fill=False`` skips the window: only already-queued
        requests are taken.  That is the PIPELINED grab — the compute
        loop calls it while the previous batch is still on the device,
        and waiting out a window there would hold the finished batch's
        replies hostage to the next batch's coalescing (measured +1
        ``max_delay`` on p99)."""
        with self._cond:
            deadline = time.perf_counter() + max(timeout, 0.0)
            while self._rows == 0:
                if self._closed:
                    return None
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            first = self._take_one(self.max_batch)
            if first is None:               # pragma: no cover - defensive
                return None
            batch = [first]
            rows = first.n
            # 2-D ladders: the FIRST request pins the batch's seq rung;
            # only same-rung requests coalesce into it (different rungs
            # close this batch and immediately form their own)
            seq_rung = first.seq_rung
            flush_at = time.perf_counter() + self.max_delay_s
            while rows < self.max_batch:
                req = self._take_one(self.max_batch - rows, seq_rung)
                if req is not None:
                    batch.append(req)
                    rows += req.n
                    continue
                if self._rows:
                    break                   # queued but nothing fits
                remaining = flush_at - time.perf_counter()
                if not wait_fill or remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
        bucket = self.ladder.bucket_for(rows)
        self._m["batches"].inc()
        self._m["batched_requests"].inc(len(batch))
        self._m["batched_rows"].inc(rows)
        self._m["padded_rows"].inc(bucket - rows)
        # padded-compute accounting (ISSUE 15): real cells are each
        # request's rows x its OWN length; the executable computes the
        # full bucket area — the difference is pure padding FLOPs
        if seq_rung is None:
            key = self.ladder.bucket_key(bucket)
            real = rows
            area = bucket
        else:
            key = self.ladder.bucket_key(bucket, seq_rung)
            real = sum(r.n * r.seq_len for r in batch)
            area = bucket * seq_rung
        self._m["real_cells"].inc(real)
        self._m["padded_cells"].inc(area - real)
        self._m_bucket_hits[key].inc()
        self._m_real_cells[key].inc(real)
        self._m_pad_cells[key].inc(area - real)
        return batch

    # -- stats -----------------------------------------------------------------

    def occupancy(self) -> Optional[float]:
        """Mean real rows per closed batch / max_batch (None before the
        first batch) — 1.0 means every batch left full."""
        if not self.batches:
            return None
        return self.batched_rows / (self.batches * self.max_batch)

    def stats(self) -> Dict:
        occ = self.occupancy()
        return {
            "max_batch": self.max_batch,
            "max_delay_ms": self.max_delay_s * 1e3,
            "queue_bound": self.queue_bound,
            "queue_depth": self.queue_depth,
            "submitted": self.submitted,
            "shed": self.shed,
            "oversized": self.oversized,
            "rate_limited": self.rate_limited,
            "batches": self.batches,
            "batched_requests": self.batched_requests,
            "batched_rows": self.batched_rows,
            "padded_rows": self.padded_rows,
            "real_cells": self.real_cells,
            "padded_cells": self.padded_cells,
            "pad_ratio": self.pad_ratio(),
            "seq_rungs": (None if self.ladder.seq_rungs is None
                          else list(self.ladder.seq_rungs)),
            "mean_occupancy": None if occ is None else round(occ, 4),
            "bucket_hits": dict(self.bucket_hits),
            "admission": self.admission_stats(),
        }


class GenSeq:
    """One generation request through its whole life: pending (prompt
    queued) -> active (holding a page-table cache; prefilling in
    ``prefill_chunk`` token chunks, then decoding one token per tick)
    -> finished.  ``prefilled`` counts prompt positions whose k/v are
    in the cache (prefix-cache hits start it > 0); ``t`` is the total
    cache fill once decoding starts.  ``pages`` is the request's page
    table — plain host ints, so "cache growth" is a list append.

    Sampling is per-sequence and deterministic under a seed on BOTH
    paths: the fused in-graph sampler keys off ``seed_val`` (device
    path), the host fallback off a seeded ``np.random.Generator`` —
    either way neighbors share nothing."""

    __slots__ = ("prompt", "prompt_len", "max_new", "temperature",
                 "top_k", "rng", "seed_val", "stream", "return_logits",
                 "return_logprobs", "reply_to", "req_id", "trace_id",
                 "client", "t_enqueued", "t_deadline", "pages",
                 "prefilled", "t", "tokens", "logits", "logprobs",
                 "gen", "t_last", "order", "t_admitted", "t_first")

    def __init__(self, prompt, max_new: int, temperature: float = 0.0,
                 top_k: int = 0, seed=None, stream: bool = False,
                 return_logits: bool = False,
                 return_logprobs: bool = False, reply_to=None,
                 req_id=None, trace_id=None, client=None,
                 deadline_s=None):
        import numpy as np

        self.prompt = np.asarray(prompt).reshape(-1)
        self.prompt_len = int(self.prompt.shape[0])
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.rng = (np.random.default_rng(seed)
                    if self.temperature > 0 else None)
        self.seed_val = (int(seed) & 0xFFFFFFFF if seed is not None
                         else int(np.random.default_rng()
                                  .integers(0, 2**32)))
        self.stream = bool(stream)
        self.return_logits = bool(return_logits)
        self.return_logprobs = bool(return_logprobs)
        self.reply_to = reply_to
        self.req_id = req_id
        self.trace_id = trace_id
        self.client = client
        self.t_enqueued = time.perf_counter()
        self.t_deadline = (None if deadline_s is None
                           else self.t_enqueued + float(deadline_s))
        self.pages: List[int] = []      # the request's page table
        self.prefilled = 0              # prompt positions cached so far
        self.t = 0                      # cache fill (positions written)
        self.tokens: List[int] = []     # emitted so far
        self.logits = [] if return_logits else None
        self.logprobs = [] if return_logprobs else None
        self.gen = None                 # snapshot generation stamp
        self.t_last = None              # last emit time (inter-token)
        self.order = 0                  # arrival index (FIFO grouping)
        self.t_admitted = None          # admission time (queue-wait end)
        self.t_first = None             # first-token time (TTFT end)

    def sample(self, row) -> int:
        """Next token from one (vocab,) logits row — the HOST sampling
        path (``on_device_sampling`` off): greedy argmax at temperature
        0 (deterministic, tie -> lowest id, bit-identical to the fused
        in-graph argmax), else seeded softmax sampling over the
        optional top-k cut."""
        import numpy as np

        if self.temperature <= 0:
            return int(np.argmax(row))
        z = row.astype(np.float64) / self.temperature
        if self.top_k > 0 and self.top_k < z.shape[0]:
            cut = np.partition(z, -self.top_k)[-self.top_k]
            z = np.where(z >= cut, z, -np.inf)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(self.rng.choice(z.shape[0], p=p))


def _host_logp(row, token: int) -> float:
    """log p(token) under one (vocab,) logits row, float64 host math —
    the ``return_logprobs`` fallback when logits were fetched anyway."""
    import numpy as np

    z = row.astype(np.float64)
    z -= z.max()
    return float(z[token] - np.log(np.exp(z).sum()))


class GenerationScheduler:
    """Continuous batching over a paged :class:`GenerationRunner`
    (module docstring).  ``submit`` enqueues from the router thread;
    ``step`` — called by the frontend's compute loop — runs one
    scheduling round on the compute thread:

      1. expire pending/active sequences past their deadline (partial
         tokens ship with the ``deadline`` policy reply);
      2. admit pending requests into the ``slots`` concurrency bound —
         admission runs the prefix-cache lookup, so a request whose
         prompt shares indexed full pages starts with those pages
         CLAIMED (read-only, refcounted) and only its tail to prefill;
      3. ONE decode tick: every fully-prefilled sequence's next token,
         grouped by page-table rung in FIFO chunks of the top decode
         rung — finished sequences release their pages mid-round, and
         a sequence at the context window force-finishes ``truncated``;
      4. ONE prefill chunk batch: up to a prefill rung of
         still-prefilling sequences each advance by ``prefill_chunk``
         tokens — a long prompt costs one BOUNDED chunk between decode
         ticks (chunked prefill), never a whole-prompt stall of the
         decode cadence.  Page allocation (and copy-on-write of shared
         pages about to be appended into) happens here on the host;
         allocation pressure stalls a row for a tick, never the batch.

    Device->host fetches follow ``on_device_sampling``: on, a tick
    ships (b,) sampled tokens (plus logprobs when asked); off, it
    ships (b, vocab) logits and samples on the host — same executable
    family either way, and greedy tokens are bit-identical across the
    knob.

    Returns the replies to ship: streamed per-token partials (opt-in)
    and whole-stream finals.  A resent ``generate`` request matching an
    in-flight ``(client, req_id)`` is deduplicated — generation is NOT
    idempotent compute, but the final reply still is (resend-same-bytes
    semantics hold end to end)."""

    COUNTERS = {
        "gen_submitted": "accepted generate requests",
        "gen_refused": "refused generate requests (policy in the reply)",
        "gen_dedup": "resent generate requests matched to an in-flight "
                     "generation (answered by the original)",
        "prefill_batches": "prefill chunk dispatches — the prompt side "
                           "of the prefill/decode split",
        "prefill_seqs": "sequences whose prefill completed",
        "prefill_tokens": "prompt tokens actually COMPUTED by prefill "
                          "chunks (prefix-cache hits skip theirs)",
        "decode_batches": "decode tick dispatches — the token side of "
                          "the prefill/decode split",
        "decode_tokens": "tokens emitted by decode ticks",
        "generated_tokens": "tokens emitted in total (prefill's first + "
                            "every decode)",
        "cow_copies": "shared prefix pages copy-on-written at the "
                      "first divergent append",
        "fetch_bytes": "bytes fetched device->host by generation ticks "
                       "(tokens or logits — the on-device-sampling "
                       "lever)",
        "gen_finished": "generations completed to max_new_tokens",
        "gen_truncated": "generations force-finished at the context "
                         "window",
        "gen_timed_out": "generations abandoned at their deadline "
                         "(partial tokens shipped)",
    }

    def __init__(self, gen_runner, max_new_cap: int = 256,
                 pending_bound: int = 64, decode_tick_ms: float = 0.0,
                 on_device_sampling: bool = True, replica_id: str = ""):
        from znicz_tpu import telemetry

        self.gen = gen_runner
        self.max_new_cap = int(max_new_cap)
        self.pending_bound = int(pending_bound)
        self.decode_tick_s = float(decode_tick_ms) / 1e3
        self.on_device = bool(on_device_sampling)
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._pending: collections.deque = collections.deque()
        self._active: List[GenSeq] = []
        #: in-flight (client, req_id) pairs — the resend dedup set
        self._inflight = set()
        self._closed = False
        self._order = 0
        self._next_tick = 0.0
        _sc = telemetry.scope("generate")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        self._m_inter_token = _sc.histogram(
            "inter_token_seconds",
            "gap between consecutive emitted tokens of one sequence",
            size=8192)
        # ISSUE 20 satellite: TTFT plus its queue-wait/compute split —
        # where the first-token latency is SPENT, not just its size
        self._m_ttft = _sc.histogram(
            "ttft_seconds",
            "time to first token (enqueue -> first emitted token)",
            size=2048)
        self._m_queue_wait = _sc.histogram(
            "gen_queue_wait_seconds",
            "pending-queue wait (enqueue -> admission to a KV slot)",
            size=2048)
        self._m_compute = _sc.histogram(
            "gen_compute_seconds",
            "admission -> first token (prefill compute + tick pacing)",
            size=2048)
        #: page-pressure episode latch: journal the TRANSITION into
        #: pressure once, not every stalled tick
        self._page_pressure = False
        self._t_shed_emit = 0.0         # queue-shed journal rate limit
        #: scheduler spans carry each request's trace_id so the fleet
        #: exporter can stitch decode/prefill ticks into the request's
        #: cross-process timeline (ISSUE 20)
        self._tracer = telemetry.tracer()
        _sc.gauge("kv_occupancy", "allocated KV pages / pool pages",
                  fn=telemetry.weak_fn(self, lambda s: s.gen.occupancy()))
        _sc.gauge("active", "generations holding KV pages",
                  fn=telemetry.weak_fn(self, lambda s: len(s._active)))
        _sc.gauge("pending", "generations queued for admission",
                  fn=telemetry.weak_fn(self, lambda s: len(s._pending)))

    # -- producer side (router thread) -----------------------------------------

    def submit(self, seq: GenSeq) -> Optional[Refusal]:
        """Queue one generation, or refuse readably.  A resend of an
        in-flight (client, req_id) is absorbed (None — the original
        generation answers it)."""
        if seq.prompt_len < 1 or seq.prompt_len > self.gen.max_ctx:
            self._m["gen_refused"].inc()
            return Refusal(
                "oversized",
                f"prompt of {seq.prompt_len} tokens outside the "
                f"context window (1..{self.gen.max_ctx})",
                scope="client")
        if seq.max_new < 1 or seq.max_new > self.max_new_cap:
            self._m["gen_refused"].inc()
            return Refusal(
                "oversized",
                f"max_new_tokens={seq.max_new} outside 1.."
                f"{self.max_new_cap} "
                f"(root.common.serving.generate.max_new_tokens)",
                scope="client")
        key = (seq.client, seq.req_id)
        with self._lock:
            if self._closed:
                return Refusal("draining", "service is shutting down")
            if seq.req_id is not None and key in self._inflight:
                self._m["gen_dedup"].inc()
                return None
            if len(self._pending) >= self.pending_bound:
                self._m["gen_refused"].inc()
                now = time.perf_counter()
                if now - self._t_shed_emit > 1.0:
                    # journal the shed EPISODE (>= 1/s), not every
                    # refusal — a flood must not wash the ring
                    self._t_shed_emit = now
                    from znicz_tpu import telemetry
                    telemetry.emit(
                        "page_shed", "serving", reason="queue_bound",
                        replica=self.replica_id,
                        pending=len(self._pending),
                        bound=self.pending_bound,
                        active=len(self._active))
                return Refusal(
                    "shed",
                    f"generation queue at bound ({len(self._pending)} "
                    f"pending, bound {self.pending_bound}) — shed")
            seq.order = self._order
            self._order += 1
            self._pending.append(seq)
            self._inflight.add(key)
            self._m["gen_submitted"].inc()
            return None

    def in_flight(self, client, req_id) -> bool:
        """Is this (client, req_id) currently queued or generating?
        The frontend answers a RESEND of an in-flight generation with a
        heartbeat partial — the client's resend timer refreshes without
        re-executing anything, so a long generation (queued behind slot
        pressure or just slow) never burns the resend cap of a healthy
        service."""
        with self._lock:
            return (client, req_id) in self._inflight

    def close(self) -> None:
        with self._lock:
            self._closed = True

    # -- consumer side (compute thread) ----------------------------------------

    def work_available(self) -> bool:
        return bool(self._pending or self._active)

    def work_ready(self, now: Optional[float] = None) -> bool:
        """True when step() would do compute RIGHT NOW (pending
        admission, sequences mid-prefill, or the decode tick pacing
        window open) — the compute loop's busy/idle poll hint."""
        if self._pending:
            return True
        if not self._active:
            return False
        if any(s.prefilled < s.prompt_len for s in self._active):
            return True
        now = time.perf_counter() if now is None else now
        return now >= self._next_tick

    def _retire(self, seq: GenSeq) -> None:
        """Drop a sequence from the live sets (lock taken here; page
        release is the caller's — compute thread owns the pool)."""
        with self._lock:
            if seq in self._active:
                self._active.remove(seq)
            self._inflight.discard((seq.client, seq.req_id))

    def _release(self, seq: GenSeq) -> None:
        """Return every page reference the request holds — shared
        prefix pages survive via the index's own refs."""
        if seq.pages:
            self.gen.release_pages(seq.pages)
            seq.pages = []

    def _final(self, seq: GenSeq, replies, truncated: Optional[str] = None,
               counter: str = "gen_finished") -> None:
        import numpy as np

        self._release(seq)
        self._retire(seq)
        self._m[counter].inc()
        if self._tracer.enabled and seq.trace_id:
            # the whole admitted lifetime, tagged for fleet stitching
            t0 = seq.t_admitted if seq.t_admitted is not None \
                else seq.t_enqueued
            t1 = seq.t_last if seq.t_last is not None \
                else time.perf_counter()
            self._tracer.add("generate", "sequence", t0,
                             max(t1 - t0, 0.0),
                             {"trace_id": seq.trace_id,
                              "req_id": seq.req_id,
                              "tokens": len(seq.tokens)})
        rep = {"ok": True, "req_id": seq.req_id,
               "replica_id": self.replica_id,
               "tokens": np.asarray(seq.tokens, np.int32),
               "gen": seq.gen, "prompt_len": seq.prompt_len,
               "trace_id": seq.trace_id,
               "timing_ms": self._timing_ms(seq)}
        if truncated:
            rep["truncated"] = truncated
        if seq.logits is not None:
            rep["logits"] = (np.stack(seq.logits) if seq.logits
                             else np.zeros((0, 0), np.float32))
        if seq.logprobs is not None:
            rep["logprobs"] = np.asarray(seq.logprobs, np.float32)
        replies.append((seq.reply_to, rep))

    @staticmethod
    def _timing_ms(seq: GenSeq) -> Dict[str, Optional[float]]:
        """Per-request latency breakdown for the final reply (the
        frontend's slow-request exemplars render it): where the
        request's wall time went, in ms.  None where a phase never
        happened (e.g. expired before admission)."""
        def ms(a, b):
            return None if a is None or b is None \
                else round((b - a) * 1e3, 3)

        end = seq.t_last if seq.t_last is not None else None
        return {"queue_wait": ms(seq.t_enqueued, seq.t_admitted),
                "ttft": ms(seq.t_enqueued, seq.t_first),
                "compute": ms(seq.t_admitted, seq.t_first),
                "total": ms(seq.t_enqueued, end)}

    def _expire(self, seq: GenSeq, replies) -> None:
        import numpy as np

        self._release(seq)
        self._retire(seq)
        self._m["gen_timed_out"].inc()
        replies.append((seq.reply_to, {
            "ok": False, "timed_out": True, "req_id": seq.req_id,
            "replica_id": self.replica_id, "policy": "deadline",
            "tokens": np.asarray(seq.tokens, np.int32),
            "gen": seq.gen, "trace_id": seq.trace_id,
            "error": "deadline expired mid-generation "
                     f"({len(seq.tokens)} of {seq.max_new} tokens "
                     "emitted — shipped partial)"}))

    def _emit(self, seq: GenSeq, token: int, row, logp, now: float,
              replies) -> None:
        seq.tokens.append(int(token))
        if seq.logits is not None:
            seq.logits.append(row.copy())
        if seq.logprobs is not None:
            seq.logprobs.append(logp)
        if seq.t_last is not None:
            self._m_inter_token.observe(now - seq.t_last)
        else:
            # first token of the sequence: TTFT plus where it went
            # (queue wait before admission vs compute after)
            seq.t_first = now
            self._m_ttft.observe(now - seq.t_enqueued)
            self._m_compute.observe(now - (seq.t_admitted
                                           if seq.t_admitted is not None
                                           else seq.t_enqueued))
        seq.t_last = now
        self._m["generated_tokens"].inc()
        if seq.stream and seq.reply_to is not None:
            replies.append((seq.reply_to, {
                "ok": True, "partial": True, "req_id": seq.req_id,
                "replica_id": self.replica_id, "token": int(token),
                "i": len(seq.tokens) - 1, "trace_id": seq.trace_id}))

    # -- page bookkeeping ------------------------------------------------------

    def _page_writable(self, seq: GenSeq, idx: int) -> bool:
        """Make page slot ``idx`` of the request's table privately
        writable: allocate at the boundary, copy-on-write a shared
        (refcount > 1) page.  False -> allocation pressure; the caller
        stalls that row one tick (its claimed pages are kept and the
        row retries next round)."""
        if idx == len(seq.pages):
            page = self.gen.alloc_page()
            if page is None:
                return False
            seq.pages.append(page)
            return True
        page = seq.pages[idx]
        if self.gen.page_ref[page] > 1:
            fresh = self.gen.alloc_page()
            if fresh is None:
                return False
            self.gen.copy_page(page, fresh)
            self.gen.decref(page)
            seq.pages[idx] = fresh
            self._m["cow_copies"].inc()
        return True

    def _ensure_chunk(self, seq: GenSeq) -> bool:
        """Make every page the next prefill chunk writes writable."""
        ps = self.gen.page_size
        t0 = seq.prefilled
        end = min(t0 + self.gen.prefill_chunk, seq.prompt_len)
        for idx in range(t0 // ps, -(-end // ps)):
            if not self._page_writable(seq, idx):
                return False
        return True

    # -- fetch policy ----------------------------------------------------------

    def _fetch(self, chunk, out):
        """Device->host transfer for one dispatch, per the
        ``on_device_sampling`` knob: tokens (+ logprobs on request) on
        the device path, full logits on the host path or when a row
        asked for them.  ``fetch_bytes`` counts the PADDED transfer —
        the wire cost, which is what the sampling fusion shrinks.
        Returns host ``(tokens, logps, logits)`` sliced to real rows
        (None where not fetched)."""
        import numpy as np

        tok_dev, logp_dev, logits_dev, _ = out
        n = len(chunk)
        need_logits = ((not self.on_device)
                       or any(s.return_logits for s in chunk))
        need_logp = (self.on_device
                     and any(s.return_logprobs for s in chunk))
        toks = logps = logits = None
        if self.on_device:
            full = np.asarray(tok_dev)
            self._m["fetch_bytes"].inc(int(full.nbytes))
            toks = full[:n]
        if need_logp:
            full = np.asarray(logp_dev)
            self._m["fetch_bytes"].inc(int(full.nbytes))
            logps = full[:n]
        if need_logits:
            full = np.asarray(logits_dev)
            self._m["fetch_bytes"].inc(int(full.nbytes))
            logits = full[:n]
        return toks, logps, logits

    def _emit_row(self, seq: GenSeq, i: int, fetched, now: float,
                  replies) -> None:
        """Emit one row of a fetched dispatch (sample on host if the
        device tokens weren't shipped)."""
        toks, logps, logits = fetched
        row = None if logits is None else logits[i]
        token = int(toks[i]) if toks is not None else seq.sample(row)
        logp = None
        if seq.return_logprobs:
            logp = (float(logps[i]) if logps is not None
                    else _host_logp(row, token))
        self._emit(seq, token, row, logp, now, replies)

    def step(self):
        """One scheduling round (class docstring).  Returns ``(worked,
        replies)``: whether any compute dispatched, and the
        ``(reply_to, payload)`` pairs to ship."""
        import numpy as np

        replies: List = []
        worked = False
        now = time.perf_counter()
        # 1. deadlines — pending first (never prefill doomed work)
        with self._lock:
            doomed_p = [s for s in self._pending
                        if s.t_deadline is not None and now > s.t_deadline]
            for s in doomed_p:
                self._pending.remove(s)
            doomed_a = [s for s in self._active
                        if s.t_deadline is not None and now > s.t_deadline]
        for s in doomed_p + doomed_a:
            self._expire(s, replies)
        # 2. admission into the concurrency bound; the prefix lookup
        # claims shared full pages (refcounted, read-only) so a hit
        # request starts with only its tail to prefill
        admitted: List[GenSeq] = []
        with self._lock:
            while (self._pending
                   and len(self._active) + len(admitted) < self.gen.slots):
                admitted.append(self._pending.popleft())
            self._active.extend(admitted)
        for seq in admitted:
            seq.t_admitted = now
            self._m_queue_wait.observe(now - seq.t_enqueued)
            if self.gen.prefix is not None:
                pages, covered = self.gen.prefix.lookup(seq.prompt)
                seq.pages = pages
                # full coverage still recomputes the LAST prompt token
                # (a 1-token chunk) — the sampled continuation needs
                # that position's logits, and the write (not the
                # content) is what diverges: it COWs the shared page
                seq.prefilled = min(covered, seq.prompt_len - 1)
        # 3. one decode tick over fully-prefilled sequences, grouped by
        # page-table rung — DISPATCHED, not yet fetched
        chunks = []
        stalled = 0             # rows page-pressure held back this round
        if self._active and now >= self._next_tick:
            groups: Dict[int, List[GenSeq]] = {}
            ticked = False
            for seq in sorted([s for s in self._active
                               if s.prefilled >= s.prompt_len],
                              key=lambda s: s.order):
                ticked = True
                if seq.t >= self.gen.max_ctx:
                    self._final(seq, replies, truncated="context window "
                                "exhausted", counter="gen_truncated")
                    continue
                if not self._page_writable(seq, seq.t
                                           // self.gen.page_size):
                    stalled += 1
                    continue            # page pressure: stall a tick
                groups.setdefault(
                    self.gen._page_rung(max(len(seq.pages), 1)),
                    []).append(seq)
            # dispatch EVERY chunk of the tick before fetching any:
            # chunk N's device compute overlaps chunk N-1's host-side
            # emit and reply shipping (decode_async contract)
            chunk_max = self.gen.decode_rungs[-1]
            for rung in sorted(groups):
                grp = groups[rung]
                for lo in range(0, len(grp), chunk_max):
                    chunk = grp[lo:lo + chunk_max]
                    out = self.gen.decode_async(
                        [s.pages for s in chunk],
                        [s.tokens[-1] for s in chunk],
                        [s.t for s in chunk],
                        [s.temperature for s in chunk],
                        [s.top_k for s in chunk],
                        [s.seed_val for s in chunk])
                    chunks.append((chunk, out))
                    self._m["decode_batches"].inc()
                    self._m["decode_tokens"].inc(len(chunk))
                    worked = True
            if ticked and self.decode_tick_s > 0:
                self._next_tick = now + self.decode_tick_s
        # 4. ONE prefill chunk batch: up to a prefill rung of
        # still-prefilling sequences advance by one bounded chunk.
        # Dispatched BETWEEN the decode dispatches and their fetches —
        # prompt compute overlaps this tick's decode emit.
        batch: List[GenSeq] = []
        for seq in sorted([s for s in self._active
                           if s.prefilled < s.prompt_len],
                          key=lambda s: s.order):
            if len(batch) >= self.gen.prefill_rungs[-1]:
                break
            if self._ensure_chunk(seq):
                batch.append(seq)
            else:
                stalled += 1
        pf = None
        t0s: List[int] = []
        nn: List[int] = []
        if batch:
            c = self.gen.prefill_chunk
            x = np.zeros((len(batch), c), self.gen.runner.dtype)
            for i, seq in enumerate(batch):
                t0 = seq.prefilled
                n_new = min(c, seq.prompt_len - t0)
                x[i, :n_new] = seq.prompt[t0:t0 + n_new]
                t0s.append(t0)
                nn.append(n_new)
            pf = self.gen.prefill_async(
                x, t0s, nn, [s.pages for s in batch],
                [s.temperature for s in batch],
                [s.top_k for s in batch],
                [s.seed_val for s in batch])
            self._m["prefill_batches"].inc()
            self._m["prefill_tokens"].inc(sum(nn))
            worked = True
        # fetch + emit: decode chunks first (oldest dispatches), then
        # the prefill batch's completions
        for chunk, out in chunks:
            fetched = self._fetch(chunk, out)
            t_emit = time.perf_counter()
            if self._tracer.enabled:
                self._tracer.add(
                    "generate", "decode_tick", now, t_emit - now,
                    {"trace_id": chunk[0].trace_id, "rows": len(chunk)})
            for i, seq in enumerate(chunk):
                seq.t += 1
                seq.gen = out[3]
                self._emit_row(seq, i, fetched, t_emit, replies)
                if len(seq.tokens) >= seq.max_new:
                    self._final(seq, replies)
        if pf is not None:
            fetched = self._fetch(batch, pf)
            t_emit = time.perf_counter()
            if self._tracer.enabled:
                self._tracer.add(
                    "generate", "prefill_chunk", now, t_emit - now,
                    {"trace_id": batch[0].trace_id, "rows": len(batch),
                     "tokens": sum(nn)})
            for i, seq in enumerate(batch):
                seq.prefilled = t0s[i] + nn[i]
                if seq.prefilled < seq.prompt_len:
                    continue        # mid-prompt chunk: sample discarded
                seq.t = seq.prompt_len
                seq.gen = pf[3]
                if self.gen.prefix is not None:
                    self.gen.prefix.register(seq.prompt, seq.pages)
                self._m["prefill_seqs"].inc()
                self._emit_row(seq, i, fetched, t_emit, replies)
                if len(seq.tokens) >= seq.max_new:
                    self._final(seq, replies)
        self._note_page_pressure(stalled, now)
        return worked, replies

    def _note_page_pressure(self, stalled: int, now: float) -> None:
        """Journal the page-pressure TRANSITION: the first round where
        allocation held rows back after a clean round emits ONE event
        with the load numbers; subsequent stalled rounds of the same
        episode stay silent (the latch resets on a clean round)."""
        if stalled and not self._page_pressure:
            from znicz_tpu import telemetry
            telemetry.emit(
                "page_shed", "serving", reason="page_pressure",
                replica=self.replica_id, stalled_rows=stalled,
                kv_occupancy=round(self.gen.occupancy(), 4),
                active=len(self._active))
        self._page_pressure = bool(stalled)

    def drain(self) -> List:
        """Abandon every queued/live generation (service shutdown):
        readable ``draining`` replies for all, pages released."""
        replies: List = []
        with self._lock:
            pending = list(self._pending)
            self._pending.clear()
            active = list(self._active)
        for seq in pending + active:
            self._release(seq)
            self._retire(seq)
            self._m["gen_refused"].inc()
            replies.append((seq.reply_to, {
                "ok": False, "rejected": True, "req_id": seq.req_id,
                "replica_id": self.replica_id, "policy": "draining",
                "trace_id": seq.trace_id,
                "error": "service is shutting down — generation "
                         "abandoned"}))
        return replies

    # -- stats -----------------------------------------------------------------

    def inter_token_quantiles(self) -> Dict[str, Optional[float]]:
        import numpy as np

        w = self._m_inter_token.window()
        if w.size == 0:
            return {"inter_token_p50_ms": None, "inter_token_p99_ms": None}
        return {"inter_token_p50_ms":
                round(float(np.percentile(w, 50)) * 1e3, 3),
                "inter_token_p99_ms":
                round(float(np.percentile(w, 99)) * 1e3, 3)}

    def ttft_quantiles(self) -> Dict[str, Optional[float]]:
        """TTFT and its queue-wait/compute split, p50/p99 in ms (None
        on an empty window) — the web panel's generation row."""
        import numpy as np

        out: Dict[str, Optional[float]] = {}
        for key, hist in (("ttft", self._m_ttft),
                          ("queue_wait", self._m_queue_wait),
                          ("compute", self._m_compute)):
            w = hist.window()
            for q in (50, 99):
                out[f"{key}_p{q}_ms"] = (
                    None if w.size == 0
                    else round(float(np.percentile(w, q)) * 1e3, 3))
        return out

    def stats(self) -> Dict:
        with self._lock:
            pending = len(self._pending)
            active = len(self._active)
        out = {"pending": pending, "active": active,
               "max_new_tokens": self.max_new_cap,
               "pending_bound": self.pending_bound,
               "decode_tick_ms": self.decode_tick_s * 1e3,
               "on_device_sampling": self.on_device}
        out.update({name: self._m[name].value for name in self.COUNTERS})
        out.update(self.inter_token_quantiles())
        out.update(self.ttft_quantiles())
        out.update({k: v for k, v in self.gen.stats().items()
                    if k != "jit_cache_size"})
        return out




# historical counter attributes, generated from COUNTERS (name + HELP
# defined exactly once)
for _name, _help in DynamicBatcher.COUNTERS.items():
    setattr(DynamicBatcher, _name, registered_property(_name, _help))
for _name, _help in GenerationScheduler.COUNTERS.items():
    setattr(GenerationScheduler, _name, registered_property(_name, _help))
del _name, _help
