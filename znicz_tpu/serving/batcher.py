"""Dynamic request batcher for the inference service (ISSUE 4).

Small-request serving throughput is dominated by two costs: the per-
dispatch overhead of running the model (a batch-1 forward pays the same
dispatch/jit-call price as a batch-32 one) and jit-cache hygiene (every
distinct batch shape is a fresh XLA compile).  The batcher attacks both:

  - **Coalescing** (clipper/triton-style): a bounded queue of requests is
    drained into batches under a ``(max_batch, max_delay_ms)`` policy —
    a batch closes as soon as it holds ``max_batch`` rows, or when
    ``max_delay_ms`` has elapsed since its first row was taken (latency
    is bounded by construction; an idle service adds no delay because
    the window only starts once a request exists).
  - **Bucket ladder**: each closed batch is padded up to the next rung
    of a fixed ladder (powers of two up to ``max_batch`` by default), so
    the jit cache holds AT MOST ``len(ladder)`` executables and a mixed-
    size request stream causes ZERO recompiles after warmup
    (``ModelRunner.compiles`` is the proof counter).
  - **Backpressure**: the queue is bounded in ROWS; a submit that would
    exceed ``queue_bound`` is shed immediately (counted, refused with a
    readable reason) instead of growing an unbounded backlog whose every
    entry would time out anyway.

Threading contract: ``submit`` may be called from the frontend's router
thread; ``next_batch`` from the single compute thread.  All state is
guarded by one condition variable.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Sequence


class BucketLadder:
    """The fixed ladder of padded batch sizes.  Default rungs are the
    powers of two up to ``max_batch`` (plus ``max_batch`` itself when it
    is not a power of two) — a ladder that over-pads by at most 2x while
    keeping the executable count logarithmic in ``max_batch``."""

    def __init__(self, max_batch: int, rungs: Optional[Sequence[int]] = None):
        self.max_batch = int(max_batch)
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if rungs is None:
            rungs = []
            r = 1
            while r < self.max_batch:
                rungs.append(r)
                r *= 2
            rungs.append(self.max_batch)
        rungs = sorted(set(int(r) for r in rungs))
        if not rungs or rungs[0] < 1 or rungs[-1] != self.max_batch:
            raise ValueError(
                f"bucket ladder {rungs} must be positive and end at "
                f"max_batch={self.max_batch}")
        self.rungs: List[int] = rungs

    def bucket_for(self, n: int) -> int:
        """Smallest rung >= n (n must be within the ladder)."""
        for r in self.rungs:
            if n <= r:
                return r
        raise ValueError(f"{n} rows exceed the ladder's top rung "
                         f"{self.rungs[-1]}")

    def __iter__(self):
        return iter(self.rungs)

    def __repr__(self):
        return f"BucketLadder({self.rungs})"


class Request:
    """One queued inference request: ``x`` is the (n_rows, *sample) host
    array, ``reply_to`` an opaque routing token the frontend uses to
    answer (the ROUTER envelope), ``req_id`` the client's correlation
    id.  ``t_enqueued`` feeds the latency stats and the TTL check."""

    __slots__ = ("x", "n", "reply_to", "req_id", "t_enqueued")

    def __init__(self, x, n: int, reply_to=None, req_id=None):
        self.x = x
        self.n = int(n)
        self.reply_to = reply_to
        self.req_id = req_id
        self.t_enqueued = time.perf_counter()


class DynamicBatcher:
    """Bounded request queue + the coalescing policy (module docstring).

    ``submit`` returns None on acceptance or a human-readable refusal
    reason (shed/oversized) — the frontend ships the reason back so a
    client sees WHY it was refused instead of timing out.
    """

    def __init__(self, max_batch: int = 32, max_delay_ms: float = 5.0,
                 queue_bound: int = 256,
                 ladder: Optional[BucketLadder] = None):
        self.ladder = ladder or BucketLadder(max_batch)
        self.max_batch = self.ladder.max_batch
        self.max_delay_s = float(max_delay_ms) / 1e3
        self.queue_bound = int(queue_bound)
        self._q: collections.deque = collections.deque()
        self._rows = 0                      # rows currently queued
        self._cond = threading.Condition()
        self._closed = False
        # -- accounting (the serving panel's inputs) -----------------------
        self.submitted = 0                  # accepted requests
        self.shed = 0                       # refused: queue at bound
        self.oversized = 0                  # refused: n > max_batch
        self.batches = 0                    # batches closed
        self.batched_requests = 0           # requests inside those batches
        self.batched_rows = 0               # real rows inside those batches
        self.padded_rows = 0                # pad rows added by the ladder
        self.bucket_hits: Dict[int, int] = {r: 0 for r in self.ladder}

    # -- producer side ---------------------------------------------------------

    def submit(self, req: Request) -> Optional[str]:
        if req.n < 1 or req.n > self.max_batch:
            self.oversized += 1
            return (f"request of {req.n} rows exceeds max_batch="
                    f"{self.max_batch} (split it client-side)")
        with self._cond:
            if self._closed:
                return "service is shutting down"
            if self._rows + req.n > self.queue_bound:
                self.shed += 1
                return (f"queue at bound ({self._rows} rows queued, "
                        f"bound {self.queue_bound}) — shed")
            self._q.append(req)
            self._rows += req.n
            self.submitted += 1
            self._cond.notify()
            return None

    @property
    def queue_depth(self) -> int:
        """Rows currently queued (not yet taken into a batch)."""
        return self._rows

    def close(self) -> None:
        """Wake every waiter; ``next_batch`` drains what is queued and
        then returns None forever."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------------

    def next_batch(self, timeout: float = 0.2,
                   wait_fill: bool = True) -> Optional[List[Request]]:
        """The next coalesced batch, or None when nothing arrived within
        ``timeout``.  Blocks up to ``timeout`` for the FIRST request;
        from that moment the ``max_delay_ms`` window runs, during which
        further requests are folded in until ``max_batch`` rows are
        reached.  A request that does not fit the remaining space stays
        queued for the next batch (requests are never split).

        ``wait_fill=False`` skips the window: only already-queued
        requests are taken.  That is the PIPELINED grab — the compute
        loop calls it while the previous batch is still on the device,
        and waiting out a window there would hold the finished batch's
        replies hostage to the next batch's coalescing (measured +1
        ``max_delay`` on p99)."""
        with self._cond:
            deadline = time.perf_counter() + max(timeout, 0.0)
            while not self._q:
                if self._closed:
                    return None
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            batch = [self._q.popleft()]
            rows = batch[0].n
            self._rows -= rows
            flush_at = time.perf_counter() + self.max_delay_s
            while rows < self.max_batch:
                if self._q:
                    if self._q[0].n > self.max_batch - rows:
                        break               # would overflow: next batch
                    req = self._q.popleft()
                    self._rows -= req.n
                    batch.append(req)
                    rows += req.n
                    continue
                remaining = flush_at - time.perf_counter()
                if not wait_fill or remaining <= 0 or self._closed:
                    break
                self._cond.wait(remaining)
        bucket = self.ladder.bucket_for(rows)
        self.batches += 1
        self.batched_requests += len(batch)
        self.batched_rows += rows
        self.padded_rows += bucket - rows
        self.bucket_hits[bucket] += 1
        return batch

    # -- stats -----------------------------------------------------------------

    def occupancy(self) -> Optional[float]:
        """Mean real rows per closed batch / max_batch (None before the
        first batch) — 1.0 means every batch left full."""
        if not self.batches:
            return None
        return self.batched_rows / (self.batches * self.max_batch)

    def stats(self) -> Dict:
        occ = self.occupancy()
        return {
            "max_batch": self.max_batch,
            "max_delay_ms": self.max_delay_s * 1e3,
            "queue_bound": self.queue_bound,
            "queue_depth": self.queue_depth,
            "submitted": self.submitted,
            "shed": self.shed,
            "oversized": self.oversized,
            "batches": self.batches,
            "batched_requests": self.batched_requests,
            "batched_rows": self.batched_rows,
            "padded_rows": self.padded_rows,
            "mean_occupancy": None if occ is None else round(occ, 4),
            "bucket_hits": dict(self.bucket_hits),
        }
