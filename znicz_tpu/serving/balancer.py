"""Replica balancer: fleet-grade serving over N ``ModelRunner``
replicas (ISSUE 12) — the serving-plane twin of the elastic training
tree (PR 10).

One ROUTER front socket faces BOTH planes:

  - **clients** (``InferenceClient`` DEALERs) send the same wire-v3
    requests they would send a single replica — the balancer is
    protocol-indistinguishable from an ``InferenceServer`` to them;
  - **replicas** heartbeat into it (``--serve ... --announce`` /
    ``InferenceServer(announce=...)``), piggybacking their ``/readyz``
    state, queue depth and per-bucket p99 on every beat.  Membership is
    TTL'd: a replica that stops beating is evicted and its in-flight
    requests fail over immediately.

Per live replica the balancer holds one DEALER onto the replica's own
ROUTER bind (the data plane).  Requests are **peeked, never decoded**:
:func:`wire.peek_message` reads the metadata skeleton without touching
a tensor byte, the client's ``req_id`` is rewritten to a balancer-unique
id (two clients may both be on request 1), and the SAME frames are
forwarded — the balancer scales because it moves buffers, not arrays
(the master stopped decoding every delta in PR 9; the balancer never
starts).

**Exactly-once failover**: every accepted request lives in a ledger
entry carrying its original (rewritten) frames.  A replica that dies,
flaps, or sits on a request past ``failover_timeout_s`` gets the entry
re-dispatched — same bytes — to a healthy replica; late duplicate
replies are dropped by the ledger (first reply wins), so the client
sees ONE answer or ONE readable refusal (``policy: failover`` once
``failover_tries`` is spent, ``deadline`` once its budget is), never
two and never silence.  The ledger balances by construction:
``accepted == replied + refused + in_flight``.

**Hedged retries**: after a hedge delay derived from the balancer's own
observed reply p99 (``max(hedge_floor_s, hedge_p99_mult * p99)`` capped
at ``hedge_cap_s``), a still-unanswered request is raced on a second
replica; the first reply wins and the loser is deduped.  ``hedges`` /
``hedge_wins`` count the races and how often the hedge paid.

**Fleet-coordinated canary rollover**: one ``swap`` command drives the
whole fleet through a canary→full wave, keyed on SNAPSHOT PATHS (the
invariant healing maintains) — never on predicted generation numbers,
which legitimately drift across rollback-retry and restart-heal
cycles.  Canary replicas are warmed OFF-ROTATION (swap sent, the
path flip confirmed via heartbeats; every phase timeout-bounded), then
serve a deterministic share of traffic while the balancer compares
their p99 against the old generation's and — unless the swap was sent
with ``parity: false`` (a deliberately-different model) — shadow-probes
reply parity: every ``parity_every``-th old-generation dispatch is
duplicated to a canary and the tensor frames compared bit-exactly.  A
p99 or parity regression (or canary starvation past
``canary_timeout_s``) triggers **auto-rollback**: canaries restore
their retained previous generation (``rollback`` command — instant,
disk-free, generation stamp restored), and the losing generation's
p99/parity/counters are preserved in ``rollover_history`` for the
postmortem.  A clean canary promotes the rest of the fleet one replica
at a time, each warmed off-rotation, so the fleet never dips below
quorum mid-wave.  A replica that restarts mid-epoch with its boot
snapshot is HEALED — its heartbeat's ``snapshot_path`` disagrees with
the fleet's promoted path, so the balancer re-swaps it off-rotation —
which keeps generation stamps lockstep across preemptions.

Config home: ``root.common.serving.balance.*`` (declared in the serving
DEFAULTS table, read through a local alias like the admission subtree).
CLI: ``python -m znicz_tpu --balance [BIND] --replicas ep1,ep2,...``;
gate: ``python bench.py --fleet`` (README "Replica fleet").
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu import telemetry
from znicz_tpu.telemetry.metrics import registered_property

from .frontend import DEFAULTS


class _Entry:
    """One ledger entry: an accepted request's rewritten frames plus
    its dispatch history — everything exactly-once needs."""

    __slots__ = ("rid", "client_rid", "envelope", "frames", "t_accept",
                 "deadline", "t_sent", "targets", "tries", "hedged",
                 "hedge_target", "held", "probe_rid", "kind",
                 "primary_rid", "trace_id")

    def __init__(self, rid: int, client_rid, envelope, frames,
                 deadline: float, kind: str = "infer"):
        self.rid = rid
        self.client_rid = client_rid
        self.envelope = envelope
        self.frames = frames
        self.t_accept = time.perf_counter()
        self.deadline = deadline            # absolute, local clock
        self.t_sent: Optional[float] = None
        self.targets: List[str] = []        # replica_ids, dispatch order
        self.tries = 0
        self.hedged = False
        self.hedge_target: Optional[str] = None
        #: replicas whose dispatch-count reservation THIS entry
        #: currently holds — released exactly once each (a failover
        #: releases its old target; retirement must not re-release it)
        self.held: set = set()
        self.probe_rid: Optional[int] = None    # parity probe spawned
        self.primary_rid: Optional[int] = None  # set on probe entries
        self.kind = kind                    # "infer" | "probe" | "ctrl"
        self.trace_id = None                # fleet stitching (ISSUE 20)


def _cfg_balance() -> Dict:
    """The resolved ``root.common.serving.balance.*`` knob set (read
    through a local alias so the config-knob lint tracks every key)."""
    d = DEFAULTS["balance"]
    bal = root.common.serving.balance
    return {k: type(d[k])(bal.get(k, d[k])) if not isinstance(d[k], bool)
            else bool(bal.get(k, d[k])) for k in d}


class ReplicaBalancer:
    """Health-checked least-loaded balancer over N replica processes.

    ``bind`` may use a wildcard port; the resolved address is in
    ``endpoint`` once serving starts.  ``replicas`` (optional) is the
    static endpoint list to pre-connect data sockets to — membership
    itself always comes from heartbeats, so a replica not on the list
    joins the moment it announces.  Drive with ``start()``/``stop()``;
    ``max_requests`` makes the loop exit after that many answered
    requests (CLI/launcher tests)."""

    #: balancer counters (telemetry component="balancer"): name -> HELP
    COUNTERS = {
        "accepted": "infer requests accepted into the ledger",
        "replied": "ok replies forwarded to clients",
        "refused": "refusals forwarded/issued to clients",
        "failovers": "in-flight requests re-dispatched (same bytes) "
                     "after a replica died/flapped/timed out",
        "hedges": "hedged second dispatches raced",
        "hedge_wins": "races the hedge replica answered first",
        "dup_replies_dropped": "late duplicate replies deduped by the "
                               "ledger",
        "sheds_retried": "service-scoped replica sheds retried on "
                         "another replica",
        "heartbeats": "replica heartbeats received",
        "replicas_lost": "TTL membership evictions",
        "rollovers": "canary waves promoted fleet-wide",
        "rollbacks": "canary waves auto-rolled-back on regression",
        "heals": "restarted replicas re-swapped onto the fleet path",
        "parity_checks": "shadow parity probes compared",
        "parity_mismatches": "probes whose tensor frames differed",
        "replica_bad_frames": "replica-side bad-frame refusals "
                              "(unattributable; failover timer recovers)",
        "scale_ups": "autoscaler spawn actions issued (ISSUE 17)",
        "scale_downs": "autoscaler drain-then-retire actions completed",
        "scale_drain_timeouts": "retiring replicas whose drain exceeded "
                                "autoscale_drain_timeout_s (retired "
                                "anyway; in-flight work fails over)",
    }

    def __init__(self, bind: str = "tcp://127.0.0.1:*",
                 replicas: Tuple[str, ...] = (),
                 min_replicas: Optional[int] = None,
                 max_requests: Optional[int] = None, **knobs):
        from znicz_tpu import telemetry
        from znicz_tpu.parallel import wire

        self.bind = bind
        self.endpoint: Optional[str] = None
        self.static_replicas = tuple(replicas)
        self.max_requests = max_requests
        self.knobs = _cfg_balance()
        self.knobs.update(knobs)            # test overrides
        if min_replicas is not None:
            self.knobs["min_replicas"] = int(min_replicas)
        self.codec = wire.Codec(owner="balancer")   # serve-thread only
        _sc = telemetry.scope("balancer")
        self._m = {name: _sc.counter(name, help)
                   for name, help in self.COUNTERS.items()}
        _sc.gauge("ready_replicas", "heartbeat-live, ready members",
                  fn=telemetry.weak_fn(self, lambda b: b.ready_count()))
        _sc.gauge("in_flight", "ledger entries awaiting a reply",
                  fn=telemetry.weak_fn(self, lambda b: b.in_flight))
        # -- fleet observability (ISSUE 20): the balancer IS the
        # serving coordinator — heartbeats/replies carry the fleet's
        # spans, events and metric snapshots into the stores behind
        # /trace.json?fleet=1, /events.json and the merged /metrics
        self._tracer = telemetry.tracer()
        telemetry.set_identity("balancer")
        self._t_obs_drain = 0.0         # self-ingest rate limiter (s)
        # -- state below is serve-thread-written, stats()-read: every
        # mutation happens under _lock (REENTRANT: helpers lock their
        # own bodies — the thread lint's lexical contract — and are
        # also called under the serve loop's outer hold)
        self._lock = threading.RLock()
        #: replica_id -> heartbeat view (endpoint, last_seen, ready,
        #: gen, queue_depth, p99_ms_by_bucket, swapping, snapshot_path)
        self._members: Dict[str, Dict] = {}
        self._inflight: Dict[int, _Entry] = {}      # infer ledger
        self._probes: Dict[int, _Entry] = {}        # parity probes
        self._ctrl: Dict[int, Dict] = {}            # swap/rollback cmds
        self._dispatch_counts: Dict[str, int] = {}  # approx per-replica
        self._parked: List[_Entry] = []     # accepted, no ready replica
        self._lat: List[float] = []         # recent reply latencies (s)
        self._rollover: Optional[Dict] = None
        self.rollover_history: List[Dict] = []
        self._fleet_path: Optional[str] = None      # last promoted path
        self._healing: Dict[str, float] = {}        # replica -> t sent
        self._parity_buf: Dict[int, Dict] = {}      # probe_rid -> frames
        # -- autoscaler (ISSUE 17; armed by enable_autoscale) — every
        # field below is serve-thread-mutated under _lock like the
        # membership state above
        self._scaler: Optional[Dict] = None     # {"spawn", "retire"}
        #: replica_id -> drain start: retired AFTER in-flight drains
        self._retiring: Dict[str, float] = {}
        #: spawn timestamps awaiting a NEW member announcement
        self._scale_pending: List[float] = []
        self._scale_known: set = set()      # member ids already seen
        self._scale_streak = {"high": 0, "low": 0}
        self._scale_last = {"action": 0.0, "eval": 0.0}
        self._rid = 0
        self._rr = 0                        # least-loaded tie-breaker
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._serve_error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self.started_at: Optional[float] = None
        #: optional FaultSchedule for the serve loop's built-in ingress
        #: fault hook (ISSUE 14 cross-plane soak); the live
        #: TransportLoop sits on ``_transport`` while serving
        self.transport_chaos = None
        self._transport = None
        self.log = logging.getLogger("znicz.balancer")

    # -- registry-backed counters under their historical names (props
    # generated from COUNTERS after the class body)

    # -- membership views ------------------------------------------------------

    @property
    def in_flight(self) -> int:
        return len(self._inflight) + len(self._parked)

    def ready_count(self) -> int:
        with self._lock:
            return sum(1 for m in self._members.values() if m["ready"])

    def member_count(self) -> int:
        with self._lock:
            return len(self._members)

    @property
    def min_replicas(self) -> int:
        return int(self.knobs["min_replicas"])

    def degraded(self) -> bool:
        """True below the ``min_replicas`` quorum — the aggregate
        ``/readyz`` 503 signal (mirrors the PR 10 training quorum)."""
        return self.ready_count() < self.min_replicas

    def ledger(self) -> Dict[str, int]:
        """The no-silent-loss invariant, one dict:
        ``accepted == replied + refused + in_flight`` at every instant
        (parity probes and control commands are tracked separately and
        never enter it)."""
        with self._lock:
            # counters tick under this same lock on the serve thread,
            # so the snapshot below is internally consistent
            in_flight = len(self._inflight) + len(self._parked)
            accepted = self.accepted
            replied = self.replied
            refused = self.refused
        return {"accepted": accepted, "replied": replied,
                "refused": refused, "in_flight": in_flight,
                "balanced": accepted == replied + refused + in_flight}

    def stats(self) -> Dict:
        now = time.perf_counter()
        with self._lock:
            members = [
                {"replica_id": rid,
                 "endpoint": m["endpoint"],
                 "ready": m["ready"],
                 "gen": m["gen"],
                 "queue_depth": m["queue_depth"],
                 "in_flight": self._dispatch_counts.get(rid, 0),
                 "last_heartbeat_s": round(now - m["last_seen"], 3),
                 "swapping": m["swapping"],
                 "snapshot_path": m["snapshot_path"],
                 "in_rotation": rid not in self._rotation_out(),
                 "device_count": m.get("device_count", 1),
                 "mesh": m.get("mesh"),
                 "warm_source": m.get("warm_source"),
                 "warm_hits": m.get("warm_hits", 0),
                 "warm_misses": m.get("warm_misses", 0),
                 "boot_s": m.get("boot_s"),
                 "retiring": rid in self._retiring,
                 "healing": rid in self._healing,
                 "p99_ms_by_bucket": dict(m["p99_ms_by_bucket"])}
                for rid, m in sorted(self._members.items())]
            autoscale = {"enabled": self._scaler is not None
                         and bool(self.knobs["autoscale"]),
                         "max": int(self.knobs["autoscale_max"]),
                         "pending_spawns": len(self._scale_pending),
                         "retiring": sorted(self._retiring),
                         "servable": len(self._servable_ids())}
            roll = None
            if self._rollover is not None:
                r = self._rollover
                roll = {"phase": r["phase"], "path": r["path"],
                        "canary": list(r["canary"]),
                        "old_gen": r["old_gen"], "new_gen": r["new_gen"],
                        "parity": r["parity"],
                        "parity_mismatches": r["mismatches"],
                        "canary_samples": len(r["lat_new"]),
                        "old_samples": len(r["lat_old"])}
            history = list(self.rollover_history)
        out = {"endpoint": self.endpoint,
               "replicas": members,
               "ready_replicas": sum(1 for m in members if m["ready"]),
               "total_replicas": len(members),
               "min_replicas": self.min_replicas,
               "degraded": sum(1 for m in members if m["ready"])
               < self.min_replicas,
               "static_replicas": list(self.static_replicas),
               "fleet_path": self._fleet_path,
               "autoscale": autoscale,
               "rollover": roll,
               "rollover_history": history,
               "hedge_delay_ms": round(self._hedge_delay() * 1e3, 2),
               "ledger": self.ledger(),
               "bad_frames": self.codec.bad_frames}
        for name in self.COUNTERS:
            out[name] = getattr(self, name)
        return out

    def _rotation_out(self) -> set:
        """Replica_ids currently held OUT of dispatch (warming during a
        rollover wave).  Lock held by callers."""
        if self._rollover is None:
            return set()
        return set(self._rollover["warming"])

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ReplicaBalancer":
        self._thread = threading.Thread(target=self.serve, daemon=True,
                                        name="znicz-balancer")
        self._thread.start()
        if not self._ready.wait(timeout=60):
            raise RuntimeError(
                f"balancer failed to come up on {self.bind} within 60s")
        if self._serve_error is not None:
            raise RuntimeError(
                f"balancer failed on {self.bind}: "
                f"{self._serve_error!r}") from self._serve_error
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def alive(self) -> bool:
        return self._serve_error is None and (
            self._thread is None or self._thread.is_alive())

    def serve(self) -> None:
        try:
            self._serve()
        except BaseException as exc:
            with self._lock:
                self._serve_error = exc
            raise
        finally:
            self._ready.set()

    # -- the serve loop --------------------------------------------------------

    def _serve(self) -> None:
        from znicz_tpu.transport import TransportLoop

        loop = self._transport = TransportLoop(
            "balancer", stop=self._stop, instance=self.bind)
        if self.transport_chaos is not None:
            loop.inject_faults(self.transport_chaos)
        #: endpoint -> data DEALER (serve-thread-owned, like the codec;
        #: reply routing rides each socket's registered closure)
        data: Dict[str, object] = {}
        try:
            front = loop.bind_router(self.bind)
            self.endpoint = loop.resolved_endpoint(front)
            self.started_at = time.perf_counter()

            def data_sock(endpoint: str):
                sock = data.get(endpoint)
                if sock is None:
                    sock = loop.connect_dealer(endpoint)
                    data[endpoint] = sock
                    # replica replies drain BEFORE new client requests
                    # (priority 0 < the front's 10): a reply frees its
                    # ledger slot, so dispatch weighs loads that are
                    # current, not one tick stale
                    loop.register(
                        sock,
                        lambda frames, _ep=endpoint:
                        self._handle_replica(_ep, frames),
                        drain=True, priority=0)
                return sock

            def drop_unused_data_socks(live_endpoints) -> None:
                # endpoint churn (wildcard-bind replicas get a fresh
                # port per restart): a socket no member references
                # anymore would otherwise leak an fd + poller
                # registration per restart
                for ep in [ep for ep in data
                           if ep not in live_endpoints
                           and ep not in self.static_replicas]:
                    sock = data.pop(ep)
                    loop.unregister(sock)   # also closes it

            for ep in self.static_replicas:
                data_sock(ep)
            self._data_sock = data_sock     # serve-thread closures for
            self._front = front             # the helpers below
            self._drop_unused_data_socks = drop_unused_data_socks
            loop.register(front, self._handle_front, drain=True,
                          priority=10)

            def tick() -> None:
                if self.max_requests is not None and \
                        self.replied + self.refused >= self.max_requests:
                    loop.stop()
                    return
                with self._lock:
                    self._tick_membership()
                    self._tick_inflight()
                    self._tick_rollover()
                # OUTSIDE the hold above: the autoscaler computes its
                # decisions under the lock but runs spawn/retire
                # callbacks unlocked (a process spawn may block for
                # seconds, and the ledger must keep ticking under it)
                self._tick_autoscale()
                # fleet self-ingest (ISSUE 20): the balancer's own
                # spans/events join the stitched stores it coordinates
                # (rate-limited — the stores lock internally)
                t = time.perf_counter()
                if t - self._t_obs_drain > 0.25:
                    self._t_obs_drain = t
                    telemetry.drain_own_spans()
                    telemetry.drain_own_events()

            loop.add_tick(tick)
            self._ready.set()
            loop.run(poll_ms=5)
        finally:
            self._stop.set()
            loop.close()

    # -- front plane: clients + heartbeats -------------------------------------

    def _send_front(self, envelope: List[bytes], frames: List) -> None:
        self._front.send_multipart(list(envelope) + list(frames),
                                   copy=False)

    def _refuse_client(self, entry: _Entry, policy: str,
                       error: str) -> None:
        """The ONE readable refusal an accepted request may end in
        (lock held)."""
        self._m["refused"].inc()
        if entry.probe_rid is not None:
            # the shadow probe's buffered reply bytes die with the
            # primary — a refused request proves no parity either way
            self._parity_buf.pop(entry.probe_rid, None)
        self._send_front(entry.envelope, self.codec.encode(
            {"ok": False, "req_id": entry.client_rid, "lb": True,
             "policy": policy, "scope": "service",
             "timed_out": policy == "deadline", "error": error}))

    def _handle_front(self, frames: List[bytes]) -> None:
        from znicz_tpu.parallel import wire

        envelope, payload = wire.split_envelope(frames)
        if not envelope and frames:
            envelope, payload = list(frames[:1]), list(frames[1:])
        try:
            skel = wire.peek_message(payload)
        except wire.WireError as exc:
            self.log.warning("refused undecodable front message: %s", exc)
            self._send_front(envelope, self.codec.refusal(
                exc, legacy=False, lb=True))
            return
        self.codec.count_message_in(payload)
        cmd = skel.get("cmd")
        rid = skel.get("req_id")
        if cmd == "heartbeat":
            self._handle_heartbeat(skel)
            self._send_front(envelope, self.codec.encode(
                {"ok": True, "hb": True}))
            return
        if cmd == "ping":
            self._send_front(envelope, self.codec.encode(
                {"ok": True, "pong": True, "req_id": rid, "lb": True}))
            return
        if cmd == "stats":
            self._send_front(envelope, self.codec.encode(
                {"ok": True, "stats": self.stats(), "req_id": rid,
                 "lb": True}))
            return
        if cmd == "swap":
            self._handle_swap(envelope, skel)
            return
        if cmd not in ("infer", "generate"):
            self._send_front(envelope, self.codec.encode(
                {"ok": False, "req_id": rid, "lb": True,
                 "error": f"unknown cmd {cmd!r}"}))
            return
        if cmd == "generate" and skel.get("stream"):
            # the exactly-once ledger is first-reply-wins: a streamed
            # generation's partials would retire the entry on token 1
            # and drop the rest as dups — refuse readably instead
            self._send_front(envelope, self.codec.encode(
                {"ok": False, "req_id": rid, "lb": True,
                 "error": "balancer cannot relay streamed generation "
                          "(first-reply-wins ledger needs ONE final "
                          "reply) — set stream=False or connect to a "
                          "replica directly"}))
            return
        # -- accept one infer/generate request into the ledger
        deadline_s = float(self.knobs["failover_tries"]) \
            * float(self.knobs["failover_timeout_s"])
        budget_ms = skel.get("deadline_ms")
        if budget_ms is not None:
            try:
                budget_s = float(budget_ms) / 1e3
            except (TypeError, ValueError):
                budget_s = float("nan")
            if np.isfinite(budget_s) and budget_s > 0:
                deadline_s = budget_s
        with self._lock:
            self._rid += 1
            lb_rid = self._rid
            rewritten = wire.restamp_message(payload, req_id=lb_rid)
            entry = _Entry(lb_rid, rid, list(envelope), rewritten,
                           time.perf_counter() + deadline_s)
            entry.trace_id = skel.get("trace_id")
            self._m["accepted"].inc()
            if not self._dispatch(entry):
                if len(self._parked) >= int(self.knobs["park_bound"]):
                    self._refuse_client(
                        entry, "shed",
                        f"no ready replica and the park queue is at "
                        f"its bound ({self.knobs['park_bound']}) — shed")
                    return
                self._parked.append(entry)

    def _handle_heartbeat(self, skel: Dict) -> None:
        self._m["heartbeats"].inc()
        replica_id = str(skel.get("replica_id") or "")
        endpoint = skel.get("endpoint")
        if not replica_id or not isinstance(endpoint, str) \
                or not endpoint:
            return                          # malformed beat: ignored
        self._data_sock(endpoint)
        with self._lock:
            prev = self._members.get(replica_id)
            self._members[replica_id] = {
                "endpoint": endpoint,
                "last_seen": time.perf_counter(),
                "ready": bool(skel.get("ready")),
                "gen": int(skel.get("gen") or 0),
                "queue_depth": int(skel.get("queue_depth") or 0),
                "swapping": bool(skel.get("swapping")),
                "draining": bool(skel.get("draining")),
                "snapshot_path": skel.get("snapshot_path") or "",
                # capacity (ISSUE 13): a pod-slice replica advertises
                # its mesh; pre-mesh replicas beat without it -> 1
                "device_count": max(1, int(skel.get("device_count")
                                           or 1)),
                "mesh": skel.get("mesh") if isinstance(
                    skel.get("mesh"), dict) else None,
                "p99_ms_by_bucket": dict(
                    skel.get("p99_ms_by_bucket") or {}),
                # warmup provenance (ISSUE 17): the fleet panel's warm
                # columns — where this replica's executables came from
                # and how long its boot took
                "warm_source": skel.get("warm_source"),
                "warm_hits": int(skel.get("warm_hits") or 0),
                "warm_misses": int(skel.get("warm_misses") or 0),
                "boot_s": skel.get("boot_s"),
            }
            if prev is None:
                telemetry.emit("replica_joined", "serving",
                               replica=replica_id, endpoint=endpoint,
                               members=len(self._members))
            if prev is not None and prev["endpoint"] != endpoint:
                # in-place endpoint change (wildcard-bind restart
                # faster than the TTL): reap the old endpoint's socket
                # now — the eviction path never sees it
                self._drop_unused_data_socks(
                    {m["endpoint"] for m in self._members.values()})
            self._maybe_heal(replica_id)
        # fleet observability piggyback (ISSUE 20): spans, journal
        # events and registry snapshots ride the beat — ingested OUTSIDE
        # the membership lock (the fleet stores lock internally)
        origin = str(skel.get("origin") or replica_id)
        if skel.get("spans"):
            telemetry.fleet_trace().ingest(origin, skel["spans"])
        if skel.get("events"):
            telemetry.fleet_events().ingest(origin, skel["events"])
        if skel.get("metrics"):
            telemetry.fleet_metrics().update(origin, skel["metrics"])

    def _maybe_heal(self, replica_id: str) -> None:
        """A replica whose boot snapshot disagrees with the promoted
        fleet path (it restarted mid-epoch) is re-swapped off-rotation
        — the runtime healing that keeps generation stamps lockstep
        under preemption (lock held)."""
        if self._fleet_path is None or self._rollover is not None:
            return
        m = self._members[replica_id]
        if m["snapshot_path"] == self._fleet_path:
            self._healing.pop(replica_id, None)
            return
        if not m["ready"] or m["swapping"]:
            return
        # debounce: heartbeats beat far faster than a swap completes,
        # and a re-heal per beat would walk the generation counter away
        # from the fleet's lockstep
        now = time.perf_counter()
        t_sent = self._healing.get(replica_id)
        if t_sent is not None and now - t_sent < float(
                self.knobs["heal_backoff_s"]):
            return
        self._healing[replica_id] = now
        self._m["heals"].inc()
        telemetry.emit("heal", "serving", replica=replica_id,
                       snapshot=m["snapshot_path"],
                       fleet=self._fleet_path)
        self.log.info("healing %s: snapshot %r != fleet %r",
                      replica_id, m["snapshot_path"], self._fleet_path)
        self._send_ctrl(replica_id, {"cmd": "swap",
                                     "path": self._fleet_path})

    # -- dispatch --------------------------------------------------------------

    def _candidates(self, exclude=()) -> List[str]:
        """Ready, in-rotation members, least-loaded first (heartbeat
        queue depth + balancer-tracked in-flight, NORMALIZED by the
        replica's advertised device count — an 8-chip pod slice drains
        8x the rows of a 1-chip replica, so equal raw queue depths do
        not mean equal wait; ISSUE 13); round-robin tie-break.  Lock
        held."""
        out = []
        stale = []
        rotation_out = self._rotation_out()
        heal_gate = self._rollover is None \
            and self._fleet_path is not None
        for rid, m in self._members.items():
            if not m["ready"] or rid in exclude or rid in rotation_out \
                    or rid in self._retiring:
                # retiring = drain-then-retire (ISSUE 17): its in-flight
                # work finishes, but NEW work never lands on a replica
                # the autoscaler is about to kill
                continue
            load = (m["queue_depth"]
                    + self._dispatch_counts.get(rid, 0)) \
                / m.get("device_count", 1)
            if heal_gate and m["snapshot_path"] != self._fleet_path:
                # awaiting heal: it would answer with stale params and
                # an off-wave generation stamp — last resort only
                stale.append((load, rid))
                continue
            out.append((load, rid))
        if not out:
            # a fully-stale fleet (mass restart) still serves: stale-
            # but-consistent beats silence, and the heals are en route
            out = stale
        if not out:
            return []
        out.sort(key=lambda t: t[0])
        best = [rid for load, rid in out if load == out[0][0]]
        self._rr += 1
        first = best[self._rr % len(best)]
        rest = [rid for _, rid in out if rid != first]
        return [first] + rest

    def _send_to(self, replica_id: str, frames: List) -> bool:
        """Ship frames to one replica's data DEALER (lock held)."""
        m = self._members.get(replica_id)
        if m is None:
            return False
        sock = self._data_sock(m["endpoint"])
        sock.send_multipart([b""] + list(frames), copy=False)
        return True

    def _dispatch(self, entry: _Entry, exclude=(), pool=None) -> bool:
        """Send an entry to the best candidate (optionally restricted
        to ``pool``); False when nobody is ready (lock held)."""
        with self._lock:
            roll = self._rollover
            if (pool is None and entry.kind == "infer" and roll is not None
                    and roll["phase"] == "canary"):
                # deterministic canary share (the wave's judged traffic):
                # every stride-th accept goes to the canary pool, the rest
                # to the old pool — least-loaded inside each; an
                # empty/unready steered pool falls back to anyone ready
                # (steering must never park a request chaos could serve)
                roll["steer"] += 1
                pool = roll["canary"] if roll["steer"] % roll["stride"] == 0 \
                    else (roll["old"] or None)
                if pool is not None:
                    cands = self._candidates(exclude=exclude)
                    steered = [c for c in cands if c in pool]
                    cands = steered or cands
                else:
                    cands = self._candidates(exclude=exclude)
            else:
                cands = self._candidates(exclude=exclude)
                if pool is not None:
                    cands = [c for c in cands if c in pool] or []
            if not cands:
                return False
            target = cands[0]
            if not self._send_to(target, entry.frames):
                return False
            entry.targets.append(target)
            entry.t_sent = time.perf_counter()
            entry.tries += 1
            if entry.kind == "probe":
                # shadow work: a probe in flight must not bias real
                # traffic away from the canary it is probing
                self._probes[entry.rid] = entry
            else:
                self._dispatch_counts[target] = \
                    self._dispatch_counts.get(target, 0) + 1
                entry.held.add(target)
                self._inflight[entry.rid] = entry
            # canary phase: parity-probe a sample of OLD-generation traffic
            roll = self._rollover
            if (roll is not None and roll["phase"] == "canary"
                    and entry.kind == "infer" and roll["parity"]
                    and target not in roll["canary"]):
                roll["old_dispatches"] += 1
                if roll["old_dispatches"] % int(
                        self.knobs["parity_every"]) == 0:
                    self._spawn_probe(entry)
            return True

    def _release(self, entry: _Entry) -> None:
        """Drop an entry's dispatch-count reservations (lock held)."""
        if entry.kind == "probe":
            return                          # never counted (see dispatch)
        for target in entry.held:
            n = self._dispatch_counts.get(target, 0)
            if n > 0:
                self._dispatch_counts[target] = n - 1
        entry.held = set()

    def _spawn_probe(self, primary: _Entry) -> None:
        """Duplicate a request to a canary replica as a shadow parity
        probe — never forwarded to the client (lock held)."""
        from znicz_tpu.parallel import wire

        roll = self._rollover
        pool = [r for r in roll["canary"] if r in self._members
                and self._members[r]["ready"]]
        if not pool or primary.probe_rid is not None:
            return
        self._rid += 1
        probe_rid = self._rid
        frames = wire.restamp_message(primary.frames, req_id=probe_rid)
        probe = _Entry(probe_rid, None, [], frames,
                       primary.deadline, kind="probe")
        probe.primary_rid = primary.rid
        if self._dispatch(probe, pool=pool):
            primary.probe_rid = probe_rid
            self._parity_buf[probe_rid] = {}

    # -- replica plane: replies ------------------------------------------------

    def _tensor_bytes(self, frames: List[bytes]) -> bytes:
        """The reply's raw tensor frames, concatenated — the parity
        comparison key (metadata differs across generations by
        design; the ANSWER must not)."""
        return b"".join(bytes(f) for f in frames[1:])

    def _handle_replica(self, endpoint: str, frames: List[bytes]) -> None:
        from znicz_tpu.parallel import wire

        _, payload = wire.split_envelope(frames)
        if not payload:
            payload = list(frames)
        try:
            skel = wire.peek_message(payload)
        except wire.WireError:
            # a reply corrupted between replica and balancer: the
            # failover timer recovers the request; nothing to attribute
            self._m["replica_bad_frames"].inc()
            return
        self.codec.count_message_in(payload)
        rid = skel.get("req_id")
        with self._lock:
            if rid in self._ctrl:
                self._ctrl.pop(rid)["on_reply"](skel)
                return
            if skel.get("bad_frame") and rid is None:
                # the replica could not decode our forwarded frames
                # (corrupted in flight): unattributable — the failover
                # timer re-ships the same bytes
                self._m["replica_bad_frames"].inc()
                return
            if rid in self._probes:
                self._finish_probe(self._probes.pop(rid), skel, payload)
                return
            entry = self._inflight.get(rid)
            if entry is None:
                self._m["dup_replies_dropped"].inc()
                return
            ok = bool(skel.get("ok"))
            policy = skel.get("policy")
            scope = skel.get("scope", "service")
            retryable = ((not ok and policy == "shed"
                          and scope == "service")
                         or bool(skel.get("bad_frame")))
            if retryable and entry.tries < int(
                    self.knobs["failover_tries"]) \
                    and time.perf_counter() < entry.deadline:
                # a service-scoped shed (or a corrupted-arrival bad
                # frame WITH our rid) from one replica is not the
                # fleet's answer: same bytes, different replica
                self._m["sheds_retried"].inc()
                replica = str(skel.get("replica_id") or "")
                self._inflight.pop(rid)
                self._release(entry)
                if not self._dispatch(entry, exclude={replica}):
                    self._parked.append(entry)
                return
            self._forward_reply(entry, skel, payload)

    def _forward_reply(self, entry: _Entry, skel: Dict,
                       payload: List[bytes]) -> None:
        """First reply wins: restamp the client's req_id back on,
        forward the tensor frames untouched, retire the entry (lock
        held)."""
        with self._lock:
            from znicz_tpu.parallel import wire

            self._inflight.pop(entry.rid, None)
            self._release(entry)
            ok = bool(skel.get("ok"))
            out = wire.restamp_message(payload, req_id=entry.client_rid,
                                       lb=True)
            self._send_front(entry.envelope, out)
            self._m["replied" if ok else "refused"].inc()
            if self._tracer.enabled and entry.trace_id:
                # the balancer's hop in the stitched fleet timeline
                self._tracer.add(
                    "balancer", "request", entry.t_accept,
                    time.perf_counter() - entry.t_accept,
                    {"trace_id": entry.trace_id,
                     "req_id": entry.client_rid,
                     "replica": str(skel.get("replica_id") or ""),
                     "tries": entry.tries})
            if skel.get("spans") and skel.get("origin"):
                # generation finals carry the replica's span summary —
                # stitch it NOW (covers the pre-first-heartbeat window)
                telemetry.fleet_trace().ingest(str(skel["origin"]),
                                               skel["spans"])
            if entry.hedge_target is not None \
                    and str(skel.get("replica_id") or "") \
                    == entry.hedge_target:
                self._m["hedge_wins"].inc()
            if entry.t_sent is not None and ok:
                lat = time.perf_counter() - entry.t_accept
                self._lat.append(lat)
                if len(self._lat) > 512:
                    del self._lat[:256]
                roll = self._rollover
                if roll is not None and roll["phase"] == "canary":
                    replica = str(skel.get("replica_id") or "")
                    if replica in roll["canary"]:
                        roll["lat_new"].append(lat)
                    elif replica in roll["old"]:
                        roll["lat_old"].append(lat)
            # parity: the primary's answer half, buffered until (unless)
            # the probe's half lands
            if entry.probe_rid is not None \
                    and entry.probe_rid in self._parity_buf:
                buf = self._parity_buf[entry.probe_rid]
                buf["primary"] = (self._tensor_bytes(payload), ok)
                self._compare_parity(entry.probe_rid)

    def _finish_probe(self, probe: _Entry, skel: Dict,
                      payload: List[bytes]) -> None:
        self._release(probe)
        buf = self._parity_buf.get(probe.rid)
        if buf is None:
            return
        buf["probe"] = (self._tensor_bytes(payload),
                        bool(skel.get("ok")))
        self._compare_parity(probe.rid)

    def _compare_parity(self, probe_rid: int) -> None:
        buf = self._parity_buf.get(probe_rid)
        if buf is None or "primary" not in buf or "probe" not in buf:
            return
        del self._parity_buf[probe_rid]
        (primary_bytes, primary_ok) = buf["primary"]
        (probe_bytes, probe_ok) = buf["probe"]
        if not (primary_ok and probe_ok):
            return                          # refusals prove nothing
        self._m["parity_checks"].inc()
        roll = self._rollover
        if roll is not None:
            roll["checks"] += 1
        if primary_bytes != probe_bytes:
            self._m["parity_mismatches"].inc()
            if roll is not None:
                roll["mismatches"] += 1

    # -- timers ----------------------------------------------------------------

    def _hedge_delay(self) -> float:
        """Telemetry-derived hedge delay: ``hedge_p99_mult`` x the
        balancer's own observed reply p99, clamped to
        ``[hedge_floor_s, hedge_cap_s]`` (the floor carries the cold
        start)."""
        lo = float(self.knobs["hedge_floor_s"])
        hi = float(self.knobs["hedge_cap_s"])
        if len(self._lat) < 20:
            return lo
        p99 = float(np.percentile(np.asarray(self._lat[-256:]), 99))
        return min(max(p99 * float(self.knobs["hedge_p99_mult"]), lo),
                   hi)

    def _tick_membership(self) -> None:
        """TTL eviction + immediate failover of the dead replica's
        in-flight entries (lock held)."""
        with self._lock:
            now = time.perf_counter()
            ttl = float(self.knobs["replica_ttl_s"])
            # a control command whose replica died before answering
            # would otherwise sit in _ctrl forever (small, but forever)
            for crid in [crid for crid, c in self._ctrl.items()
                         if now - c["t"] > 10 * ttl]:
                del self._ctrl[crid]
            dead = [rid for rid, m in self._members.items()
                    if now - m["last_seen"] > ttl]
            for rid in dead:
                self._m["replicas_lost"].inc()
                self._evict_member(rid, f"no heartbeat for >{ttl}s")

    def _evict_member(self, rid: str, why: str) -> None:
        """Drop one member from the fleet NOW (lock held): fail over
        its in-flight entries, clear its heal state, drop a parity
        probe it was answering, prune its data socket when no other
        member shares the endpoint.  Shared by TTL eviction and the
        autoscaler's retire path — a deliberately retired replica must
        not linger as phantom servable capacity until its TTL.  The
        RLock re-enter costs nothing from the already-locked callers
        and keeps the method safe to call bare (same idiom as
        :meth:`_failover`)."""
        with self._lock:
            if self._members.pop(rid, None) is None:
                return
            self._healing.pop(rid, None)
            self._drop_unused_data_socks(
                {m["endpoint"] for m in self._members.values()})
            self.log.warning("replica %s evicted (%s); failing over "
                             "its in-flight requests", rid, why)
            telemetry.emit("replica_lost", "serving", replica=rid,
                           why=why, members=len(self._members))
            for entry in list(self._inflight.values()):
                if entry.targets and entry.targets[-1] == rid:
                    self._failover(entry, exclude={rid})
            for probe in list(self._probes.values()):
                if probe.targets and probe.targets[-1] == rid:
                    self._probes.pop(probe.rid)
                    self._release(probe)
                    self._parity_buf.pop(probe.rid, None)

    def _failover(self, entry: _Entry, exclude=()) -> None:
        """Re-dispatch the SAME bytes to another replica, or refuse
        readably once the try budget is spent (lock held)."""
        with self._lock:
            self._inflight.pop(entry.rid, None)
            self._release(entry)
            if entry.tries >= int(self.knobs["failover_tries"]):
                self._refuse_client(
                    entry, "failover",
                    f"request failed over {entry.tries} times "
                    f"(replicas tried: {entry.targets}) — giving up")
                return
            self._m["failovers"].inc()
            telemetry.emit("failover", "serving",
                           req_id=entry.client_rid, tries=entry.tries,
                           targets=list(entry.targets))
            # exclude EVERY replica already tried (primary, hedge,
            # earlier failovers) — the try budget exists to spread
            # across the fleet; parking is the fallback when nobody
            # untried is ready
            if not self._dispatch(entry, exclude=set(exclude)
                                  | set(entry.targets)):
                self._parked.append(entry)

    def _tick_inflight(self) -> None:
        """Deadlines, failover timeouts, hedges, parked dispatch (lock
        held)."""
        with self._lock:
            now = time.perf_counter()
            failover_after = float(self.knobs["failover_timeout_s"])
            hedge_after = self._hedge_delay() if self.knobs["hedge"] else None
            for entry in list(self._inflight.values()):
                if now > entry.deadline:
                    self._inflight.pop(entry.rid, None)
                    self._release(entry)
                    self._refuse_client(
                        entry, "deadline",
                        "deadline budget spent awaiting the fleet "
                        f"(replicas tried: {entry.targets})")
                    continue
                if entry.t_sent is None:
                    continue
                waited = now - entry.t_sent
                if waited > failover_after:
                    self._failover(entry)
                    continue
                if (hedge_after is not None and not entry.hedged
                        and waited > hedge_after):
                    pool = self._candidates(exclude=set(entry.targets))
                    if pool:
                        target = pool[0]
                        if self._send_to(target, entry.frames):
                            entry.targets.append(target)
                            entry.hedged = True
                            entry.hedge_target = target
                            entry.tries += 1
                            self._dispatch_counts[target] = \
                                self._dispatch_counts.get(target, 0) + 1
                            entry.held.add(target)
                            self._m["hedges"].inc()
            for probe in list(self._probes.values()):
                if now > probe.deadline:
                    self._probes.pop(probe.rid, None)
                    self._release(probe)
                    self._parity_buf.pop(probe.rid, None)
            if self._parked:
                parked, self._parked = self._parked, []
                for entry in parked:
                    if now > entry.deadline:
                        self._refuse_client(
                            entry, "deadline",
                            "deadline budget spent parked — no replica "
                            "became ready in time")
                        continue
                    if not self._dispatch(entry):
                        self._parked.append(entry)

    # -- autoscaler (ISSUE 17) -------------------------------------------------

    def enable_autoscale(self, spawn, retire, **overrides) -> None:
        """Arm the autoscaler: ``spawn()`` must start ONE new replica
        process announcing to this balancer (the ``--serve --announce``
        launcher path); ``retire(replica_id)`` must terminate one.
        Both are invoked OUTSIDE the balancer lock — they may block on
        process startup/teardown.  ``overrides`` land on the
        ``autoscale_*`` knobs (tests/bench use fast cadences)."""
        with self._lock:
            self.knobs.update(overrides)
            self.knobs["autoscale"] = True
            self._scaler = {"spawn": spawn, "retire": retire}
            self._scale_known = set(self._members)

    def _servable_ids(self) -> List[str]:
        """Members that carry REAL capacity right now (lock held):
        ready, in rotation, not draining toward retirement, and NOT
        mid-heal.  The heal exclusion is the ISSUE 17 satellite bugfix:
        a replica inside its ``heal_backoff_s`` window is serving stale
        params and about to swap — counting it as capacity let the
        scale-down decision retire the last HEALTHY replica while the
        heal was still in flight (regression test in
        tests/test_balancer.py)."""
        rotation_out = self._rotation_out()
        return [rid for rid, m in self._members.items()
                if m["ready"] and rid not in rotation_out
                and rid not in self._retiring
                and rid not in self._healing]

    def _tick_autoscale(self) -> None:
        """One autoscaler evaluation (serve tick cadence): reconcile
        pending spawns with announcements, finish drains, and hold the
        fleet inside the load band with hysteresis — scale-up after
        ``autoscale_up_after`` consecutive high evals (parked requests
        count as high: demand the fleet cannot even queue), drain-then-
        retire after ``autoscale_down_after`` low evals, never below
        the ``min_replicas`` quorum, one action per cooldown.
        Decisions are computed under the lock; spawn/retire callbacks
        run AFTER it is released."""
        actions = []
        with self._lock:
            if self._scaler is None or not bool(self.knobs["autoscale"]):
                return
            now = time.perf_counter()
            # 1. reconcile: a newly announced member consumes the
            # oldest pending spawn; spawns past the boot deadline are
            # forgotten (the process died before announcing — capacity
            # accounting must not wedge on it)
            fresh = set(self._members) - self._scale_known
            for _ in fresh:
                if self._scale_pending:
                    self._scale_pending.pop(0)
            self._scale_known = set(self._members)
            boot_deadline = float(self.knobs["autoscale_boot_deadline_s"])
            late = [t for t in self._scale_pending
                    if now - t > boot_deadline]
            if late:
                self._scale_pending = [t for t in self._scale_pending
                                       if now - t <= boot_deadline]
                self.log.warning(
                    "autoscale: %d spawned replica(s) never announced "
                    "within %gs — abandoning the reservation(s)",
                    len(late), boot_deadline)
            # 2. finish drains: a retiring replica is killed once its
            # in-flight work is gone (or the drain timeout spends —
            # the failover ledger recovers whatever was left)
            drain_timeout = float(self.knobs["autoscale_drain_timeout_s"])
            for rid, t0 in list(self._retiring.items()):
                m = self._members.get(rid)
                drained = m is None or (
                    self._dispatch_counts.get(rid, 0) == 0
                    and m["queue_depth"] == 0)
                if not drained and now - t0 > drain_timeout:
                    self._m["scale_drain_timeouts"].inc()
                    self.log.warning(
                        "autoscale: %s drain exceeded %gs — retiring "
                        "anyway (in-flight work fails over)", rid,
                        drain_timeout)
                    drained = True
                if drained:
                    del self._retiring[rid]
                    self._m["scale_downs"].inc()
                    self.log.info("autoscale: retiring %s", rid)
                    actions.append(("retire", rid))
                    # evict NOW, not at TTL: a retired corpse that
                    # lingers as "ready" would count as servable
                    # capacity and let the band retire healthy
                    # replicas right past the quorum
                    self._evict_member(rid, "autoscale retire")
            # 3. band evaluation at its own (slower) cadence
            if now - self._scale_last["eval"] \
                    >= float(self.knobs["autoscale_eval_s"]):
                self._scale_last["eval"] = now
                servable = self._servable_ids()
                if servable:
                    load = sum(
                        (self._members[r]["queue_depth"]
                         + self._dispatch_counts.get(r, 0))
                        / self._members[r].get("device_count", 1)
                        for r in servable) / len(servable)
                else:
                    # zero servable capacity with work waiting is the
                    # hardest possible "high"
                    load = float("inf") if (self._parked
                                            or self._inflight) else 0.0
                high = bool(self._parked) \
                    or load > float(self.knobs["autoscale_high_load"])
                low = not self._parked and not high \
                    and load < float(self.knobs["autoscale_low_load"])
                self._scale_streak["high"] = \
                    self._scale_streak["high"] + 1 if high else 0
                self._scale_streak["low"] = \
                    self._scale_streak["low"] + 1 if low else 0
                cooling = now - self._scale_last["action"] \
                    < float(self.knobs["autoscale_cooldown_s"])
                total = len(self._members) + len(self._scale_pending)
                if (self._scale_streak["high"]
                        >= int(self.knobs["autoscale_up_after"])
                        and not cooling
                        and total < int(self.knobs["autoscale_max"])):
                    self._scale_pending.append(now)
                    self._scale_last["action"] = now
                    self._scale_streak["high"] = 0
                    self._m["scale_ups"].inc()
                    self.log.info(
                        "autoscale: scale-up (load %.2f, %d parked, "
                        "%d members, %d pending)", load,
                        len(self._parked), len(self._members),
                        len(self._scale_pending))
                    telemetry.emit(
                        "autoscale_up", "serving",
                        load=round(load, 3) if np.isfinite(load)
                        else "inf",
                        parked=len(self._parked),
                        members=len(self._members),
                        pending=len(self._scale_pending))
                    actions.append(("spawn", None))
                elif (self._scale_streak["low"]
                        >= int(self.knobs["autoscale_down_after"])
                        and not cooling
                        and not self._scale_pending
                        and not self._retiring
                        and len(servable) - 1 >= self.min_replicas):
                    # scale-down only ABOVE quorum, and only from the
                    # SERVABLE set (never a healing/retiring replica's
                    # phantom capacity); drain first — _candidates
                    # stops routing to it this instant
                    victim = min(servable, key=lambda r: (
                        self._members[r]["queue_depth"]
                        + self._dispatch_counts.get(r, 0)))
                    self._retiring[victim] = now
                    self._scale_last["action"] = now
                    self._scale_streak["low"] = 0
                    self.log.info(
                        "autoscale: scale-down — draining %s "
                        "(load %.2f, %d servable)", victim, load,
                        len(servable))
                    telemetry.emit(
                        "autoscale_down", "serving", victim=victim,
                        load=round(load, 3), servable=len(servable))
        for kind, arg in actions:
            # unlocked on purpose: process spawn/terminate may block,
            # and the serve loop's ledger must keep ticking meanwhile
            try:
                if kind == "spawn":
                    self._scaler["spawn"]()
                else:
                    self._scaler["retire"](arg)
            except Exception:
                self.log.exception("autoscale: %s callback failed "
                                   "(%s)", kind, arg)

        # -- fleet-coordinated canary rollover -------------------------------------

    def _send_ctrl(self, replica_id: str, msg: Dict,
                   on_reply=None) -> None:
        """One control command (swap/rollback) to one replica over its
        data socket, tracked outside the infer ledger (lock held)."""
        self._rid += 1
        msg = dict(msg, req_id=self._rid)
        self._ctrl[self._rid] = {
            "replica_id": replica_id, "cmd": msg["cmd"],
            "t": time.perf_counter(),
            "on_reply": on_reply or (lambda skel: None)}
        frames = self.codec.encode(msg)
        self._send_to(replica_id, frames)

    def _handle_swap(self, envelope: List[bytes], skel: Dict) -> None:
        path = skel.get("path")
        rid = skel.get("req_id")
        parity = bool(skel.get("parity", True))
        with self._lock:
            if not isinstance(path, str) or not path:
                self._send_front(envelope, self.codec.encode(
                    {"ok": False, "req_id": rid, "lb": True,
                     "error": "swap needs a snapshot 'path'"}))
                return
            if self._rollover is not None:
                self._send_front(envelope, self.codec.encode(
                    {"ok": False, "req_id": rid, "lb": True,
                     "error": "rollover already in progress "
                              f"(phase {self._rollover['phase']})"}))
                return
            ready = [r for r, m in self._members.items() if m["ready"]]
            if not ready:
                self._send_front(envelope, self.codec.encode(
                    {"ok": False, "req_id": rid, "lb": True,
                     "error": "no ready replicas to roll over"}))
                return
            # the wave is keyed on SNAPSHOT PATHS, never on predicted
            # generation numbers: per-replica gen counters are hwm-
            # allocated (a rollback-then-retry or a restart-then-heal
            # legitimately desynchronizes them), and a balancer that
            # predicts gens wedges the moment they drift.  Paths are
            # the invariant healing maintains.
            paths = {self._members[r]["snapshot_path"] for r in ready}
            if len(paths) != 1:
                self._send_front(envelope, self.codec.encode(
                    {"ok": False, "req_id": rid, "lb": True,
                     "error": f"fleet snapshot paths not uniform "
                              f"({sorted(paths)}) — healing in "
                              f"progress; retry shortly"}))
                return
            old_path = paths.pop()
            if path == old_path:
                self._send_front(envelope, self.codec.encode(
                    {"ok": False, "req_id": rid, "lb": True,
                     "error": f"fleet already serves snapshot "
                              f"{path!r}"}))
                return
            old_gen = max(self._members[r]["gen"] for r in ready)
            n_canary = max(1, int(round(
                float(self.knobs["canary_fraction"]) * len(ready))))
            n_canary = min(n_canary, len(ready))
            canary = sorted(ready)[:n_canary]
            self._rollover = {
                "path": path, "parity": parity,
                "phase": "warm_canary",
                "canary": canary, "old": [r for r in sorted(ready)
                                          if r not in canary],
                # gens are INFORMATIONAL (history/panel); new_gen is
                # read off the first warmed canary's heartbeat
                "old_gen": old_gen, "new_gen": None,
                "old_path": old_path,
                "t_start": time.perf_counter(),
                "t_canary": None,
                "t_phase": time.perf_counter(),
                "warming": set(),           # out-of-rotation right now
                "sent": set(),              # swap/rollback cmd sent
                "done": set(),              # confirmed flipped
                "errors": [],               # (replica, refusal reason)
                "checks": 0,                # parity probes compared
                "lat_old": [], "lat_new": [],
                "old_dispatches": 0, "mismatches": 0,
                "steer": 0,
                "stride": max(1, int(round(len(ready) / n_canary))),
            }
            self.log.info("rollover to %r started: canary %s (of %d "
                          "ready), parity %s", path, canary,
                          len(ready), parity)
            telemetry.emit("swap_begin", "serving", path=path,
                           canary=list(canary), ready=len(ready))
            self._send_front(envelope, self.codec.encode(
                {"ok": True, "swap_started": True, "req_id": rid,
                 "lb": True, "canary": canary, "generation": old_gen}))

    def _warm_one(self, roll: Dict, replica_id: str, cmd: Dict) -> bool:
        """Drive one replica through an off-rotation swap/rollback;
        True once its heartbeat confirms the flip.  Confirmation is
        keyed on the SNAPSHOT PATH the heartbeat reports (the invariant
        healing maintains), never on a predicted generation number —
        per-replica gen counters are hwm-allocated and legitimately
        drift across rollback-retry/restart-heal cycles.  A refused
        command (broken snapshot, nothing retained) lands in
        roll["errors"] for the phase driver to act on (lock held)."""
        if replica_id in roll["done"]:
            return True
        m = self._members.get(replica_id)
        if m is None:
            return False                    # died mid-warm: caller acts
        if replica_id not in roll["sent"]:
            roll["warming"].add(replica_id)
            roll["sent"].add(replica_id)

            def on_reply(skel, _rid=replica_id):
                # runs under the serve thread's lock (reply handler)
                r = self._rollover
                if r is roll and not skel.get("ok"):
                    r["errors"].append((_rid,
                                        str(skel.get("error"))))
            self._send_ctrl(replica_id, cmd, on_reply=on_reply)
            return False
        want = roll["path"] if cmd["cmd"] == "swap" else roll["old_path"]
        if m["snapshot_path"] == want and m["ready"] \
                and not m["swapping"]:
            if cmd["cmd"] == "swap" and roll["new_gen"] is None:
                roll["new_gen"] = m["gen"]  # observed, not predicted
            roll["warming"].discard(replica_id)
            roll["done"].add(replica_id)
            return True
        return False

    def _finish_rollover(self, result: str, reason: str) -> None:
        """Record the wave (the losing side's counters preserved) and
        clear the state machine (lock held)."""
        roll = self._rollover
        self._rollover = None
        record = {
            "result": result, "reason": reason, "path": roll["path"],
            "old_gen": roll["old_gen"], "new_gen": roll["new_gen"],
            "canary": roll["canary"],
            "parity_mismatches": roll["mismatches"],
            "canary_samples": len(roll["lat_new"]),
            "old_samples": len(roll["lat_old"]),
            "canary_p99_ms": None, "old_p99_ms": None,
            "elapsed_s": round(time.perf_counter() - roll["t_start"], 3),
        }
        if roll["lat_new"]:
            record["canary_p99_ms"] = round(float(np.percentile(
                np.asarray(roll["lat_new"]), 99)) * 1e3, 3)
        if roll["lat_old"]:
            record["old_p99_ms"] = round(float(np.percentile(
                np.asarray(roll["lat_old"]), 99)) * 1e3, 3)
        self.rollover_history.append(record)
        if result == "promoted":
            self._fleet_path = roll["path"]
            self._m["rollovers"].inc()
            telemetry.emit("swap_done", "serving", path=roll["path"],
                           new_gen=roll["new_gen"],
                           elapsed_s=record["elapsed_s"])
        elif result == "rolled_back":
            # the fleet's intended path is the PRE-wave one: pinning it
            # arms the heal loop against rollback stragglers too
            self._fleet_path = roll["old_path"]
            self._m["rollbacks"].inc()
            telemetry.emit("rollback", "serving", path=roll["path"],
                           reason=reason,
                           elapsed_s=record["elapsed_s"])
        self.log.warning("rollover to %r %s: %s", roll["path"], result,
                         reason)

    def _enter_phase(self, roll: Dict, phase: str) -> None:
        """Phase transition: fresh sent/warming/done sets + the phase
        timer every timeout below is held against (lock held)."""
        roll["phase"] = phase
        roll["sent"], roll["warming"] = set(), set()
        roll["done"] = set()
        roll["t_phase"] = time.perf_counter()
        telemetry.emit("swap_phase", "serving", phase=phase,
                       path=roll["path"])

    def _abort_to_rollback(self, roll: Dict, reason: str) -> None:
        """Warm-phase abort: whatever already flipped rolls back, then
        the wave finishes rolled_back (lock held)."""
        flipped = list(roll["done"])
        telemetry.emit("rollback", "serving", path=roll["path"],
                       reason=reason, flipped=len(flipped))
        roll["reason"] = reason
        roll["canary"] = flipped            # only these need undoing
        if not flipped:
            self._finish_rollover("rolled_back", reason)
            return
        self._enter_phase(roll, "rollback")

    def _tick_rollover(self) -> None:
        """Advance the canary state machine one step (lock held).
        Every phase is timeout-bounded (``canary_timeout_s`` against
        ``t_phase``): a replica that silently never warms, a refused
        control command, or a stuck rollback must never wedge the wave
        machinery forever — the one unrecoverable state a fleet
        balancer may not have."""
        roll = self._rollover
        if roll is None:
            return
        timeout = float(self.knobs["canary_timeout_s"])
        stuck = time.perf_counter() - roll["t_phase"] > timeout
        if roll["phase"] == "warm_canary":
            done = [r for r in roll["canary"]
                    if self._warm_one(roll, r,
                                      {"cmd": "swap",
                                       "path": roll["path"]})]
            lost = [r for r in roll["canary"] if r not in self._members]
            if lost or roll["errors"] or stuck:
                # a canary died, refused the swap (broken snapshot), or
                # never confirmed: survivors that flipped roll back;
                # nothing was promoted
                reason = (f"canary {lost} died while warming" if lost
                          else f"swap refused: {roll['errors']}"
                          if roll["errors"]
                          else f"canary warm timed out after "
                               f"{timeout:g}s")
                self._abort_to_rollback(roll, reason)
                return
            if len(done) == len(roll["canary"]):
                self._enter_phase(roll, "canary")
                roll["t_canary"] = time.perf_counter()
            return
        if roll["phase"] == "canary":
            verdict = self._canary_verdict(roll)
            if verdict is None:
                return
            ok, reason = verdict
            if not ok:
                roll["reason"] = reason
                self._enter_phase(roll, "rollback")
                return
            self._enter_phase(roll, "promote")
            roll["queue"] = [r for r in roll["old"]
                             if r in self._members]
            return
        if roll["phase"] == "promote":
            # one replica at a time, each warmed off-rotation, so the
            # fleet never dips below quorum mid-wave.  A replica that
            # dies, refuses, or times out mid-promote is SKIPPED — the
            # wave still promotes, and post-promote healing (which
            # targets the new fleet path) keeps retrying it with
            # backoff and a visible counter
            roll["queue"] = [r for r in roll["queue"]
                            if r in self._members]
            skip = {r for r, _ in roll["errors"]}
            if skip:
                roll["queue"] = [r for r in roll["queue"]
                                 if r not in skip]
                for r in skip:
                    roll["warming"].discard(r)
                self.log.warning("promote: skipping %s (refused: %s) — "
                                 "healing will retry", sorted(skip),
                                 roll["errors"])
                roll["errors"] = []
            if not roll["queue"]:
                self._finish_rollover("promoted", "canary verdict clean")
                return
            head = roll["queue"][0]
            if self._warm_one(roll, head, {"cmd": "swap",
                                           "path": roll["path"]}):
                roll["queue"].pop(0)
                roll["t_phase"] = time.perf_counter()  # per-replica
            elif stuck:
                roll["warming"].discard(head)
                roll["queue"].pop(0)
                roll["t_phase"] = time.perf_counter()
                self.log.warning("promote: %s never confirmed within "
                                 "%gs — skipped; healing will retry",
                                 head, timeout)
            return
        if roll["phase"] == "rollback":
            done = [r for r in roll["canary"]
                    if r not in self._members
                    or self._warm_one(roll, r, {"cmd": "rollback"})]
            if len(done) == len(roll["canary"]) or stuck:
                stragglers = [r for r in roll["canary"] if r not in done]
                reason = roll.get("reason", "regression")
                if stragglers:
                    # force-finish: a straggler still on the new path
                    # disagrees with the (unchanged) fleet path, so the
                    # heal loop re-swaps it back — self-correcting
                    reason += (f" (rollback stragglers {stragglers} "
                               f"left to healing)")
                self._finish_rollover("rolled_back", reason)
            return

    def _canary_verdict(self, roll: Dict) -> Optional[Tuple[bool, str]]:
        """(ok, reason) once the canary has enough evidence; None to
        keep watching (lock held)."""
        if roll["parity"] and roll["mismatches"] > 0:
            return False, (f"reply parity broken: "
                           f"{roll['mismatches']} mismatching "
                           f"shadow probes")
        lost = [r for r in roll["canary"] if r not in self._members]
        if lost:
            return False, f"canary {lost} died while serving"
        need = int(self.knobs["canary_requests"])
        have_old = bool(roll["old"])        # an all-canary fleet (one
        # replica, or canary_fraction ~1) has no old pool: the p99
        # comparison is vacuous and parity/health alone judge the wave
        if len(roll["lat_new"]) >= need and (
                not have_old or len(roll["lat_old"]) >= 1):
            if roll["lat_old"]:
                p99_new = float(np.percentile(
                    np.asarray(roll["lat_new"]), 99))
                p99_old = float(np.percentile(
                    np.asarray(roll["lat_old"]), 99))
                mult = float(self.knobs["canary_p99_mult"])
                if p99_new > p99_old * mult:
                    return False, (f"canary p99 {p99_new * 1e3:.1f}ms "
                                   f"> {mult}x old "
                                   f"{p99_old * 1e3:.1f}ms")
            if roll["parity"] and have_old and roll["checks"] == 0:
                return None     # promote only after >=1 parity probe
                # completed (canary_timeout_s is the backstop; with no
                # old pool there is nothing to probe against)
            return True, "clean"
        if time.perf_counter() - roll["t_canary"] > float(
                self.knobs["canary_timeout_s"]):
            # starvation is NOT evidence of health: conservative
            return False, (f"canary starved: only "
                           f"{len(roll['lat_new'])} samples inside "
                           f"{self.knobs['canary_timeout_s']}s")
        return None


for _name, _help in ReplicaBalancer.COUNTERS.items():
    setattr(ReplicaBalancer, _name, registered_property(_name, _help))
del _name, _help
