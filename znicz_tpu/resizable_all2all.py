"""ResizableAll2All (rebuild of ``znicz/resizable_all2all.py``): a fully
connected layer whose output width can grow (or shrink) mid-training —
new rows are freshly initialized, surviving rows keep their trained values.
The reference used this for progressively-widened nets."""

from __future__ import annotations

import numpy as np

from znicz_tpu.all2all import All2All
from znicz_tpu.core import prng


class ResizableAll2All(All2All):
    def resize(self, new_width: int) -> None:
        """Change output width in place; keeps trained rows, initializes new
        ones from the unit's seeded stream.  Invalidates the jit cache (the
        shapes changed) and the paired GD unit's velocity buffers."""
        new_width = int(new_width)
        old = self.weights.map_read()
        out_old, in_size = old.shape if not self.weights_transposed \
            else (old.shape[1], old.shape[0])
        if new_width == out_old:
            return
        w = np.zeros((new_width, in_size), np.float32)
        keep = min(out_old, new_width)
        w[:keep] = old[:keep] if not self.weights_transposed \
            else old[:, :keep].T
        if new_width > out_old:
            stddev = self.weights_stddev or 1.0 / np.sqrt(in_size)
            grow = np.zeros((new_width - out_old, in_size), np.float32)
            self._fill(grow, self.weights_filling, stddev)
            w[out_old:] = grow
        self.weights.mem = np.ascontiguousarray(
            w.T) if self.weights_transposed else w
        if self.include_bias:
            b_old = self.bias.map_read()
            b = np.zeros(new_width, np.float32)
            b[:keep] = b_old[:keep]
            self.bias.mem = b
        self.output_sample_shape = (new_width,)
        self.output_samples_number = new_width
        if self.input is not None and self.input.mem is not None:
            self.create_output()
        self._compiled = None               # shapes changed -> recompile
        # reallocate any paired GD unit's velocity buffers (momentum state
        # for vanished/new rows is meaningless -> zeros) + its jit cache
        if self.workflow is not None:
            from znicz_tpu.nn_units import GradientDescentBase

            from znicz_tpu.nn_units import _state_dtype

            for unit in self.workflow:
                if (isinstance(unit, GradientDescentBase)
                        and unit.forward is self and unit._velocities):
                    for k, arr in self.params().items():
                        unit._velocities[k].mem = np.zeros(
                            arr.shape, _state_dtype())
                    unit._compiled = None
