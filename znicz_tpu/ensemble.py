"""Ensemble training/evaluation (rebuild of ``veles/ensemble/``).

The reference trained N instances of a workflow with different seeds and
combined their predictions.  Rebuild:

  - ``EnsembleTrainer(factory, n_models)`` — runs the factory N times with
    distinct seeds, collecting each run's best metric and final params;
  - ``EnsembleEvaluator`` — averages member softmax outputs (soft voting)
    for a batch and reports combined n_err.
"""

from __future__ import annotations

from typing import Callable, List

import numpy as np

from znicz_tpu.core import prng


class EnsembleTrainer:
    """factory(seed) -> trained workflow with .decision and .forwards."""

    def __init__(self, factory: Callable[[int], object], n_models: int = 3,
                 base_seed: int = 1013):
        self.factory = factory
        self.n_models = int(n_models)
        self.base_seed = int(base_seed)
        self.members: List[object] = []
        self.metrics: List[float] = []

    def run(self):
        for i in range(self.n_models):
            seed = self.base_seed + 1000 * i
            prng.reset(seed)
            wf = self.factory(seed)
            self.members.append(wf)
            self.metrics.append(float(wf.decision.best_metric))
        return self


class EnsembleEvaluator:
    """Soft-voting over member workflows' forward stacks.  Inference is a
    PURE composition of each forward's ``apply`` (eval-mode branches for
    dropout / stochastic pooling) — member workflows are never mutated."""

    def __init__(self, members: List[object]):
        self.members = list(members)

    @staticmethod
    def pure_forward(forwards, x):
        import jax.numpy as jnp

        from znicz_tpu.dropout import DropoutForward
        from znicz_tpu.misc_units import MeanDispNormalizerUnit
        from znicz_tpu.pooling import StochasticPoolingBase

        h = jnp.asarray(x, jnp.float32)
        for f in forwards:
            if isinstance(f, DropoutForward):
                continue                           # eval: identity
            if isinstance(f, StochasticPoolingBase):
                h, _ = f._select_expected(f.windows(h))
                continue
            if isinstance(f, MeanDispNormalizerUnit):
                h = f._normalize(f.mean.devmem, f.disp.devmem, h)
                continue
            params = {k: a.devmem for k, a in f.params().items()}
            h = f.apply(params, h)
        return h

    def predict_proba(self, data: np.ndarray) -> np.ndarray:
        probs = [np.array(self.pure_forward(wf.forwards, data))
                 for wf in self.members]
        return np.mean(probs, axis=0)

    def n_err(self, data: np.ndarray, labels: np.ndarray) -> int:
        pred = self.predict_proba(data).argmax(-1)
        return int((pred != np.asarray(labels)).sum())
