"""charlm sample: the repo's first SEQUENCE workload end-to-end
(ISSUE 15) — a small character language model built from the sequence
units (CharEmbedding -> causal MultiHeadAttention with residual ->
position-wise SeqAll2AllStrictRELU FFN -> SeqAll2AllSoftmax head),
trained with next-char softmax-CE per token.

    start -> repeater -> loader -> embed -> mha -> ffn -> head
                ^                                          |
                |                                     evaluator(seq)
                +-- gd_embed <- gd_mha <- gd_ffn <- gd_head <- decision

Everything rides the existing stack unchanged: the unit engine and the
FusedTrainer both differentiate the same pure applies (the fused tail's
seq epilogue + softmax-CE loss head engage under
``root.common.engine.fused_tail``), snapshots flow through the
snapshotter's standard collect/restore (so ``--serve --snapshot`` loads
a charlm checkpoint like any other), master/slave distribution ships
the param deltas as plain tensors over wire v3, and serving pads
variable-length requests onto the 2-D (batch x seq) bucket ladder —
importing this module declares its serving shape
(``root.common.serving.seq.max_len`` defaults to the trained
``seq_len``).

Data: a deterministic, seeded synthetic corpus (no downloads): a cyclic
alphabet walk whose STRIDE is announced by the first character of each
line — the next char is predictable only from context several positions
back, so the attention layer is load-bearing (an embedding+head model
plateaus; with attention the token error collapses).  Ids reserve 0 as
the serving PAD; real chars live in 1..vocab-1.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu.attention import (CharEmbedding, GDCharEmbedding,
                                 GDMultiHeadAttention, GDSeqAll2All,
                                 GDSeqSoftmax, MultiHeadAttention,
                                 SeqAll2AllSoftmax, SeqAll2AllStrictRELU)
from znicz_tpu.core.config import root
from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.decision import DecisionGD
from znicz_tpu.evaluator import EvaluatorSeqSoftmax
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.snapshotter import Snapshotter

#: id 0 is the serving plane's padding id — never emitted by the corpus,
#: so a padded tail is distinguishable from every real token
PAD_ID = 0

root.charlm.defaults({
    "loader": {"minibatch_size": 32, "n_train": 384, "n_valid": 96,
               "n_test": 0, "seq_len": 64},
    "model": {"vocab": 32, "embed": 32, "heads": 2, "ffn": 64},
    "learning_rate": 0.5,
    "gradient_moment": 0.9,
    "weights_decay": 0.0,
    "decision": {"max_epochs": 8, "fail_iterations": 0},
    "snapshotter": {"prefix": "charlm", "interval": 0},
})


def make_corpus(n_chars: int, vocab: int, seed: int = 1013) -> np.ndarray:
    """The synthetic charlm stream as u8 ids in 1..vocab-1: lines of a
    cyclic alphabet walk, each line's stride set by its seeded first
    char — predicting a char needs the stride, i.e. CONTEXT, not just
    the previous char."""
    rng = np.random.default_rng(seed)
    span = vocab - 1                      # usable alphabet (0 = PAD)
    out = np.empty(n_chars + 1, np.uint8)
    i = 0
    while i < len(out):
        stride = int(rng.integers(1, 4))          # 1..3
        start = int(rng.integers(0, span))
        line = (start + stride * np.arange(16)) % span + 1
        take = min(len(line), len(out) - i)
        out[i:i + take] = line[:take]
        i += take
    return out


class CharLMLoader(FullBatchLoader):
    """Sliding windows over the synthetic stream: data[i] is ids[i:i+T]
    (u8 — the 1-byte wire/HBM form every u8 dataset keeps), labels[i]
    the next-char ids ids[i+1:i+T+1]."""

    def load_data(self):
        cfg = root.charlm.loader
        n_train = int(cfg.get("n_train", 384))
        n_valid = int(cfg.get("n_valid", 96))
        n_test = int(cfg.get("n_test", 0))
        seq_len = int(cfg.get("seq_len", 64))
        vocab = int(root.charlm.model.get("vocab", 32))
        total = n_train + n_valid + n_test
        stream = make_corpus(total + seq_len, vocab)
        idx = np.arange(total)[:, None] + np.arange(seq_len)[None]
        # order: [test | valid | train] to match class offsets
        self.original_data.mem = stream[idx].astype(np.uint8)
        self.original_labels.mem = stream[idx + 1].astype(np.uint8)
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()

    def create_minibatch_data(self):
        super().create_minibatch_data()
        # labels are per TOKEN (mb, T), not per sample (mb,)
        self.minibatch_labels.mem = np.zeros(
            (self.max_minibatch_size,) + tuple(self.original_labels.shape[1:]),
            self.original_labels.mem.dtype)


class CharLMWorkflow(Workflow):
    def __init__(self, **kwargs):
        super().__init__(name="CharLMWorkflow", **kwargs)
        cfg = root.charlm
        seq_len = int(cfg.loader.get("seq_len", 64))
        vocab = int(cfg.model.get("vocab", 32))
        embed = int(cfg.model.get("embed", 32))
        heads = int(cfg.model.get("heads", 2))
        ffn = int(cfg.model.get("ffn", 64))
        lr = float(cfg.get("learning_rate"))
        mom = float(cfg.get("gradient_moment"))
        wd = float(cfg.get("weights_decay"))
        # declare the serving plane's seq shape (frontend fallback when
        # root.common.serving.seq.max_len is unset): variable-length
        # requests bucket up to the trained window.  An attribute, not
        # a global config write — a fixed-shape service built later in
        # the same process must not inherit a seq axis.
        self.serving_seq_len = seq_len

        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = CharLMLoader(
            self, name="loader",
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        self.loader.link_from(self.repeater)

        self.forwards = []
        specs = [
            ("embed", CharEmbedding,
             dict(vocab=vocab, embed=embed, max_len=seq_len)),
            ("mha", MultiHeadAttention,
             dict(heads=heads, causal=True, residual=True)),
            ("ffn", SeqAll2AllStrictRELU,
             dict(output_sample_shape=(ffn,))),
            ("head", SeqAll2AllSoftmax,
             dict(output_sample_shape=(vocab,))),
        ]
        prev, prev_attr = self.loader, "minibatch_data"
        for name, cls, kw in specs:
            fwd = cls(self, name=name, **kw)
            fwd.link_from(prev if not self.forwards else self.forwards[-1])
            fwd.link_attrs(prev, ("input", prev_attr))
            self.forwards.append(fwd)
            prev, prev_attr = fwd, "output"

        self.evaluator = EvaluatorSeqSoftmax(self, name="evaluator",
                                             n_classes=vocab)
        self.evaluator.link_from(self.forwards[-1])
        self.evaluator.link_attrs(self.forwards[-1], "output")
        self.evaluator.link_attrs(self.loader,
                                  ("labels", "minibatch_labels"),
                                  ("batch_size", "minibatch_size"))

        self.decision = DecisionGD(
            self, name="decision",
            max_epochs=int(cfg.decision.get("max_epochs")),
            fail_iterations=int(cfg.decision.get("fail_iterations")))
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch", "class_ended",
            "epoch_number", "class_lengths", "minibatch_size")
        self.decision.link_attrs(
            self.evaluator, ("minibatch_loss", "loss"),
            ("minibatch_n_err", "n_err"), "confusion_matrix",
            "max_err_output_sum")

        self.snapshotter = Snapshotter(
            self, name="snapshotter",
            prefix=cfg.snapshotter.get("prefix"),
            interval=int(cfg.snapshotter.get("interval", 0)))
        self.snapshotter.link_from(self.decision)
        self.snapshotter.link_attrs(self.decision, "epoch_number")
        self.snapshotter.improved = self.decision.improved   # shared Bool
        self.snapshotter.gate_skip = ~self.decision.epoch_ended

        # backward chain, reverse order
        gd_specs = [
            ("gd_head", GDSeqSoftmax, 3, True),
            ("gd_ffn", GDSeqAll2All, 2, True),
            ("gd_mha", GDMultiHeadAttention, 1, True),
            ("gd_embed", GDCharEmbedding, 0, False),
        ]
        self.gds = []
        err_src, err_attr = self.evaluator, "err_output"
        for name, cls, i, need_err in gd_specs:
            gd = cls(self, name=name, forward=self.forwards[i],
                     learning_rate=lr, gradient_moment=mom,
                     weights_decay=wd, need_err_input=need_err)
            gd.link_from(self.snapshotter if not self.gds else self.gds[-1])
            gd.link_attrs(err_src, ("err_output", err_attr))
            gd.gate_skip = self.decision.gd_skip
            self.gds.append(gd)
            err_src, err_attr = gd, "err_input"

        self.repeater.link_from(self.gds[-1])
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def run(snapshot: str = "", device=None) -> CharLMWorkflow:
    wf = CharLMWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train
    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
