"""MnistAE sample: convolutional autoencoder — rebuild of the reference's
``znicz/samples/MnistAE`` workflow, BASELINE config[2].

Architecture (the reference's): ConvTanh encoder -> MaxPooling ->
Depooling (routed by the pooling's recorded offsets) -> Deconv decoder with
weights *tied* to the encoder conv, trained by GDDeconv against
EvaluatorMSE(target = input image), DecisionMSE control.
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.conv import ConvTanh
from znicz_tpu.core.config import root
from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.decision import DecisionMSE
from znicz_tpu.deconv import Deconv
from znicz_tpu.depooling import Depooling, GDDepooling
from znicz_tpu.evaluator import EvaluatorMSE
from znicz_tpu.gd_conv import GDTanhConv
from znicz_tpu.gd_deconv import GDDeconv
from znicz_tpu.gd_pooling import GDMaxPooling
from znicz_tpu.loader.fullbatch import FullBatchLoaderMSE
from znicz_tpu.pooling import MaxPooling
from znicz_tpu.snapshotter import Snapshotter

root.mnist_ae.defaults({
    "loader": {"minibatch_size": 100, "n_train": 2000, "n_valid": 400,
               "n_test": 0, "data_path": ""},
    "conv": {"n_kernels": 9, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2),
             "sliding": (1, 1)},
    "pooling": {"kx": 2, "ky": 2},
    "learning_rate": 0.0003,     # MSE grads sum over pixels — keep lr small
    "gradient_moment": 0.9,
    "weights_decay": 0.0,
    "decision": {"max_epochs": 5, "fail_iterations": 0},
    "snapshotter": {"prefix": "mnist_ae", "interval": 0},
})


class MnistAELoader(FullBatchLoaderMSE):
    def load_data(self):
        cfg = root.mnist_ae.loader
        n_train = int(cfg.get("n_train"))
        n_valid = int(cfg.get("n_valid"))
        n_test = int(cfg.get("n_test"))
        total = n_train + n_valid + n_test
        data, _ = datasets.load_or_generate(
            cfg.get("data_path") or None, datasets.digits, total)
        self.original_data.mem = data[..., None]     # NHWC, C=1
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()


class MnistAEWorkflow(Workflow):
    def __init__(self, **kwargs):
        super().__init__(name="MnistAEWorkflow", **kwargs)
        cfg = root.mnist_ae
        gd_kw = {"learning_rate": float(cfg.get("learning_rate")),
                 "gradient_moment": float(cfg.get("gradient_moment")),
                 "weights_decay": float(cfg.get("weights_decay"))}

        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)
        self.loader = MnistAELoader(
            self, name="loader", targets_from_data=True,
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        self.loader.link_from(self.repeater)

        conv_cfg = cfg.conv.to_dict()
        self.conv = ConvTanh(self, name="conv", **conv_cfg)
        self.conv.link_from(self.loader)
        self.conv.link_attrs(self.loader, ("input", "minibatch_data"))

        self.pool = MaxPooling(self, name="pool",
                               kx=int(cfg.pooling.get("kx")),
                               ky=int(cfg.pooling.get("ky")))
        self.pool.link_from(self.conv)
        self.pool.link_attrs(self.conv, ("input", "output"))

        self.depool = Depooling(self, name="depool", pooling_from=self.pool)
        self.depool.link_from(self.pool)
        self.depool.link_attrs(self.pool, ("input", "output"))

        # decoder deconv: weights tied to the encoder conv (reference AE)
        self.deconv = Deconv(self, name="deconv", weights_from=self.conv)
        self.deconv.link_from(self.depool)
        self.deconv.link_attrs(self.depool, ("input", "output"))
        self.deconv.output_shape_from = self.conv.input

        self.evaluator = EvaluatorMSE(self, name="evaluator")
        self.evaluator.link_from(self.deconv)
        self.evaluator.link_attrs(self.deconv, "output")
        self.evaluator.link_attrs(self.loader,
                                  ("target", "minibatch_targets"),
                                  ("batch_size", "minibatch_size"))

        self.decision = DecisionMSE(
            self, name="decision",
            max_epochs=int(cfg.decision.get("max_epochs")),
            fail_iterations=int(cfg.decision.get("fail_iterations")))
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch", "class_ended",
            "epoch_number", "class_lengths", "minibatch_size")
        self.decision.link_attrs(self.evaluator, ("minibatch_loss", "loss"))

        self.snapshotter = Snapshotter(
            self, name="snapshotter",
            prefix=cfg.snapshotter.get("prefix"),
            interval=int(cfg.snapshotter.get("interval", 0)))
        self.snapshotter.link_from(self.decision)
        self.snapshotter.link_attrs(self.decision, "epoch_number")
        self.snapshotter.improved = self.decision.improved
        self.snapshotter.gate_skip = ~self.decision.epoch_ended

        # backward chain: deconv -> depool -> pool -> conv
        self.gd_deconv = GDDeconv(self, name="gd_deconv",
                                  forward=self.deconv, **gd_kw)
        self.gd_deconv.link_from(self.snapshotter)
        self.gd_deconv.link_attrs(self.evaluator, "err_output")

        self.gd_depool = GDDepooling(self, name="gd_depool",
                                     forward=self.depool)
        self.gd_depool.link_from(self.gd_deconv)
        self.gd_depool.link_attrs(self.gd_deconv,
                                  ("err_output", "err_input"))

        self.gd_pool = GDMaxPooling(self, name="gd_pool", forward=self.pool)
        self.gd_pool.link_from(self.gd_depool)
        self.gd_pool.link_attrs(self.gd_depool, ("err_output", "err_input"))

        self.gd_conv = GDTanhConv(self, name="gd_conv", forward=self.conv,
                                  need_err_input=False, **gd_kw)
        self.gd_conv.link_from(self.gd_pool)
        self.gd_conv.link_attrs(self.gd_pool, ("err_output", "err_input"))

        for gd in (self.gd_deconv, self.gd_depool, self.gd_pool,
                   self.gd_conv):
            gd.gate_skip = self.decision.gd_skip

        self.repeater.link_from(self.gd_conv)
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def run(snapshot: str = "", device=None) -> MnistAEWorkflow:
    wf = MnistAEWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train
    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
