"""YaleFaces-style sample (SURVEY §1 L10 lists YaleFaces among the
reference's ``znicz/samples/``): grayscale face identification from
DIRECTORIES of image files via ``FullBatchFileImageLoader`` — this sample
exercises the real file pipeline (directory scan, PIL decode, resize,
native u8->f32) end to end, unlike the resident-array samples.

No face data exists in this environment, so ``ensure_dataset`` synthesizes
a deterministic stand-in with the Yale B structure: each subject is a
fixed set of facial-geometry parameters (face ellipse, eye spacing, brow,
mouth curvature); each image varies ONLY nuisance conditions — lighting
direction (the defining Yale variation), exposure, small pose shifts and
noise — so identity is the sole reliable cue.  Images are written as real
PNG files under ``<data_dir>/<train|valid>/<subject_NN>/``.
"""

from __future__ import annotations

import os

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader.image import FullBatchFileImageLoader
from znicz_tpu.standard_workflow import StandardWorkflow

root.yale_faces.defaults({
    "loader": {"data_dir": "yale_faces_data", "n_subjects": 8,
               "n_train_per_subject": 16, "n_valid_per_subject": 4,
               "minibatch_size": 32, "size": 32},
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "weights_decay": 0.0001,
    "decision": {"max_epochs": 10, "fail_iterations": 0},
    "snapshotter": {"prefix": "yale", "interval": 0},
})


def _render_face(rng, geom, size):
    """One (size, size) image of the subject ``geom`` under a random
    lighting direction/exposure — Yale's nuisance axes."""
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    dy = float(rng.uniform(-0.04, 0.04))
    dx = float(rng.uniform(-0.04, 0.04))
    cy, cx = 0.5 + dy, 0.5 + dx
    face = np.exp(-(((xx - cx) / geom["fw"]) ** 2
                    + ((yy - cy) / geom["fh"]) ** 2) ** 2)
    img = 0.55 * face
    for side in (-1.0, 1.0):
        ex = cx + side * geom["eye_dx"]
        ey = cy - geom["eye_dy"]
        eye = np.exp(-((xx - ex) ** 2 + (yy - ey) ** 2)
                     / (2 * geom["eye_r"] ** 2))
        img -= 0.5 * eye
        brow = np.exp(-((xx - ex) ** 2 / (2 * (2.2 * geom["eye_r"]) ** 2)
                        + (yy - (ey - geom["brow_h"])) ** 2
                        / (2 * (0.35 * geom["eye_r"]) ** 2)))
        img -= 0.3 * brow
    mouth_y = cy + geom["mouth_dy"] + geom["mouth_curve"] * \
        np.square((xx - cx) / geom["fw"])
    mouth = np.exp(-((yy - mouth_y) ** 2 / (2 * 0.015 ** 2))
                   - ((xx - cx) ** 2 / (2 * geom["mouth_w"] ** 2)))
    img -= 0.4 * mouth
    # nuisance: directional lighting + exposure + noise
    ang = float(rng.uniform(0, 2 * np.pi))
    light = 0.5 + 0.5 * ((xx - 0.5) * np.cos(ang) + (yy - 0.5) * np.sin(ang))
    img = img * (0.45 + 0.55 * light) * float(rng.uniform(0.7, 1.0))
    img += rng.normal(0, 0.04, img.shape).astype(np.float32)
    return np.clip(img, 0.0, 1.0)


def _subject_geometry(rng):
    return {
        "fw": float(rng.uniform(0.26, 0.36)),
        "fh": float(rng.uniform(0.33, 0.45)),
        "eye_dx": float(rng.uniform(0.09, 0.15)),
        "eye_dy": float(rng.uniform(0.06, 0.12)),
        "eye_r": float(rng.uniform(0.02, 0.035)),
        "brow_h": float(rng.uniform(0.04, 0.07)),
        "mouth_dy": float(rng.uniform(0.12, 0.2)),
        "mouth_w": float(rng.uniform(0.05, 0.1)),
        "mouth_curve": float(rng.uniform(-0.12, 0.12)),
    }


def ensure_dataset(data_dir=None) -> str:
    """Write the PNG directory tree if absent; returns the base dir."""
    from PIL import Image

    cfg = root.yale_faces.loader
    base = data_dir or cfg.get("data_dir")
    if os.path.isdir(os.path.join(base, "train")):
        return base
    size = int(cfg.get("size"))
    gen = prng.get("dataset.yale")
    rng = gen.state
    for si in range(int(cfg.get("n_subjects"))):
        geom = _subject_geometry(rng)
        for split, count in (("train", int(cfg.get("n_train_per_subject"))),
                             ("valid", int(cfg.get("n_valid_per_subject")))):
            d = os.path.join(base, split, f"subject_{si:02d}")
            os.makedirs(d, exist_ok=True)
            for i in range(count):
                img = _render_face(rng, geom, size)
                Image.fromarray(
                    (img * 255).astype(np.uint8), mode="L").save(
                    os.path.join(d, f"img_{i:03d}.png"))
    return base


def make_layers(n_classes):
    cfg = root.yale_faces
    gd = {"learning_rate": float(cfg.get("learning_rate")),
          "gradient_moment": float(cfg.get("gradient_moment")),
          "weights_decay": float(cfg.get("weights_decay"))}
    return [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 8, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 16, "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 48},
         "<-": dict(gd)},
        {"type": "softmax", "->": {"output_sample_shape": n_classes},
         "<-": dict(gd)},
    ]


class YaleFacesWorkflow(StandardWorkflow):
    def __init__(self, data_dir=None, **kwargs):
        cfg = root.yale_faces
        base = ensure_dataset(data_dir)
        size = int(cfg.loader.get("size"))
        # PNGs on disk are grayscale; decode to 3-channel so the conv
        # stack sees (B, H, W, C) — the reference's image pipeline did the
        # same channel replication for L-mode inputs
        loader = FullBatchFileImageLoader(
            name="loader",
            train_path=os.path.join(base, "train"),
            valid_path=os.path.join(base, "valid"),
            target_shape=(size, size), grayscale=False,
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        super().__init__(
            name="YaleFacesWorkflow", loader=loader,
            layers=make_layers(int(cfg.loader.get("n_subjects"))),
            loss_function="softmax",
            decision_config={
                "max_epochs": int(cfg.decision.get("max_epochs")),
                "fail_iterations": int(cfg.decision.get("fail_iterations"))},
            snapshotter_config={
                "prefix": cfg.snapshotter.get("prefix"),
                "interval": int(cfg.snapshotter.get("interval", 0))},
            **kwargs)


def run(snapshot: str = "", device=None) -> YaleFacesWorkflow:
    wf = YaleFacesWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        from znicz_tpu.snapshotter import Snapshotter

        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train

    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
