"""CIFAR10 sample: 3-conv + 2-fc convnet — rebuild of the reference's
``znicz/samples/CIFAR10`` workflow, BASELINE config[1].  Declarative build
via StandardWorkflow; data is the procedural 32x32x3 texture set unless
``root.cifar.loader.data_path`` points at a real .npz.
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.core.config import root
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.standard_workflow import StandardWorkflow

root.cifar.defaults({
    "loader": {"minibatch_size": 100, "n_train": 2000, "n_valid": 400,
               "n_test": 0, "data_path": ""},
    "learning_rate": 0.02,
    "gradient_moment": 0.9,
    "weights_decay": 0.0001,
    "decision": {"max_epochs": 12, "fail_iterations": 0},
    "snapshotter": {"prefix": "cifar", "interval": 0},
})


class CifarLoader(FullBatchLoader):
    def load_data(self):
        cfg = root.cifar.loader
        n_train = int(cfg.get("n_train"))
        n_valid = int(cfg.get("n_valid"))
        n_test = int(cfg.get("n_test"))
        total = n_train + n_valid + n_test
        data, labels = datasets.load_or_generate(
            cfg.get("data_path") or None, datasets.tinyimages, total)
        self.original_data.mem = data                # NHWC
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()


def make_layers():
    cfg = root.cifar
    gd = {"learning_rate": float(cfg.get("learning_rate")),
          "gradient_moment": float(cfg.get("gradient_moment")),
          "weights_decay": float(cfg.get("weights_decay"))}
    return [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 16, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "norm"},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "avg_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 32, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "avg_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 64},
         "<-": dict(gd)},
        {"type": "softmax", "->": {"output_sample_shape": 10},
         "<-": dict(gd)},
    ]


class CifarWorkflow(StandardWorkflow):
    def __init__(self, **kwargs):
        cfg = root.cifar
        loader = CifarLoader(
            name="loader",
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        super().__init__(
            name="CifarWorkflow", loader=loader, layers=make_layers(),
            loss_function="softmax",
            decision_config={
                "max_epochs": int(cfg.decision.get("max_epochs")),
                "fail_iterations": int(cfg.decision.get("fail_iterations"))},
            snapshotter_config={
                "prefix": cfg.snapshotter.get("prefix"),
                "interval": int(cfg.snapshotter.get("interval", 0))},
            **kwargs)


def run(snapshot: str = "", device=None) -> CifarWorkflow:
    wf = CifarWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        from znicz_tpu.snapshotter import Snapshotter
        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train
    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
