"""AlexNet ImageNet sample — rebuild of the reference's
``znicz/samples/AlexNet`` workflow, BASELINE config[4].

Standard single-tower AlexNet (227x227x3 -> 1000): 5 convs (11/5/3/3/3) with
LRN after conv1/conv2, overlapping 3x3/s2 max pools, fc6/fc7 4096 with
dropout 0.5, softmax 1000.  Trains data-parallel: the FusedTrainer jits one
SPMD step over the device mesh; gradient psum rides ICI (the reference
shipped gradients to a ZeroMQ master instead — SURVEY.md §2.4).

Data: procedural 227x227 texture classes (no network in this environment);
point ``root.alexnet.loader.data_path`` at a real .npz for actual ImageNet.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu import datasets
from znicz_tpu.core.config import root
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.standard_workflow import StandardWorkflow

root.alexnet.defaults({
    "loader": {"minibatch_size": 128, "n_train": 512, "n_valid": 128,
               "n_test": 0, "n_classes": 100, "image_size": 227,
               "data_path": "", "train_dir": "", "valid_dir": "",
               "stream": False, "stream_budget_mb": 0},
    "learning_rate": 0.01,
    "gradient_moment": 0.9,
    "weights_decay": 0.0005,
    "dropout": 0.5,
    "decision": {"max_epochs": 3, "fail_iterations": 0},
    "snapshotter": {"prefix": "alexnet", "interval": 0},
})


class AlexNetLoader(FullBatchLoader):
    def load_data(self):
        cfg = root.alexnet.loader
        n_train = int(cfg.get("n_train"))
        n_valid = int(cfg.get("n_valid"))
        n_test = int(cfg.get("n_test"))
        total = n_train + n_valid + n_test
        data, labels = datasets.load_or_generate(
            cfg.get("data_path") or None, datasets.tinyimages, total,
            size=int(cfg.get("image_size", 227)))
        labels = (labels % int(cfg.get("n_classes", 100))).astype(np.int32)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()


def make_layers(n_classes: int):
    cfg = root.alexnet
    gd = {"learning_rate": float(cfg.get("learning_rate")),
          "gradient_moment": float(cfg.get("gradient_moment")),
          "weights_decay": float(cfg.get("weights_decay"))}
    drop = float(cfg.get("dropout"))
    # conv1_padding (default none — the reference geometry): an OPT-IN
    # layout experiment (VERDICT r4 item 2b).  (2,2,2,2) makes the
    # conv1/lrn1/pool1-input planes 56x56 instead of 55x55 — 56 = 8*7 is
    # sublane-friendly for the big elementwise fusions — while pool1
    # still emits 27x27, so everything downstream is unchanged.  It is a
    # DIFFERENT network at the borders (padded conv taps), so it is a
    # perf experiment, never the anchor protocol.
    conv1_pad = tuple(cfg.get("conv1_padding", (0, 0, 0, 0)))
    return [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 96, "kx": 11, "ky": 11, "sliding": (4, 4),
                "padding": conv1_pad},
         "<-": dict(gd)},
        {"type": "norm"},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 256, "kx": 5, "ky": 5, "padding": (2, 2, 2, 2)},
         "<-": dict(gd)},
        {"type": "norm"},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
         "<-": dict(gd)},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 384, "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
         "<-": dict(gd)},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 256, "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 3, "ky": 3, "sliding": (2, 2)}},
        {"type": "all2all_strict_relu", "->": {"output_sample_shape": 4096},
         "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": drop}},
        {"type": "all2all_strict_relu", "->": {"output_sample_shape": 4096},
         "<-": dict(gd)},
        {"type": "dropout", "->": {"dropout_ratio": drop}},
        {"type": "softmax", "->": {"output_sample_shape": n_classes},
         "<-": dict(gd)},
    ]


class AlexNetWorkflow(StandardWorkflow):
    """``root.alexnet.loader.train_dir`` (directory of class subdirs of
    image files — the reference's file-image route) switches the loader to
    ``FullBatchFileImageLoader`` with the ``image_size`` knob; the class
    count then comes from the directory tree.  With
    ``root.alexnet.loader.stream`` true the same directory feeds a
    ``StreamingLoader`` over a decode-on-demand ``ImageFileSource``
    instead — the ImageNet-at-scale route: nothing is decoded up front,
    HBM residency is capped by ``stream_budget_mb`` (0 = the engine
    default), and beyond it the fused driver stages minibatches straight
    from disk.  Otherwise data_path/.npz or the procedural stand-in feed
    the plain AlexNetLoader."""

    def __init__(self, **kwargs):
        cfg = root.alexnet
        train_dir = cfg.loader.get("train_dir", "")
        if train_dir and bool(cfg.loader.get("stream", False)):
            from znicz_tpu.loader.image import scan_class_dirs
            from znicz_tpu.loader.streaming import (ImageFileSource,
                                                    StreamingLoader)

            size = int(cfg.loader.get("image_size", 227))
            valid_dir = cfg.loader.get("valid_dir", "") or None
            # [valid | train] sample order matches the class offsets
            v_paths, v_labels = [], []
            if valid_dir:
                v_paths, v_labels, v_names = scan_class_dirs(valid_dir)
            t_paths, t_labels, names = scan_class_dirs(train_dir)
            if valid_dir:
                index_of = {n: i for i, n in enumerate(names)}
                v_labels = [index_of[v_names[l]] for l in v_labels]
            source = ImageFileSource(
                list(v_paths) + list(t_paths),
                list(v_labels) + list(t_labels), (size, size))
            budget_mb = float(cfg.loader.get("stream_budget_mb", 0))
            loader = StreamingLoader(
                name="loader", source=source,
                class_lengths=[0, len(v_paths), len(t_paths)],
                device_budget_bytes=int(budget_mb * 2**20) or None,
                minibatch_size=int(cfg.loader.get("minibatch_size")))
            n_classes = len(names)
        elif train_dir:
            import os

            from znicz_tpu.loader.image import FullBatchFileImageLoader

            size = int(cfg.loader.get("image_size", 227))
            loader = FullBatchFileImageLoader(
                name="loader", train_path=train_dir,
                valid_path=cfg.loader.get("valid_dir", "") or None,
                target_shape=(size, size),
                minibatch_size=int(cfg.loader.get("minibatch_size")))
            # class count = class SUBDIRS (scan_class_dirs' class_names
            # rule) — not the full per-file walk, which the loader
            # performs once itself at load_data
            n_classes = sum(
                os.path.isdir(os.path.join(train_dir, d))
                for d in os.listdir(train_dir))
        else:
            loader = AlexNetLoader(
                name="loader",
                minibatch_size=int(cfg.loader.get("minibatch_size")))
            n_classes = int(cfg.loader.get("n_classes", 100))
        super().__init__(
            name="AlexNetWorkflow", loader=loader,
            layers=make_layers(n_classes),
            loss_function="softmax",
            decision_config={
                "max_epochs": int(cfg.decision.get("max_epochs")),
                "fail_iterations": int(cfg.decision.get("fail_iterations"))},
            snapshotter_config={
                "prefix": cfg.snapshotter.get("prefix"),
                "interval": int(cfg.snapshotter.get("interval", 0))},
            **kwargs)


def run(device=None, fused: bool = True, mesh=None) -> AlexNetWorkflow:
    wf = AlexNetWorkflow()
    wf.initialize(device=device)
    if fused:
        from znicz_tpu.parallel.fused import FusedTrainer

        FusedTrainer(wf, mesh=mesh).run()
        wf.print_stats()
    else:
        wf.run()
        wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
