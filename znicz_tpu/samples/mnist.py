"""MNIST sample: 784 -> 100(tanh) -> 10(softmax) MLP — the rebuild of the
reference's ``znicz/samples/MNIST`` workflow, BASELINE config[0].

Wiring mirrors the reference call stack (SURVEY.md §3.1):

    start -> repeater -> loader -> fwd1 -> fwd2 -> evaluator -> decision
                ^                                                 |
                |            (gd_skip gates on non-TRAIN)         v
                +------------- gd1 <---------- gd2 <--------------+
    decision.complete -> end_point (gate_block otherwise)
    decision.improved & epoch_ended -> snapshotter

Data: procedural digit glyphs (datasets.digits) unless
``root.mnist.loader.data_path`` points at an .npz with real MNIST.
"""

from __future__ import annotations

import numpy as np

from znicz_tpu import datasets
from znicz_tpu.all2all import All2AllSoftmax, All2AllTanh
from znicz_tpu.core.config import root
from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.decision import DecisionGD
from znicz_tpu.evaluator import EvaluatorSoftmax
from znicz_tpu.gd import GDSoftmax, GDTanh
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.snapshotter import Snapshotter

# -- defaults (override like the reference: root.mnist.decision.max_epochs=3;
#    overrides set before import win, exactly like reference config files)
root.mnist.defaults({
    "loader": {"minibatch_size": 60, "n_train": 4000, "n_valid": 800,
               "n_test": 0, "data_path": ""},
    "layers": [100, 10],
    "learning_rate": 0.1,
    "gradient_moment": 0.9,
    "weights_decay": 0.0,
    "decision": {"max_epochs": 5, "fail_iterations": 0},
    "snapshotter": {"prefix": "mnist", "interval": 0},
})


class MnistLoader(FullBatchLoader):
    def load_data(self):
        cfg = root.mnist.loader
        n_train = int(cfg.get("n_train", 4000))
        n_valid = int(cfg.get("n_valid", 800))
        n_test = int(cfg.get("n_test", 0))
        total = n_train + n_valid + n_test
        data, labels = datasets.load_or_generate(
            cfg.get("data_path") or None, datasets.digits, total)
        # order: [test | valid | train] to match class offsets
        self.original_data.mem = data.reshape(total, -1)
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()


class MnistWorkflow(Workflow):
    def __init__(self, **kwargs):
        super().__init__(name="MnistWorkflow", **kwargs)
        cfg = root.mnist
        layers = list(cfg.get("layers"))
        lr = float(cfg.get("learning_rate"))
        mom = float(cfg.get("gradient_moment"))
        wd = float(cfg.get("weights_decay"))

        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)

        self.loader = MnistLoader(
            self, name="loader",
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        self.loader.link_from(self.repeater)

        # forwards
        self.forwards = []
        prev = self.loader
        prev_attr = "minibatch_data"
        for i, width in enumerate(layers):
            cls = All2AllSoftmax if i == len(layers) - 1 else All2AllTanh
            fwd = cls(self, name=f"fwd{i}", output_sample_shape=(width,))
            fwd.link_from(prev if i == 0 else self.forwards[-1])
            fwd.link_attrs(prev, ("input", prev_attr))
            self.forwards.append(fwd)
            prev, prev_attr = fwd, "output"

        self.evaluator = EvaluatorSoftmax(self, name="evaluator",
                                          n_classes=layers[-1])
        self.evaluator.link_from(self.forwards[-1])
        self.evaluator.link_attrs(self.forwards[-1], "output")
        self.evaluator.link_attrs(self.loader,
                                  ("labels", "minibatch_labels"),
                                  ("batch_size", "minibatch_size"))

        self.decision = DecisionGD(
            self, name="decision",
            max_epochs=int(cfg.decision.get("max_epochs")),
            fail_iterations=int(cfg.decision.get("fail_iterations")))
        self.decision.link_from(self.evaluator)
        self.decision.link_attrs(
            self.loader, "minibatch_class", "last_minibatch", "class_ended",
            "epoch_number", "class_lengths", "minibatch_size")
        self.decision.link_attrs(
            self.evaluator, ("minibatch_loss", "loss"),
            ("minibatch_n_err", "n_err"), "confusion_matrix",
            "max_err_output_sum")

        self.snapshotter = Snapshotter(
            self, name="snapshotter",
            prefix=cfg.snapshotter.get("prefix"),
            interval=int(cfg.snapshotter.get("interval", 0)))
        self.snapshotter.link_from(self.decision)
        self.snapshotter.link_attrs(self.decision, "epoch_number")
        self.snapshotter.improved = self.decision.improved   # shared Bool
        self.snapshotter.gate_skip = ~self.decision.epoch_ended

        # backward chain, reverse order
        self.gds = []
        err_src, err_attr = self.evaluator, "err_output"
        for i in reversed(range(len(layers))):
            cls = GDSoftmax if i == len(layers) - 1 else GDTanh
            gd = cls(self, name=f"gd{i}", forward=self.forwards[i],
                     learning_rate=lr, gradient_moment=mom, weights_decay=wd,
                     need_err_input=(i > 0))
            gd.link_from(self.snapshotter if not self.gds else self.gds[-1])
            gd.link_attrs(err_src, ("err_output", err_attr))
            gd.gate_skip = self.decision.gd_skip
            self.gds.append(gd)
            err_src, err_attr = gd, "err_input"

        self.repeater.link_from(self.gds[-1])
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def run(snapshot: str = "", device=None) -> MnistWorkflow:
    wf = MnistWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train
    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
