"""Kanji sample (SURVEY §1 L10 lists Kanji among the reference's
``znicz/samples/``): many-class glyph classification — the regime that
stresses the wide-softmax head and per-class balancing, unlike the
10-class MNIST/CIFAR anchors.

Data is the procedural stroke-composition set (``datasets.kanji``: each
class a fixed random arrangement of stroke segments) unless
``root.kanji.loader.data_path`` points at a real .npz.
"""

from __future__ import annotations

from znicz_tpu import datasets
from znicz_tpu.core.config import root
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.standard_workflow import StandardWorkflow

root.kanji.defaults({
    "loader": {"minibatch_size": 128, "n_train": 4096, "n_valid": 512,
               "n_test": 0, "n_classes": 64, "data_path": ""},
    "learning_rate": 0.03,
    "gradient_moment": 0.9,
    "weights_decay": 0.0001,
    "decision": {"max_epochs": 8, "fail_iterations": 0},
    "snapshotter": {"prefix": "kanji", "interval": 0},
})


class KanjiLoader(FullBatchLoader):
    def load_data(self):
        cfg = root.kanji.loader
        n_train = int(cfg.get("n_train"))
        n_valid = int(cfg.get("n_valid"))
        n_test = int(cfg.get("n_test"))
        total = n_train + n_valid + n_test
        data, labels = datasets.load_or_generate(
            cfg.get("data_path") or None, datasets.kanji, total,
            n_classes=int(cfg.get("n_classes")))
        self.original_data.mem = data[..., None]        # NHWC, C=1
        self.original_labels.mem = labels
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()


def make_layers(n_classes):
    cfg = root.kanji
    gd = {"learning_rate": float(cfg.get("learning_rate")),
          "gradient_moment": float(cfg.get("gradient_moment")),
          "weights_decay": float(cfg.get("weights_decay"))}
    return [
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 16, "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "conv_strict_relu",
         "->": {"n_kernels": 32, "kx": 3, "ky": 3, "padding": (1, 1, 1, 1)},
         "<-": dict(gd)},
        {"type": "max_pooling", "->": {"kx": 2, "ky": 2}},
        {"type": "all2all_tanh", "->": {"output_sample_shape": 128},
         "<-": dict(gd)},
        {"type": "softmax", "->": {"output_sample_shape": n_classes},
         "<-": dict(gd)},
    ]


class KanjiWorkflow(StandardWorkflow):
    def __init__(self, **kwargs):
        cfg = root.kanji
        loader = KanjiLoader(
            name="loader",
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        super().__init__(
            name="KanjiWorkflow", loader=loader,
            layers=make_layers(int(cfg.loader.get("n_classes"))),
            loss_function="softmax",
            decision_config={
                "max_epochs": int(cfg.decision.get("max_epochs")),
                "fail_iterations": int(cfg.decision.get("fail_iterations"))},
            snapshotter_config={
                "prefix": cfg.snapshotter.get("prefix"),
                "interval": int(cfg.snapshotter.get("interval", 0))},
            **kwargs)


def run(snapshot: str = "", device=None) -> KanjiWorkflow:
    wf = KanjiWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        from znicz_tpu.snapshotter import Snapshotter

        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train

    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
