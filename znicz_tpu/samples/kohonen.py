"""Kohonen sample: self-organizing map on 2-D gaussian clusters — rebuild of
the reference's ``znicz/samples/Kohonen`` workflow, BASELINE config[3].
Unsupervised: no evaluator/GD chain; the trainer is the learning rule and
the forward unit accumulates the hit map (behavioral-parity artifact)."""

from __future__ import annotations

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.core.workflow import Repeater, Workflow
from znicz_tpu.kohonen import KohonenDecision, KohonenForward, KohonenTrainer
from znicz_tpu.loader.fullbatch import FullBatchLoader

root.kohonen.defaults({
    "loader": {"minibatch_size": 50, "n_train": 1000, "n_clusters": 10},
    "som": {"shape": (8, 8), "learning_rate": 0.5, "decay_epochs": 15},
    "decision": {"max_epochs": 10},
})


def cluster_points(n: int, n_clusters: int,
                   stream: str = "dataset.kohonen") -> np.ndarray:
    """2-D points from gaussian clusters on a ring (deterministic)."""
    gen = prng.get(stream)
    rng = gen.state
    which = rng.integers(0, n_clusters, size=n)
    angles = 2 * np.pi * which / n_clusters
    centers = np.stack([np.cos(angles), np.sin(angles)], axis=1)
    return (centers + rng.normal(0, 0.08, size=(n, 2))).astype(np.float32)


class KohonenLoader(FullBatchLoader):
    def load_data(self):
        cfg = root.kohonen.loader
        n = int(cfg.get("n_train"))
        self.original_data.mem = cluster_points(
            n, int(cfg.get("n_clusters")))
        self.class_lengths = [0, 0, n]
        super().load_data()

    def create_minibatch_data(self):
        super().create_minibatch_data()
        self.minibatch_labels.mem = None    # unsupervised


class KohonenWorkflow(Workflow):
    def __init__(self, **kwargs):
        super().__init__(name="KohonenWorkflow", **kwargs)
        cfg = root.kohonen
        shape = tuple(cfg.som.get("shape"))

        self.repeater = Repeater(self, name="repeater")
        self.repeater.link_from(self.start_point)
        self.loader = KohonenLoader(
            self, name="loader",
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        self.loader.link_from(self.repeater)

        self.trainer = KohonenTrainer(
            self, name="trainer", shape=shape,
            learning_rate=float(cfg.som.get("learning_rate")),
            decay_epochs=float(cfg.som.get("decay_epochs")))
        self.trainer.link_from(self.loader)
        self.trainer.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("batch_size", "minibatch_size"),
                                "epoch_number")

        self.forward = KohonenForward(self, name="forward", shape=shape,
                                      weights_from=self.trainer)
        self.forward.link_from(self.trainer)
        self.forward.link_attrs(self.loader, ("input", "minibatch_data"),
                                ("batch_size", "minibatch_size"))

        self.decision = KohonenDecision(
            self, name="decision",
            max_epochs=int(cfg.decision.get("max_epochs")))
        self.decision.link_from(self.forward)
        self.decision.link_attrs(self.loader, "last_minibatch",
                                 "epoch_number")
        self.decision.link_attrs(self.trainer, "qerror")

        self.repeater.link_from(self.decision)
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete


def run(device=None) -> KohonenWorkflow:
    wf = KohonenWorkflow()
    wf.initialize(device=device)
    from znicz_tpu.engine import train
    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
