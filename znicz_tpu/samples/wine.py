"""Wine sample: tiny tabular MLP (13 features -> 3 classes) — rebuild of the
reference's ``znicz/samples/Wine``, its smallest end-to-end smoke workflow.
Data: procedural 3-cluster tabular set with the Wine dataset's shape."""

from __future__ import annotations

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import root
from znicz_tpu.loader.fullbatch import FullBatchLoader
from znicz_tpu.normalization import MeanDispNormalizer
from znicz_tpu.standard_workflow import StandardWorkflow

root.wine.defaults({
    "loader": {"minibatch_size": 10, "n_train": 130, "n_valid": 48},
    "layers": [8, 3],
    "learning_rate": 0.3,
    "gradient_moment": 0.5,
    "decision": {"max_epochs": 20, "fail_iterations": 0},
})


def wine_like(n: int, stream: str = "dataset.wine"):
    """13-feature, 3-class gaussian clusters with per-feature scales that
    mimic the real Wine dataset's wildly different feature ranges."""
    gen = prng.get(stream)
    rng = gen.state
    labels = rng.integers(0, 3, size=n).astype(np.int32)
    centers = rng.normal(0, 1.0, size=(3, 13)).astype(np.float32)
    scales = np.geomspace(0.1, 100.0, 13).astype(np.float32)
    data = (centers[labels] + rng.normal(0, 0.6, size=(n, 13))) * scales
    return data.astype(np.float32), labels


class WineLoader(FullBatchLoader):
    def load_data(self):
        cfg = root.wine.loader
        n_train = int(cfg.get("n_train"))
        n_valid = int(cfg.get("n_valid"))
        data, labels = wine_like(n_train + n_valid)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = [0, n_valid, n_train]
        super().load_data()


class WineWorkflow(StandardWorkflow):
    def __init__(self, **kwargs):
        cfg = root.wine
        gd = {"learning_rate": float(cfg.get("learning_rate")),
              "gradient_moment": float(cfg.get("gradient_moment"))}
        widths = list(cfg.get("layers"))
        layers = [{"type": "all2all_tanh",
                   "->": {"output_sample_shape": w}, "<-": dict(gd)}
                  for w in widths[:-1]]
        layers.append({"type": "softmax",
                       "->": {"output_sample_shape": widths[-1]},
                       "<-": dict(gd)})
        loader = WineLoader(
            name="loader", normalizer=MeanDispNormalizer(),
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        super().__init__(
            name="WineWorkflow", loader=loader, layers=layers,
            loss_function="softmax",
            decision_config={
                "max_epochs": int(cfg.decision.get("max_epochs")),
                "fail_iterations": int(cfg.decision.get("fail_iterations"))},
            **kwargs)


def run(snapshot: str = "", device=None) -> WineWorkflow:
    wf = WineWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        from znicz_tpu.snapshotter import Snapshotter
        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train
    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
