"""VideoAE sample (SURVEY §1 L10 lists VideoAE among the reference's
``znicz/samples/``): an autoencoder trained on video FRAMES — the
reference compressed video by learning the frame manifold.  Data is the
procedural moving-blob clip set (``datasets.videoframes``); the declarative
StandardWorkflow build with ``loss_function="mse"`` wires EvaluatorMSE /
DecisionMSE (targets = the frames themselves).
"""

from __future__ import annotations

import numpy as np

from znicz_tpu import datasets
from znicz_tpu.core.config import root
from znicz_tpu.loader.fullbatch import FullBatchLoaderMSE
from znicz_tpu.standard_workflow import StandardWorkflow

root.video_ae.defaults({
    "loader": {"minibatch_size": 100, "n_train": 2000, "n_valid": 400,
               "n_test": 0, "data_path": ""},
    "latent": 24,
    "learning_rate": 0.05,       # tuned: see tests/test_samples_ext.py
    "gradient_moment": 0.9,
    "weights_decay": 0.0,
    "decision": {"max_epochs": 20, "fail_iterations": 0},
    "snapshotter": {"prefix": "video_ae", "interval": 0},
})


class VideoAELoader(FullBatchLoaderMSE):
    def load_data(self):
        cfg = root.video_ae.loader
        n_train = int(cfg.get("n_train"))
        n_valid = int(cfg.get("n_valid"))
        n_test = int(cfg.get("n_test"))
        total = n_train + n_valid + n_test
        data, _ = datasets.load_or_generate(
            cfg.get("data_path") or None, datasets.videoframes, total)
        self.original_data.mem = np.asarray(data, np.float32)
        self.class_lengths = [n_test, n_valid, n_train]
        super().load_data()


def make_layers(frame_shape):
    cfg = root.video_ae
    gd = {"learning_rate": float(cfg.get("learning_rate")),
          "gradient_moment": float(cfg.get("gradient_moment")),
          "weights_decay": float(cfg.get("weights_decay"))}
    latent = int(cfg.get("latent"))
    return [
        {"type": "all2all_tanh", "->": {"output_sample_shape": latent},
         "<-": dict(gd)},
        {"type": "all2all", "->": {"output_sample_shape": frame_shape},
         "<-": dict(gd)},
    ]


class VideoAEWorkflow(StandardWorkflow):
    def __init__(self, **kwargs):
        cfg = root.video_ae
        loader = VideoAELoader(
            name="loader", targets_from_data=True,
            minibatch_size=int(cfg.loader.get("minibatch_size")))
        super().__init__(
            name="VideoAEWorkflow", loader=loader,
            layers=make_layers((16, 16)),
            loss_function="mse",
            decision_config={
                "max_epochs": int(cfg.decision.get("max_epochs")),
                "fail_iterations": int(cfg.decision.get("fail_iterations"))},
            snapshotter_config={
                "prefix": cfg.snapshotter.get("prefix"),
                "interval": int(cfg.snapshotter.get("interval", 0))},
            **kwargs)


def run(snapshot: str = "", device=None) -> VideoAEWorkflow:
    wf = VideoAEWorkflow()
    wf.initialize(device=device)
    if snapshot:
        from znicz_tpu import snapshotter as snap_mod
        from znicz_tpu.snapshotter import Snapshotter

        snap_mod.restore(wf, Snapshotter.load(snapshot))
    from znicz_tpu.engine import train

    train(wf)
    wf.print_stats()
    return wf


if __name__ == "__main__":
    run()
