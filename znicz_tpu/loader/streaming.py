"""Streaming loaders: datasets larger than HBM feed the fused scan path
(rebuild of the reference's file-image minibatch streaming, SURVEY.md §2.1
image-loaders row / §3.1 hot loop — the reference assembled every minibatch
on the host and shipped it to the device per step).

TPU-native design, three residency regimes behind ONE loader:

  1. **f32-resident** (small data): behaves exactly like FullBatchLoader —
     the dataset is one HBM array, the fused step gathers on device.
  2. **u8-resident** (medium data): the dataset stays in HBM in its STORAGE
     dtype (uint8) and is decoded to f32 *inside* the jitted step, fused
     into the gather (`FusedTrainer._gather_decode`).  4x more samples
     resident than the f32 layout — an AlexNet set whose f32 form exceeds
     a v5e's 16 GB trains entirely from HBM.  Decode is VPU elementwise
     work that XLA fuses into the first conv's input pipeline; throughput
     is indistinguishable from f32-resident (bench `--stream`).
  3. **host-staged** (large data): the dataset lives on the host (numpy,
     memmap, or decode-on-demand image files).  The fused driver stages
     each scan segment as (K, B, ...) minibatch tensors consumed
     directly by the scan xs — `host_gather` assembles the rows (native
     C++ row gather when available) and ships them batch-sharded over
     the mesh's ``data`` axis (u8 over the wire, decode on device).  In
     a MULTI-HOST run each process gathers ONLY the rows of the batch
     shards its own devices hold (`FusedTrainer._stage_direct`) — the
     SPMD analogue of the reference's per-slave minibatch feed.
     Dispatch is async, so segment N+1's host assembly + transfer
     overlap segment N's device compute (double buffering without
     threads — there is nothing to wait on until the metrics flush).
     Steady state (three-term roofline, bench --stream measures each):
     ``img/s = min(compute rate, H2D bytes/s / bytes-per-sample,
     decode rate)`` — u8 staging needs ~1.6 GB/s for AlexNet-227 at the
     r3 compute rate, i.e. any real PCIe-attached TPU host is
     compute-bound on the link; tunneled dev hosts are link-bound and
     bench --stream records the measured link bandwidth next to the
     throughput so the number explains itself.  The DECODE term is
     served by the host ingest engine (loader/ingest.py): file-backed
     sources decode on an N-worker pool, and the fused driver's
     lookahead prefetches future segments' rows so decode overlaps
     device compute (VERDICT r4 item 1).

The residency regime is chosen at initialize: ``device_budget_bytes``
(kwarg or ``root.common.engine.stream_budget_mb``) caps what may sit in
HBM; a dataset within budget is uploaded once (regime 1/2 by storage
dtype), beyond it stays host-side (regime 3).

Normalization: host-staged u8 data reaches the graph as
``u8 * scale + shift`` (linear decode, the image-pipeline norm).  Nonlinear
normalizers need the f32 path (FullBatchLoader) — asserted, not silent.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from znicz_tpu import native
from znicz_tpu.loader.base import Loader

#: default HBM budget for keeping the dataset resident (bytes); overridden
#: by ``root.common.engine.stream_budget_mb`` or the loader kwarg
DEFAULT_DEVICE_BUDGET = 4 << 30


class HostArraySource:
    """A sample-major numpy (or memmap) array as the streaming data source.
    ``data`` keeps its storage dtype (uint8 passes through to the device
    untouched; float32 is gathered with the native row-gather)."""

    def __init__(self, data: np.ndarray, labels: Optional[np.ndarray] = None,
                 targets: Optional[np.ndarray] = None):
        if data.dtype not in (np.uint8, np.float32):
            data = np.asarray(data, np.float32)
        self.data = data
        self.labels = (None if labels is None
                       else np.asarray(labels, np.int32))
        self.targets = (None if targets is None
                        else np.asarray(targets, np.float32))

    def __len__(self) -> int:
        return len(self.data)

    @property
    def sample_shape(self) -> Tuple[int, ...]:
        return tuple(self.data.shape[1:])

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes)

    def gather(self, idx: np.ndarray) -> np.ndarray:
        """Contiguous sample rows for ``idx`` (storage dtype preserved)."""
        if self.data.dtype == np.float32 and not isinstance(
                self.data, np.memmap):
            return native.gather_f32(self.data, idx).reshape(
                (len(idx),) + self.sample_shape)
        return np.ascontiguousarray(np.take(self.data, idx, axis=0))

    def whole(self) -> np.ndarray:
        return np.ascontiguousarray(self.data)


class ImageFileSource:
    """Decode-on-demand image files (the reference's file-image route at
    beyond-HBM scale): rows are decoded u8 only when a segment stages them.
    ``paths``/``labels`` aligned; images resized to ``target_shape``.

    Decode runs on a ``DecodePool`` (loader/ingest.py): ``workers`` threads
    decode a gather's rows in parallel, and ``prefetch(idx)`` starts decode
    for rows a FUTURE segment will stage — the fused driver submits its
    lookahead so decode overlaps device compute.  ``workers`` defaults to
    ``root.common.engine.decode_workers`` (else one per CPU, capped);
    ``workers=0`` forces the serial path.  Pooled and serial decode are
    bit-identical (decode is pure), so the parallelism is invisible to
    training math."""

    def __init__(self, paths: Sequence[str], labels: Sequence[int],
                 target_shape: Tuple[int, int], grayscale: bool = False,
                 workers: Optional[int] = None):
        assert len(paths) == len(labels)
        self.paths = list(paths)
        self.labels = np.asarray(labels, np.int32)
        self.target_shape = tuple(target_shape)
        self.grayscale = bool(grayscale)
        self.targets = None
        self.workers = workers
        self._pool = None

    def __len__(self) -> int:
        return len(self.paths)

    @property
    def sample_shape(self) -> Tuple[int, ...]:
        h, w = self.target_shape
        return (h, w) if self.grayscale else (h, w, 3)

    @property
    def dtype(self):
        return np.dtype(np.uint8)

    @property
    def nbytes(self) -> int:
        return len(self) * int(np.prod(self.sample_shape))

    def _decode_u8(self, path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as img:
            img = img.convert("L" if self.grayscale else "RGB")
            img = img.resize((self.target_shape[1], self.target_shape[0]))
            return np.asarray(img, np.uint8)

    def _decode_row(self, i: int) -> np.ndarray:
        return self._decode_u8(self.paths[i])

    def pool(self):
        """The lazily-created decode pool, or None in serial mode
        (``workers=0``).  Even ``workers=1`` keeps the pool: a single
        worker cannot raise the decode RATE, but prefetched rows still
        decode on the worker thread while the training thread waits on
        device compute — the overlap matters on any host."""
        if self._pool is None:
            from znicz_tpu.loader.ingest import DecodePool, default_workers

            w = (default_workers() if self.workers is None
                 else int(self.workers))
            if w < 1:
                return None
            self._pool = DecodePool(self._decode_row, workers=w)
        return self._pool

    def with_workers(self, workers: int) -> "ImageFileSource":
        """A sibling source over the same files with a different worker
        count (measurement helper — ingest.measure_decode_rate)."""
        return ImageFileSource(self.paths, self.labels, self.target_shape,
                               self.grayscale, workers=workers)

    def prefetch(self, idx: np.ndarray) -> int:
        """Start decoding rows a future gather will consume (bounded;
        see DecodePool.submit).  Returns rows newly submitted."""
        pool = self.pool()
        return pool.submit(idx) if pool is not None else 0

    @property
    def ingest_stats(self) -> Optional[dict]:
        return None if self._pool is None else dict(self._pool.stats)

    def gather(self, idx: np.ndarray) -> np.ndarray:
        pool = self.pool()
        if pool is not None:
            return pool.take(idx)
        return np.stack([self._decode_u8(self.paths[i]) for i in idx])

    def whole(self) -> np.ndarray:
        return self.gather(np.arange(len(self)))


class StreamingLoader(Loader):
    """Loader over a host data source; serves all three residency regimes.

    kwargs beyond Loader's:
      - ``source``: HostArraySource / ImageFileSource (or a raw numpy
        array, wrapped automatically);
      - ``class_lengths``: [test, valid, train] split (default: all TRAIN);
      - ``scale``/``shift``: the on-device u8 decode ``u8*scale + shift``
        (default 1/255, 0 — [0,1] images);
      - ``device_budget_bytes``: HBM residency cap (see module docstring).
    """

    streaming = True

    def __init__(self, workflow=None, name=None, source=None,
                 class_lengths=None, scale=1.0 / 255.0, shift=0.0,
                 device_budget_bytes=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        from znicz_tpu.memory import Array

        if isinstance(source, np.ndarray):
            source = HostArraySource(source)
        self.source = source
        self._class_lengths_arg = class_lengths
        self.scale = float(scale)
        self.shift = float(shift)
        self.device_budget_bytes = device_budget_bytes
        #: set by initialize: True -> original_data holds the whole dataset
        #: (storage dtype) and the fused path runs its resident gather;
        #: False -> the fused path stages segments via host_gather
        self.device_resident = False
        self.original_data = Array()
        self.original_labels = Array()
        self.original_targets = Array()
        self.minibatch_targets = Array()
        if kwargs.get("normalizer") is not None:
            raise ValueError(
                f"{name}: nonlinear normalizers need the f32-resident "
                "FullBatchLoader; streaming decode is linear scale/shift")

    # -- geometry / split ------------------------------------------------------

    def _budget(self) -> int:
        if self.device_budget_bytes is not None:
            return int(self.device_budget_bytes)
        from znicz_tpu.core.config import root

        mb = root.common.engine.get("stream_budget_mb", None)
        return (int(mb) << 20) if mb is not None else DEFAULT_DEVICE_BUDGET

    def load_data(self) -> None:
        if self.source is None:
            raise ValueError(f"{self.name}: source not set")
        n = len(self.source)
        if self._class_lengths_arg is not None:
            self.class_lengths = list(self._class_lengths_arg)
            if sum(self.class_lengths) != n:
                raise ValueError(
                    f"{self.name}: class_lengths {self.class_lengths} "
                    f"!= {n} source samples")
        else:
            self.class_lengths = [0, 0, n]
        if self.source.labels is not None:
            self.original_labels.mem = np.asarray(self.source.labels,
                                                  np.int32)
        if getattr(self.source, "targets", None) is not None:
            self.original_targets.mem = self.source.targets
        self.device_resident = self.source.nbytes <= self._budget()
        if self.device_resident:
            self.original_data.mem = self.source.whole()

    def create_minibatch_data(self) -> None:
        self.minibatch_data.mem = np.zeros(
            (self.max_minibatch_size,) + tuple(self.source.sample_shape),
            np.float32)
        if self.original_labels.mem is not None:
            self.minibatch_labels.mem = np.zeros(self.max_minibatch_size,
                                                 np.int32)
        if self.original_targets.mem is not None:
            self.minibatch_targets.mem = np.zeros(
                (self.max_minibatch_size,)
                + self.original_targets.mem.shape[1:], np.float32)

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        for arr in (self.original_data, self.original_labels,
                    self.original_targets, self.minibatch_targets):
            arr.initialize(device)

    def train_labels(self):
        return (self.original_labels.mem
                if self.original_labels.mem is not None else None)

    # -- the streaming surface (consumed by FusedTrainer) ----------------------

    def host_gather(self, idx: np.ndarray) -> np.ndarray:
        """Sample rows for global indices, STORAGE dtype (u8 ships as u8;
        the device decodes)."""
        return self.source.gather(np.asarray(idx, np.int32))

    def prefetch_rows(self, idx: np.ndarray) -> int:
        """Hint that a FUTURE host_gather will need these rows: sources
        with a decode pool (ImageFileSource) start decoding them now so
        the decode overlaps device compute (loader/ingest.py).  No-op for
        memcpy-cheap sources.  Returns rows newly submitted."""
        fn = getattr(self.source, "prefetch", None)
        return int(fn(np.asarray(idx, np.int32))) if fn is not None else 0

    @property
    def ingest_stats(self) -> Optional[dict]:
        """Decode-pool counters (prefetch_hits / decode_misses / ...) or
        None when the source has no pool."""
        return getattr(self.source, "ingest_stats", None)

    def host_gather_labels(self, idx: np.ndarray) -> np.ndarray:
        return np.take(self.original_labels.mem,
                       np.asarray(idx, np.int32), axis=0)

    def host_gather_targets(self, idx: np.ndarray) -> np.ndarray:
        return np.ascontiguousarray(np.take(
            self.original_targets.mem, np.asarray(idx, np.int32), axis=0))

    @property
    def decode_needed(self) -> bool:
        return self.source.dtype == np.uint8

    # -- unit-engine path ------------------------------------------------------

    def fill_minibatch(self) -> None:
        """Host gather + decode into the f32 minibatch buffers (the unit
        engine's per-step route; the fused path never calls this)."""
        idx = np.asarray(self.minibatch_indices.mem, np.int32)
        rows = self.host_gather(idx)
        data = self.minibatch_data.map_invalidate()
        if rows.dtype == np.uint8:
            data[...] = rows.astype(np.float32) * self.scale + self.shift
        else:
            data[...] = rows
        if self.original_labels.mem is not None:
            self.minibatch_labels.map_invalidate()[...] = \
                self.host_gather_labels(idx)
        if self.original_targets.mem is not None:
            self.minibatch_targets.map_invalidate()[...] = \
                self.host_gather_targets(idx)


def class_dir_source(base: str, target_shape: Tuple[int, int],
                     grayscale: bool = False,
                     workers: Optional[int] = None) -> ImageFileSource:
    """<base>/<class>/*.img -> a decode-on-demand source (the directory
    layout of loader/image.py, without the resident decode)."""
    from znicz_tpu.loader.image import scan_class_dirs

    paths, labels, _names = scan_class_dirs(base)
    return ImageFileSource(paths, labels, target_shape, grayscale,
                           workers=workers)
