"""Image loaders (rebuild of ``veles/loader/image.py`` + ``file_image.py``).

``FullBatchFileImageLoader`` walks class directories of image files, decodes
with PIL, resizes/crops to a fixed ``target_shape``, converts u8 -> f32
through the native C++ decode path (znicz_tpu.native) and serves them as a
resident FullBatch dataset — the reference's directory-image pipeline with
the scale/crop semantics preserved.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from znicz_tpu import native
from znicz_tpu.loader.fullbatch import FullBatchLoader

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm", ".pgm")


def decode_image(path: str, target_shape: Tuple[int, int],
                 grayscale: bool = False) -> np.ndarray:
    """Decode + resize one image to (H, W[, 3]) float32 in [0, 1]."""
    from PIL import Image

    with Image.open(path) as img:
        img = img.convert("L" if grayscale else "RGB")
        img = img.resize((target_shape[1], target_shape[0]))
        arr = np.asarray(img, np.uint8)
    return native.u8_to_f32(arr)


def scan_class_dirs(base: str,
                    exts: Sequence[str] = IMAGE_EXTS
                    ) -> Tuple[List[str], List[int], List[str]]:
    """<base>/<class_name>/*.img -> (paths, labels, class_names)."""
    class_names = sorted(
        d for d in os.listdir(base)
        if os.path.isdir(os.path.join(base, d)))
    paths, labels = [], []
    for ci, cname in enumerate(class_names):
        cdir = os.path.join(base, cname)
        for fname in sorted(os.listdir(cdir)):
            if os.path.splitext(fname)[1].lower() in exts:
                paths.append(os.path.join(cdir, fname))
                labels.append(ci)
    return paths, labels, class_names


class FullBatchFileImageLoader(FullBatchLoader):
    """kwargs: ``train_path`` (required), ``valid_path``, ``test_path`` —
    each a directory of class subdirectories; ``target_shape=(H, W)``;
    ``grayscale``."""

    def __init__(self, workflow=None, name=None, train_path=None,
                 valid_path=None, test_path=None, target_shape=(32, 32),
                 grayscale=False, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.train_path = train_path
        self.valid_path = valid_path
        self.test_path = test_path
        self.target_shape = tuple(target_shape)
        self.grayscale = bool(grayscale)
        self.class_names: Optional[List[str]] = None

    def _load_split(self, base: Optional[str]):
        """Class indices always come from the TRAIN directory's class_names
        mapping (fixed before eval splits load); a split containing a class
        absent from train is an error, not a silent relabel."""
        if not base:
            return np.zeros((0,) + self._sample_shape(), np.float32), \
                np.zeros(0, np.int32)
        paths, local_labels, names = scan_class_dirs(base)
        index_of = {n: i for i, n in enumerate(self.class_names)}
        unknown = [n for n in names if n not in index_of]
        if unknown:
            raise ValueError(
                f"{self.name}: classes {unknown} in {base} are absent from "
                f"train_path (classes: {self.class_names})")
        labels = [index_of[names[l]] for l in local_labels]
        data = np.stack([decode_image(p, self.target_shape, self.grayscale)
                         for p in paths]) if paths else \
            np.zeros((0,) + self._sample_shape(), np.float32)
        return data.astype(np.float32), np.asarray(labels, np.int32)

    def _sample_shape(self):
        h, w = self.target_shape
        return (h, w) if self.grayscale else (h, w, 3)

    def load_data(self):
        assert self.train_path, f"{self.name}: train_path required"
        _, _, self.class_names = scan_class_dirs(self.train_path)
        test_d, test_l = self._load_split(self.test_path)
        valid_d, valid_l = self._load_split(self.valid_path)
        train_d, train_l = self._load_split(self.train_path)
        self.original_data.mem = np.concatenate(
            [test_d, valid_d, train_d], axis=0)
        self.original_labels.mem = np.concatenate(
            [test_l, valid_l, train_l], axis=0)
        self.class_lengths = [len(test_l), len(valid_l), len(train_l)]
        super().load_data()
