"""Pickled-array loaders (rebuild of ``veles/loader/pickles.py``).

``FullBatchPicklesLoader`` takes up to three pickle files (test/valid/train),
each containing either a ``(data, labels)`` tuple or a dict with ``data`` /
``labels`` arrays, and serves them as a resident dataset."""

from __future__ import annotations

import gzip
import pickle
from typing import Optional

import numpy as np

from znicz_tpu.loader.fullbatch import FullBatchLoader


def load_pickle(path: str):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        obj = pickle.load(f)
    if isinstance(obj, dict):
        return (np.asarray(obj["data"], np.float32),
                np.asarray(obj["labels"], np.int32))
    data, labels = obj
    return np.asarray(data, np.float32), np.asarray(labels, np.int32)


class FullBatchPicklesLoader(FullBatchLoader):
    def __init__(self, workflow=None, name=None, test_pickle=None,
                 valid_pickle=None, train_pickle=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.test_pickle = test_pickle
        self.valid_pickle = valid_pickle
        self.train_pickle = train_pickle

    def load_data(self):
        assert self.train_pickle, f"{self.name}: train_pickle required"
        splits = []
        for path in (self.test_pickle, self.valid_pickle, self.train_pickle):
            if path:
                splits.append(load_pickle(path))
            else:
                splits.append((None, None))
        sample_shape = splits[2][0].shape[1:]
        datas, labels, lengths = [], [], []
        for d, l in splits:
            if d is None:
                d = np.zeros((0,) + sample_shape, np.float32)
                l = np.zeros(0, np.int32)
            datas.append(d)
            labels.append(l)
            lengths.append(len(d))
        self.original_data.mem = np.concatenate(datas, axis=0)
        self.original_labels.mem = np.concatenate(labels, axis=0)
        self.class_lengths = lengths
        super().load_data()
