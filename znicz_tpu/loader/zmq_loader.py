"""External-process feed (rebuild of ``veles/zmq_loader.py``): a loader-like
unit that PULLs pickled minibatch dicts from a ZeroMQ socket, for pipelines
where another process produces the data (the reference's streaming mode).

Message format (pickled dict): ``{"data": ndarray, "labels": ndarray|None,
"class": 0|1|2, "size": int, "last": bool}``.  A ``{"end": True}`` message
marks end-of-stream (sets ``finished``)."""

from __future__ import annotations

import pickle
from typing import Optional

from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Array


class ZeroMQLoader(Unit):
    def __init__(self, workflow=None, name=None,
                 endpoint="tcp://127.0.0.1:5555", bind=True,
                 recv_timeout=30.0, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.endpoint = endpoint
        self.bind = bool(bind)
        self.recv_timeout = float(recv_timeout)   # seconds; feeder-death guard
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_class = TRAIN
        self.minibatch_size = 0
        self.last_minibatch = False
        # full Loader attribute surface so DecisionBase links work
        # (class_lengths is unknown for a stream — senders may set it via
        # the optional "class_lengths" field of any message)
        self.class_ended = False
        self.epoch_ended = False
        self.epoch_number = 0
        self.class_lengths = [0, 0, 0]
        self.finished = False
        self._socket = None
        self._context = None

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        import zmq

        self._context = zmq.Context.instance()
        self._socket = self._context.socket(zmq.PULL)
        self._socket.setsockopt(zmq.RCVTIMEO,
                                int(self.recv_timeout * 1000))
        self._socket.setsockopt(zmq.LINGER, 0)
        if self.bind:
            from znicz_tpu.network_common import bind_with_retry

            bind_with_retry(self._socket, self.endpoint)
        else:
            self._socket.connect(self.endpoint)
        for arr in (self.minibatch_data, self.minibatch_labels):
            arr.initialize(device)

    def run(self):
        if self.last_minibatch:
            self.epoch_number += 1
            self.last_minibatch = False
        self.epoch_ended = False
        import zmq

        try:
            msg = self._socket.recv()
        except zmq.Again:
            raise RuntimeError(
                f"{self.name}: no minibatch from {self.endpoint} within "
                f"{self.recv_timeout}s — feeder process dead or absent")
        rec = pickle.loads(msg)
        if rec.get("end"):
            self.finished = True
            self.last_minibatch = True
            self.epoch_ended = True
            self.class_ended = True
            return
        self.minibatch_data.mem = rec["data"]
        if rec.get("labels") is not None:
            self.minibatch_labels.mem = rec["labels"]
        self.minibatch_class = int(rec.get("class", TRAIN))
        self.minibatch_size = int(rec.get("size", len(rec["data"])))
        self.last_minibatch = bool(rec.get("last", False))
        self.class_ended = bool(rec.get("class_ended",
                                        self.last_minibatch))
        self.epoch_ended = self.last_minibatch
        if rec.get("class_lengths") is not None:
            self.class_lengths = [int(x) for x in rec["class_lengths"]]

    def stop(self):
        if self._socket is not None:
            self._socket.close(0)
            self._socket = None
