"""Host ingest engine: parallel image decode + bounded prefetch (VERDICT r4
item 1 — the file-fed north star was serial-PIL host-decode-bound).

The chip consumes ~11.4k img/s (bench headline); one PIL decode+resize on
the staging thread delivers a few hundred.  The reference's file-image
loaders (SURVEY.md §2.1 image-loaders row) existed precisely to feed
accelerators from disk at training rate, so the rebuild gets a real ingest
engine:

  - ``DecodePool``: an N-worker decode pool.  PIL's JPEG/PNG decode and
    resize release the GIL inside libjpeg/zlib, so threads scale to real
    multiples of the serial rate without shipping arrays across process
    boundaries (a process pool would pay a pickle+pipe copy per row).
  - A **bounded prefetch cache**: ``submit(indices)`` starts decode
    futures for rows a FUTURE segment will need; ``take(indices)`` serves
    the current segment — cache hits consume the already-running future,
    misses decode in the pool right then (still parallel).  Entries pop
    on consumption, and ``max_outstanding_rows`` caps memory, so the
    cache is a queue, not a leak.
  - The fused driver (``FusedTrainer._run_segmented``) keeps a lookahead
    fifo of advanced-but-unprocessed minibatches and submits their rows
    as soon as the indices are known — segment N+1's (and N+2's) decode
    overlaps segment N's device compute.  In a multi-controller run only
    the rows of batch shards this process's devices hold are submitted
    (the gather-own-rows-only property of ``_stage_direct`` extends to
    the prefetcher).

Decode is deterministic, so pooled results are BIT-IDENTICAL to serial
decode regardless of worker count or arrival order (tests/test_ingest.py).

Steady-state throughput becomes the three-term roofline

    img/s = min(compute rate, link_bw / bytes_per_sample, decode rate)

which ``bench.py --stream`` measures term by term (``measure_decode_rate``
below provides the decode term).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional

import numpy as np

#: default cap on cached + in-flight prefetched rows.  227x227x3 u8 rows
#: are ~151 KB, so 8192 rows bound the cache at ~1.2 GB — a few staged
#: segments' worth at bench shapes, far below host RAM.
DEFAULT_MAX_OUTSTANDING_ROWS = 8192


def default_workers() -> int:
    """Worker count when neither the source nor the config pins one:
    ``root.common.engine.decode_workers`` wins, else one thread per CPU
    (capped — decode threads beyond ~16 fight the staging thread for
    memory bandwidth before they add decode rate)."""
    from znicz_tpu.core.config import root

    cfg = root.common.engine.get("decode_workers", None)
    if cfg is not None:
        return int(cfg)
    return min(os.cpu_count() or 1, 16)


class DecodePool:
    """N-worker decode pool with a bounded prefetch cache.

    ``decode_row(i) -> np.ndarray`` decodes ONE row by global index; it
    must be pure (same i -> same bytes) — that is what makes pooled
    ingest bit-identical to serial decode.

    Threading contract (ISSUE 7): ``submit`` runs on the training
    thread (the fused driver's lookahead) while ``take`` may run on the
    ``DeviceStager`` worker — the futures dict is guarded by a lock;
    workers only ever run ``decode_row``.
    """

    def __init__(self, decode_row: Callable[[int], np.ndarray],
                 workers: Optional[int] = None,
                 max_outstanding_rows: int = DEFAULT_MAX_OUTSTANDING_ROWS):
        import threading

        self._decode_row = decode_row
        self._workers = workers
        self._ex = None
        self._futures: Dict[int, object] = {}
        self._lock = threading.Lock()
        self.max_outstanding_rows = int(max_outstanding_rows)
        #: prefetch_hits: take() rows served by an already-submitted
        #: future (the queue was non-empty when the segment arrived);
        #: decode_misses: rows the segment had to decode on demand
        self.stats = {"prefetch_hits": 0, "decode_misses": 0,
                      "rows_decoded": 0, "rows_prefetched": 0}

    @property
    def workers(self) -> int:
        if self._workers is None:
            self._workers = default_workers()
        return max(1, int(self._workers))

    def _executor(self):
        if self._ex is None:
            from concurrent.futures import ThreadPoolExecutor

            self._ex = ThreadPoolExecutor(
                self.workers, thread_name_prefix="znicz-decode")
        return self._ex

    def submit(self, indices) -> int:
        """Start decode futures for rows a future take() will consume.
        Already-cached rows are skipped; past ``max_outstanding_rows``
        the rest of the batch is dropped (the later take() decodes them
        on demand — prefetch is an optimization, never a requirement).
        Returns the number of rows newly submitted."""
        ex = self._executor()
        n = 0
        with self._lock:
            for i in np.unique(np.asarray(indices)):
                i = int(i)
                if i in self._futures:
                    continue
                if len(self._futures) >= self.max_outstanding_rows:
                    break
                self._futures[i] = ex.submit(self._decode_row, i)
                n += 1
            self.stats["rows_prefetched"] += n
        return n

    def take(self, indices) -> np.ndarray:
        """Rows for ``indices``, in order (duplicates allowed — padded
        tail minibatches repeat their last index).  Prefetched rows are
        consumed from the cache; the rest decode across the pool now."""
        ex = self._executor()
        local: Dict[int, object] = {}
        futs = []
        with self._lock:
            for i in np.asarray(indices).reshape(-1):
                i = int(i)
                f = local.get(i)
                if f is None:
                    f = self._futures.pop(i, None)
                    if f is None:
                        self.stats["decode_misses"] += 1
                        f = ex.submit(self._decode_row, i)
                    else:
                        self.stats["prefetch_hits"] += 1
                    local[i] = f
                futs.append(f)
            self.stats["rows_decoded"] += len(futs)
        rows = [f.result() for f in futs]
        return np.stack(rows)

    @property
    def outstanding_rows(self) -> int:
        with self._lock:
            return len(self._futures)

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None
        with self._lock:
            self._futures.clear()


class DeviceStager:
    """Async double-buffered device staging (ISSUE 7): background
    workers (one per buffer) run ``assemble(idx_rows) -> staged device
    tensors`` — host gather (decode-pool take), ``np.stack``, and the
    async ``device_put`` — for upcoming segments WHILE the current one
    computes, so the training thread's per-segment staging cost
    collapses to a dictionary pop.  With donation on (TPU — the trainer donates staged
    buffers into the scan) at most two staged segments exist at any
    moment: the one the device is consuming and the one being put — the
    serving layer's donated ping-pong pair, now feeding training.

    ``submit(idx_rows)`` starts staging a PREDICTED future segment
    (bounded at ``depth`` outstanding; extra submits are dropped —
    staging ahead is an optimization, never a requirement).  ``take(
    idx_rows)`` serves the segment about to be dispatched: a key match
    consumes the in-flight future (``stage_hits``; the blocking time is
    the ``ingest_wait_ms`` histogram — the number the overlap gate
    bounds), anything else assembles inline on the calling thread
    (``stage_misses``).

    Keys are the exact stacked index rows, so a mispredicted segment
    (decision completed early, scan boundary moved) can never serve
    wrong data — it is simply dropped and the real one assembled
    inline.  Assembly is pure data work (gather + put — no RNG, no
    loader state), so concurrent assemblies cannot reorder anything
    observable; ``close`` drops pending work without waiting."""

    def __init__(self, assemble: Callable[[np.ndarray], tuple],
                 depth: int = 2):
        from znicz_tpu import telemetry

        self._assemble = assemble
        self.depth = max(1, int(depth))
        self._ex = None
        self._pending: Dict[bytes, object] = {}   # key -> Future
        self._stale: set = set()    # pending keys marked at the last miss
        _sc = telemetry.scope("ingest")
        self._tracer = telemetry.tracer()
        #: the training thread's blocking time per take() — the overlap
        #: gate's subject (bench.py --ingest): with the double buffer
        #: absorbing an injected decode delay this stays well under it
        self._m_wait_ms = _sc.histogram(
            "ingest_wait_ms", "training-thread wait per staged segment "
            "(ms); the --ingest overlap gate bounds this", size=2048)
        #: worker-side assemble+put time (host gather through device_put
        #: dispatch) — where a decode/link stall actually shows up
        self._m_h2d_ms = _sc.histogram(
            "h2d_copy_ms", "host gather + device_put dispatch per staged "
            "segment (ms), measured on the stager worker", size=2048)
        self._m_occupancy = _sc.gauge(
            "staging_occupancy", "staged segments in flight or ready "
            "(ping-pong bound: depth)")
        self._m_hits = _sc.counter(
            "stage_hits", "take() segments served by a background-staged "
            "future")
        self._m_misses = _sc.counter(
            "stage_misses", "take() segments assembled inline (not "
            "predicted, or capacity-dropped)")
        self._m_evictions = _sc.counter(
            "stage_evictions", "pending predictions dropped on a take() "
            "miss (stale — their slot and buffers are reclaimed)")

    @staticmethod
    def key_of(idx_rows) -> bytes:
        """Hashable identity of a segment: the exact stacked index rows
        (small int32 matrices — hashing is microseconds)."""
        mat = np.stack([np.asarray(r, np.int32) for r in idx_rows])
        return mat.shape[0].to_bytes(4, "little") + mat.tobytes()

    def _executor(self):
        if self._ex is None:
            from concurrent.futures import ThreadPoolExecutor

            # one worker PER buffer: the dispatch loop runs ahead of
            # device compute, so adjacent segments' assemblies must be
            # able to overlap each other, not just the compute
            self._ex = ThreadPoolExecutor(
                self.depth, thread_name_prefix="znicz-stage")
        return self._ex

    def _timed_assemble(self, idx_rows):
        t0 = time.perf_counter()
        out = self._assemble(idx_rows)
        dt = time.perf_counter() - t0
        self._m_h2d_ms.observe(dt * 1e3)
        if self._tracer.enabled:
            self._tracer.add("ingest", "stage", t0, dt,
                             {"steps": len(idx_rows)})
        return out

    def submit(self, idx_rows) -> bool:
        """Start staging a predicted segment; False when already pending
        or the ping-pong is full."""
        key = self.key_of(idx_rows)
        if key in self._pending or len(self._pending) >= self.depth:
            return False
        self._pending[key] = self._executor().submit(
            self._timed_assemble, list(idx_rows))
        self._m_occupancy.set(len(self._pending))
        return True

    def take(self, idx_rows):
        """The staged tensors for EXACTLY these index rows — from the
        in-flight future when predicted, assembled inline otherwise.  A
        pending prediction that survives from one miss to the NEXT miss
        is stale and gets evicted — a hot loop serves predictions within
        a take or two, so anything a full miss-to-miss interval old was
        predicted wrong and would otherwise pin its ping-pong slot (and
        staged device buffers) forever.  (Eviction must NOT fire on the
        first miss alone: the cold-start take legitimately misses while
        CORRECT predictions for the next groups sit pending.)"""
        key = self.key_of(idx_rows)
        fut = self._pending.pop(key, None)
        if fut is None:
            stale = self._stale & set(self._pending)
            for k in stale:
                del self._pending[k]
            if stale:
                self._m_evictions.inc(len(stale))
            self._stale = set(self._pending)
            self._m_occupancy.set(len(self._pending))
            self._m_misses.inc()
            return self._timed_assemble(idx_rows)
        self._stale.discard(key)
        self._m_occupancy.set(len(self._pending))
        self._m_hits.inc()
        t0 = time.perf_counter()
        out = fut.result()
        dt = time.perf_counter() - t0
        self._m_wait_ms.observe(dt * 1e3)
        if self._tracer.enabled:
            self._tracer.add("ingest", "wait", t0, dt,
                             {"steps": len(idx_rows)})
        return out

    @property
    def outstanding(self) -> int:
        return len(self._pending)

    def stats(self) -> Dict[str, float]:
        waits = self._m_wait_ms.window()
        return {"stage_hits": self._m_hits.value,
                "stage_misses": self._m_misses.value,
                "stage_evictions": self._m_evictions.value,
                "outstanding": len(self._pending),
                "wait_ms_p50": self._m_wait_ms.quantile(0.5),
                "wait_ms_max": (float(np.max(waits)) if len(waits)
                                else None),
                "wait_ms_window": [round(float(w), 3) for w in waits],
                "h2d_ms_p50": self._m_h2d_ms.quantile(0.5)}

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None
        self._pending.clear()
        self._stale.clear()
        self._m_occupancy.set(0)


def measure_decode_rate(source, n: int = 256,
                        workers: Optional[int] = None) -> float:
    """Measured decode throughput (img/s) of a file-backed source — the
    third roofline term for ``bench.py --stream``.  Decodes ``n`` rows
    through the source's own gather path (pooled when the source has a
    pool, serial otherwise) and times it cold-cache-fair: the same rows
    are decoded twice and the SECOND pass is timed, so the OS page cache
    state matches steady training (epochs revisit files)."""
    n = min(int(n), len(source))
    idx = np.arange(n, dtype=np.int32)
    if workers is not None and hasattr(source, "with_workers"):
        source = source.with_workers(workers)
    source.gather(idx)                      # warm page cache + pool
    t0 = time.perf_counter()
    source.gather(idx)
    return n / max(time.perf_counter() - t0, 1e-9)
