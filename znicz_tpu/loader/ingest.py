"""Host ingest engine: parallel image decode + bounded prefetch (VERDICT r4
item 1 — the file-fed north star was serial-PIL host-decode-bound).

The chip consumes ~11.4k img/s (bench headline); one PIL decode+resize on
the staging thread delivers a few hundred.  The reference's file-image
loaders (SURVEY.md §2.1 image-loaders row) existed precisely to feed
accelerators from disk at training rate, so the rebuild gets a real ingest
engine:

  - ``DecodePool``: an N-worker decode pool.  PIL's JPEG/PNG decode and
    resize release the GIL inside libjpeg/zlib, so threads scale to real
    multiples of the serial rate without shipping arrays across process
    boundaries (a process pool would pay a pickle+pipe copy per row).
  - A **bounded prefetch cache**: ``submit(indices)`` starts decode
    futures for rows a FUTURE segment will need; ``take(indices)`` serves
    the current segment — cache hits consume the already-running future,
    misses decode in the pool right then (still parallel).  Entries pop
    on consumption, and ``max_outstanding_rows`` caps memory, so the
    cache is a queue, not a leak.
  - The fused driver (``FusedTrainer._run_segmented``) keeps a lookahead
    fifo of advanced-but-unprocessed minibatches and submits their rows
    as soon as the indices are known — segment N+1's (and N+2's) decode
    overlaps segment N's device compute.  In a multi-controller run only
    the rows of batch shards this process's devices hold are submitted
    (the gather-own-rows-only property of ``_stage_direct`` extends to
    the prefetcher).

Decode is deterministic, so pooled results are BIT-IDENTICAL to serial
decode regardless of worker count or arrival order (tests/test_ingest.py).

Steady-state throughput becomes the three-term roofline

    img/s = min(compute rate, link_bw / bytes_per_sample, decode rate)

which ``bench.py --stream`` measures term by term (``measure_decode_rate``
below provides the decode term).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Optional

import numpy as np

#: default cap on cached + in-flight prefetched rows.  227x227x3 u8 rows
#: are ~151 KB, so 8192 rows bound the cache at ~1.2 GB — a few staged
#: segments' worth at bench shapes, far below host RAM.
DEFAULT_MAX_OUTSTANDING_ROWS = 8192


def default_workers() -> int:
    """Worker count when neither the source nor the config pins one:
    ``root.common.engine.decode_workers`` wins, else one thread per CPU
    (capped — decode threads beyond ~16 fight the staging thread for
    memory bandwidth before they add decode rate)."""
    from znicz_tpu.core.config import root

    cfg = root.common.engine.get("decode_workers", None)
    if cfg is not None:
        return int(cfg)
    return min(os.cpu_count() or 1, 16)


class DecodePool:
    """N-worker decode pool with a bounded prefetch cache.

    ``decode_row(i) -> np.ndarray`` decodes ONE row by global index; it
    must be pure (same i -> same bytes) — that is what makes pooled
    ingest bit-identical to serial decode.

    Threading contract: ``submit``/``take`` are called from the staging
    (main) thread only; workers only ever run ``decode_row``.  The
    futures dict therefore needs no lock.
    """

    def __init__(self, decode_row: Callable[[int], np.ndarray],
                 workers: Optional[int] = None,
                 max_outstanding_rows: int = DEFAULT_MAX_OUTSTANDING_ROWS):
        self._decode_row = decode_row
        self._workers = workers
        self._ex = None
        self._futures: Dict[int, object] = {}
        self.max_outstanding_rows = int(max_outstanding_rows)
        #: prefetch_hits: take() rows served by an already-submitted
        #: future (the queue was non-empty when the segment arrived);
        #: decode_misses: rows the segment had to decode on demand
        self.stats = {"prefetch_hits": 0, "decode_misses": 0,
                      "rows_decoded": 0, "rows_prefetched": 0}

    @property
    def workers(self) -> int:
        if self._workers is None:
            self._workers = default_workers()
        return max(1, int(self._workers))

    def _executor(self):
        if self._ex is None:
            from concurrent.futures import ThreadPoolExecutor

            self._ex = ThreadPoolExecutor(
                self.workers, thread_name_prefix="znicz-decode")
        return self._ex

    def submit(self, indices) -> int:
        """Start decode futures for rows a future take() will consume.
        Already-cached rows are skipped; past ``max_outstanding_rows``
        the rest of the batch is dropped (the later take() decodes them
        on demand — prefetch is an optimization, never a requirement).
        Returns the number of rows newly submitted."""
        ex = self._executor()
        n = 0
        for i in np.unique(np.asarray(indices)):
            i = int(i)
            if i in self._futures:
                continue
            if len(self._futures) >= self.max_outstanding_rows:
                break
            self._futures[i] = ex.submit(self._decode_row, i)
            n += 1
        self.stats["rows_prefetched"] += n
        return n

    def take(self, indices) -> np.ndarray:
        """Rows for ``indices``, in order (duplicates allowed — padded
        tail minibatches repeat their last index).  Prefetched rows are
        consumed from the cache; the rest decode across the pool now."""
        ex = self._executor()
        local: Dict[int, object] = {}
        futs = []
        for i in np.asarray(indices).reshape(-1):
            i = int(i)
            f = local.get(i)
            if f is None:
                f = self._futures.pop(i, None)
                if f is None:
                    self.stats["decode_misses"] += 1
                    f = ex.submit(self._decode_row, i)
                else:
                    self.stats["prefetch_hits"] += 1
                local[i] = f
            futs.append(f)
        rows = [f.result() for f in futs]
        self.stats["rows_decoded"] += len(rows)
        return np.stack(rows)

    @property
    def outstanding_rows(self) -> int:
        return len(self._futures)

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=False, cancel_futures=True)
            self._ex = None
        self._futures.clear()


def measure_decode_rate(source, n: int = 256,
                        workers: Optional[int] = None) -> float:
    """Measured decode throughput (img/s) of a file-backed source — the
    third roofline term for ``bench.py --stream``.  Decodes ``n`` rows
    through the source's own gather path (pooled when the source has a
    pool, serial otherwise) and times it cold-cache-fair: the same rows
    are decoded twice and the SECOND pass is timed, so the OS page cache
    state matches steady training (epochs revisit files)."""
    n = min(int(n), len(source))
    idx = np.arange(n, dtype=np.int32)
    if workers is not None and hasattr(source, "with_workers"):
        source = source.with_workers(workers)
    source.gather(idx)                      # warm page cache + pool
    t0 = time.perf_counter()
    source.gather(idx)
    return n / max(time.perf_counter() - t0, 1e-9)
