"""FullBatchLoader: whole dataset resident, minibatches sliced by gather
(rebuild of ``veles/loader/fullbatch.py``).

TPU-native change: the reference kept the full batch in device memory and ran
a "copy minibatch" kernel; here the dataset lives in HBM as one jax array and
``fill_minibatch`` is a jitted ``jnp.take`` gather — no host↔device traffic
in the steady state (SURVEY.md guidance: minimise transfers).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from znicz_tpu.loader.base import Loader
from znicz_tpu.memory import Array


class FullBatchLoader(Loader):
    """Subclasses (or callers) provide the full dataset via ``original_data``
    / ``original_labels`` (numpy, sample-major) before initialize, or
    override ``load_data`` to fill them."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.original_data = Array()
        self.original_labels = Array()
        self.normalizer = kwargs.get("normalizer")
        self._gather = None

    def load_data(self) -> None:
        if self.original_data.mem is None:
            raise ValueError(f"{self.name}: original_data not set")
        if sum(self.class_lengths) == 0:
            # default: everything is TRAIN
            self.class_lengths = [0, 0, len(self.original_data)]
        if self.normalizer is not None:
            data = self.original_data.map_write()
            train_start = self.class_end_offsets[1]
            self.normalizer.fit(data[train_start:])
            self.normalizer.apply_inplace(data)

    def create_minibatch_data(self) -> None:
        sample_shape = self.original_data.shape[1:]
        self.minibatch_data.mem = np.zeros(
            (self.max_minibatch_size,) + tuple(sample_shape), np.float32)
        if self.original_labels.mem is not None:
            self.minibatch_labels.mem = np.zeros(
                self.max_minibatch_size,
                self.original_labels.mem.dtype)

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self.original_data.initialize(device)
        self.original_labels.initialize(device)

    def train_labels(self):
        return (self.original_labels.mem
                if self.original_labels.mem is not None else None)

    def fill_minibatch(self) -> None:
        if self._gather is None:
            import jax

            self._gather = jax.jit(
                lambda data, idx: jax.numpy.take(data, idx, axis=0))
        idx = self.minibatch_indices.devmem
        self.minibatch_data.devmem = self._gather(
            self.original_data.devmem, idx)
        if self.original_labels.mem is not None:
            self.minibatch_labels.devmem = self._gather(
                self.original_labels.devmem, idx)


class FullBatchLoaderMSE(FullBatchLoader):
    """Adds per-sample regression targets (``original_targets``); for
    autoencoders targets default to the input data itself."""

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.original_targets = Array()
        self.minibatch_targets = Array()
        self.targets_from_data = kwargs.get("targets_from_data", False)

    def load_data(self) -> None:
        super().load_data()
        if self.original_targets.mem is None:
            if not self.targets_from_data:
                raise ValueError(
                    f"{self.name}: original_targets not set "
                    "(pass targets_from_data=True for autoencoders)")
            self.original_targets.mem = self.original_data.mem

    def create_minibatch_data(self) -> None:
        super().create_minibatch_data()
        self.minibatch_targets.mem = np.zeros(
            (self.max_minibatch_size,) + tuple(self.original_targets.shape[1:]),
            np.float32)

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self.original_targets.initialize(device)
        self.minibatch_targets.initialize(device)

    def fill_minibatch(self) -> None:
        super().fill_minibatch()
        self.minibatch_targets.devmem = self._gather(
            self.original_targets.devmem, self.minibatch_indices.devmem)
