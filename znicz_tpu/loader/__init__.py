from znicz_tpu.loader.base import Loader, TEST, VALID, TRAIN  # noqa: F401
from znicz_tpu.loader.fullbatch import (  # noqa: F401
    FullBatchLoader,
    FullBatchLoaderMSE,
)
