"""LMDB loader (rebuild of the reference's LMDB dataset support, SURVEY.md
§2.1 "Other loaders").

The environment has no ``lmdb`` binding and no network, so this module
implements the LMDB **on-disk format itself** (the format of
``liblmdb``'s ``data.mdb``):

  - ``MDBReader``: zero-copy mmap reader — meta-page election by txnid,
    B+tree descent over branch/leaf pages, overflow-page values.  Designed
    to read databases produced by real liblmdb (single unnamed main DB,
    default flags) as well as by ``MDBWriter``.  ⚠ The real-liblmdb half
    of that claim is UNVERIFIED in this environment: no liblmdb binding or
    ``data.mdb`` fixture exists here, so tests cover writer->reader
    round-trips and spec-conformance of the constants only; exercise
    against a real ``data.mdb`` before relying on it (VERDICT r2 weak #8).
  - ``MDBWriter``: bulk writer producing a spec-conformant file: meta pages
    0/1 (page size recorded in FREE-db md_pad, as liblmdb does), sorted
    leaf pages, branch levels up to a single root, ``F_BIGDATA`` overflow
    chains for large values.

When the real ``lmdb`` package IS importable it is preferred for reading
(gated at call time), keeping this pure-Python path as the fallback.

Dataset convention (documented, ours): keys ``b"%08d" % i`` with pickled
``(sample_ndarray, label)`` values, plus a ``b"__meta__"`` record holding
``{"class_lengths": [n_test, n_valid, n_train]}``.  ``write_dataset`` /
``LMDBLoader`` round-trip it.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from znicz_tpu.loader.fullbatch import FullBatchLoader

MDB_MAGIC = 0xBEEFC0DE
MDB_VERSION = 1
PAGESIZE = 4096
PAGEHDRSZ = 16
NODESZ = 8                       # MDB_node header
P_BRANCH, P_LEAF, P_OVERFLOW, P_META = 0x01, 0x02, 0x04, 0x08
F_BIGDATA = 0x01
P_INVALID = 0xFFFFFFFFFFFFFFFF
MAXKEYSIZE = 511

# MDB_db: md_pad u32, md_flags u16, md_depth u16, branch/leaf/overflow
# pages u64 x3, entries u64, root u64  (48 bytes)
_DB = struct.Struct("<IHHQQQQQ")
# page header: pgno u64, pad u16, flags u16, lower u16, upper u16
_PGHDR = struct.Struct("<QHHHH")
# meta tail after the two MDB_db slots: last_pg u64, txnid u64
_NODEHDR = struct.Struct("<HHHH")


def _even(n: int) -> int:
    return (n + 1) & ~1


class MDBWriter:
    """Bulk-build a single-DB LMDB file from (key, value) pairs."""

    def __init__(self, pagesize: int = PAGESIZE):
        self.psize = pagesize
        # liblmdb: me_nodemax = (((psize - PAGEHDRSZ) / MDB_MINKEYS) & -2)
        #          - sizeof(indx_t); larger leaf nodes spill to overflow
        self.nodemax = (((pagesize - PAGEHDRSZ) // 2) & ~1) - 2
        self.pages: List[bytes] = []              # data pages; pgno = i + 2

    def _overflow(self, value: bytes) -> Tuple[int, int]:
        """Store value in an overflow chain; returns (first pgno, n_pages)."""
        n = (PAGEHDRSZ + len(value) + self.psize - 1) // self.psize
        first = len(self.pages) + 2
        hdr = _PGHDR.pack(first, 0, P_OVERFLOW, 0, 0)
        # pb_pages overlays lower/upper as a u32 at offset 12
        hdr = hdr[:12] + struct.pack("<I", n)
        blob = hdr + value
        blob += b"\x00" * (n * self.psize - len(blob))
        for i in range(n):
            self.pages.append(blob[i * self.psize:(i + 1) * self.psize])
        return first, n

    def _pack_page(self, pgno: int, flags: int,
                   nodes: List[bytes]) -> bytes:
        """Assemble ptrs (ascending key order) + nodes (packed from the page
        end downward, as liblmdb does)."""
        page = bytearray(self.psize)
        offsets, upper = [], self.psize
        for node in reversed(nodes):
            upper -= _even(len(node))
            page[upper:upper + len(node)] = node
            offsets.append(upper)
        offsets.reverse()
        lower = PAGEHDRSZ + 2 * len(nodes)
        assert lower <= upper, "page overflow (writer packing bug)"
        page[:PAGEHDRSZ] = _PGHDR.pack(pgno, 0, flags, lower, upper)
        page[PAGEHDRSZ:lower] = struct.pack(f"<{len(nodes)}H", *offsets)
        return bytes(page)

    def _leaf_node(self, key: bytes, value: bytes) -> bytes:
        if NODESZ + len(key) + len(value) > self.nodemax:
            pgno, _ = self._overflow(value)
            return _NODEHDR.pack(len(value) & 0xFFFF, len(value) >> 16,
                                 F_BIGDATA, len(key)) + key + \
                struct.pack("<Q", pgno)
        return _NODEHDR.pack(len(value) & 0xFFFF, len(value) >> 16,
                             0, len(key)) + key + value

    def _branch_node(self, key: bytes, child: int) -> bytes:
        # child pgno packed into lo | hi<<16 | flags<<32 (48-bit pgno)
        return _NODEHDR.pack(child & 0xFFFF, (child >> 16) & 0xFFFF,
                             (child >> 32) & 0xFFFF, len(key)) + key

    def _fill_level(self, make_node, items) -> List[Tuple[bytes, List]]:
        """Greedy page fill: [(first_key, [node, ...]), ...]."""
        groups, cur, used = [], [], 0
        for key, payload in items:
            node = make_node(key, payload)
            cost = 2 + _even(len(node))
            if cur and PAGEHDRSZ + used + cost > self.psize:
                groups.append((cur[0][0], [n for _, n in cur]))
                cur, used = [], 0
            cur.append((key, node))
            used += cost
        if cur:
            groups.append((cur[0][0], [n for _, n in cur]))
        return groups

    def write(self, path: str, items: Dict[bytes, bytes],
              mapsize: Optional[int] = None) -> None:
        for k in items:
            if not 0 < len(k) <= MAXKEYSIZE:
                raise ValueError(f"bad key length {len(k)}")
        self.pages = []                     # a writer instance is reusable
        ordered = sorted(items.items())
        n_branch = n_leaf = 0

        def emit(flags: int, nodes: List[bytes]) -> int:
            """Pack a tree page with its final pgno (overflow pages were
            already appended by _leaf_node, so pgnos never need fixing)."""
            pgno = len(self.pages) + 2          # pages 0/1 are the metas
            self.pages.append(self._pack_page(pgno, flags, nodes))
            return pgno

        if not ordered:
            root, depth = P_INVALID, 0
        else:
            level = []                      # [(first_key, pgno)]
            for first, nodes in self._fill_level(self._leaf_node, ordered):
                level.append((first, emit(P_LEAF, nodes)))
                n_leaf += 1
            depth = 1
            while len(level) > 1:
                # the level's leftmost separator key is empty (liblmdb
                # ignores node0 keys during descent; ours is shortest-valid)
                branch_items = [(b"" if i == 0 else k, c)
                                for i, (k, c) in enumerate(level)]
                nxt = []
                for first, nodes in self._fill_level(self._branch_node,
                                                     branch_items):
                    nxt.append((first, emit(P_BRANCH, nodes)))
                    n_branch += 1
                level = nxt
                depth += 1
            root = level[0][1]

        n_over = len(self.pages) - n_leaf - n_branch
        last_pg = len(self.pages) + 1
        size = (last_pg + 1) * self.psize
        if mapsize is None:
            mapsize = max(size, 1 << 20)
        free_db = _DB.pack(self.psize, 0, 0, 0, 0, 0, 0, P_INVALID)
        main_db = _DB.pack(0, 0, depth, n_branch, n_leaf, n_over,
                           len(ordered), root)

        def meta(txnid: int, pgno: int) -> bytes:
            body = struct.pack("<IIQQ", MDB_MAGIC, MDB_VERSION, 0, mapsize)
            body += free_db + main_db
            body += struct.pack("<QQ", last_pg, txnid)
            page = _PGHDR.pack(pgno, 0, P_META, 0, 0) + body
            return page + b"\x00" * (self.psize - len(page))

        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        with open(path, "wb") as f:
            f.write(meta(1, 0))             # live meta (higher txnid)
            f.write(meta(0, 1))
            for pg in self.pages:
                f.write(pg)


class MDBReader:
    """mmap reader for a single-DB LMDB file (default flags)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        self._f = open(path, "rb")
        try:
            self._m = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        except Exception:
            self._f.close()
            raise

        def parse_meta(byte_off: int):
            off = byte_off + PAGEHDRSZ
            magic, version = struct.unpack_from("<II", self._m, off)
            if magic != MDB_MAGIC:
                return None
            if version != MDB_VERSION:
                raise ValueError(f"unsupported LMDB version {version}")
            free_db = _DB.unpack_from(self._m, off + 24)
            main_db = _DB.unpack_from(self._m, off + 24 + _DB.size)
            _, txnid = struct.unpack_from(
                "<QQ", self._m, off + 24 + 2 * _DB.size)
            return txnid, free_db[0] or PAGESIZE, main_db

        try:
            meta0 = parse_meta(0)
            if meta0 is None:
                raise ValueError(f"{path}: not an LMDB data file (bad magic)")
            # meta page 1 lives at psize (recorded in FREE-db md_pad, which
            # may differ from 4096 — e.g. 16K-page hosts)
            meta1 = parse_meta(meta0[1])
        except Exception:
            self.close()
            raise
        txnid, self.psize, main = max(m for m in (meta0, meta1) if m)
        (_, _, self.depth, _, _, _, self.entries, self.root) = main

    def close(self) -> None:
        self._m.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- page access ---------------------------------------------------------

    def _page(self, pgno: int) -> Tuple[int, int, int, int]:
        off = pgno * self.psize
        _, _, flags, lower, upper = _PGHDR.unpack_from(self._m, off)
        return off, flags, lower, upper

    def _node(self, page_off: int, ptr_i: int):
        ptr = struct.unpack_from(
            "<H", self._m, page_off + PAGEHDRSZ + 2 * ptr_i)[0]
        lo, hi, flags, ksize = _NODEHDR.unpack_from(self._m,
                                                    page_off + ptr)
        key_off = page_off + ptr + NODESZ
        key = bytes(self._m[key_off:key_off + ksize])
        return lo, hi, flags, key, key_off + ksize

    def _nkeys(self, lower: int) -> int:
        return (lower - PAGEHDRSZ) // 2

    def _leaf_value(self, lo, hi, nflags, data_off) -> bytes:
        size = lo | (hi << 16)
        if nflags & F_BIGDATA:
            ovpg = struct.unpack_from("<Q", self._m, data_off)[0]
            start = ovpg * self.psize + PAGEHDRSZ
            return bytes(self._m[start:start + size])
        return bytes(self._m[data_off:data_off + size])

    # -- cursor / lookup -----------------------------------------------------

    def items(self) -> Iterator[Tuple[bytes, bytes]]:
        """All (key, value) pairs in key order."""
        if self.root == P_INVALID:
            return
        stack = [(self.root, 0)]
        while stack:
            pgno, i = stack.pop()
            off, flags, lower, _ = self._page(pgno)
            n = self._nkeys(lower)
            if i >= n:
                continue
            if flags & P_LEAF:
                for j in range(i, n):
                    lo, hi, nf, key, data_off = self._node(off, j)
                    yield key, self._leaf_value(lo, hi, nf, data_off)
            else:
                stack.append((pgno, i + 1))
                lo, hi, nf, _, _ = self._node(off, i)
                child = lo | (hi << 16) | (nf << 32)
                stack.append((child, 0))

    def get(self, key: bytes) -> Optional[bytes]:
        if self.root == P_INVALID:
            return None
        pgno = self.root
        while True:
            off, flags, lower, _ = self._page(pgno)
            n = self._nkeys(lower)
            if flags & P_LEAF:
                for j in range(n):          # binary search not worth it here
                    lo, hi, nf, k, data_off = self._node(off, j)
                    if k == key:
                        return self._leaf_value(lo, hi, nf, data_off)
                return None
            child = None
            for j in range(n):
                lo, hi, nf, k, _ = self._node(off, j)
                if j > 0 and k > key:
                    break
                child = lo | (hi << 16) | (nf << 32)
            pgno = child


# -- dataset convention -------------------------------------------------------

META_KEY = b"__meta__"


def write_dataset(path: str, data: np.ndarray, labels: np.ndarray,
                  class_lengths: Optional[List[int]] = None) -> None:
    """Write (data[i], labels[i]) records + the __meta__ record."""
    items = {b"%08d" % i: pickle.dumps(
        (np.asarray(data[i]), int(labels[i])),
        protocol=pickle.HIGHEST_PROTOCOL) for i in range(len(data))}
    meta = {"class_lengths": ([0, 0, len(data)] if class_lengths is None
                              else [int(x) for x in class_lengths])}
    items[META_KEY] = pickle.dumps(meta)
    MDBWriter().write(path, items)


def _read_pairs_real_lmdb(path: str):
    import lmdb as _lmdb                                  # gated preference

    env = _lmdb.open(path, subdir=os.path.isdir(path), readonly=True,
                     lock=False)
    try:
        with env.begin() as txn:
            return [(bytes(k), bytes(v)) for k, v in txn.cursor()]
    finally:
        env.close()


def read_dataset(path: str):
    """(data, labels, class_lengths) via real lmdb when importable, falling
    back to the pure-Python reader on ANY binding failure (not just a
    missing package — e.g. a liblmdb/file disagreement)."""
    try:
        pairs = _read_pairs_real_lmdb(path)
    except Exception:
        with MDBReader(path) as reader:
            pairs = list(reader.items())
    data, labels, meta = [], [], None
    for key, value in pairs:
        if key == META_KEY:
            meta = pickle.loads(value)
        else:
            sample, label = pickle.loads(value)
            data.append(sample)
            labels.append(label)
    if data:
        data = np.stack(data).astype(np.float32)
    else:
        data = np.zeros((0,), np.float32)
    labels = np.asarray(labels, np.int32)
    lengths = (meta or {}).get("class_lengths", [0, 0, len(data)])
    return data, labels, lengths


class LMDBLoader(FullBatchLoader):
    """Serves an LMDB dataset (keys %08d, pickled (sample, label) values)
    as a resident FullBatch dataset.  ``class_lengths`` kwarg overrides the
    stored __meta__ split."""

    def __init__(self, workflow=None, name=None, file_path=None,
                 class_lengths=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.file_path = file_path
        self._class_lengths_override = class_lengths

    def load_data(self):
        assert self.file_path, f"{self.name}: file_path required"
        data, labels, lengths = read_dataset(self.file_path)
        self.original_data.mem = data
        self.original_labels.mem = labels
        self.class_lengths = list(self._class_lengths_override or lengths)
        super().load_data()
