"""Minibatch stream capture/replay (rebuild of ``veles/loader/saver.py``).

``MinibatchesSaver`` is a unit linked after any loader: it appends every
served minibatch (data/labels/class/size) to a gzip pickle stream.
``MinibatchesLoader`` replays such a file as a loader-compatible unit —
the reference used this to freeze a preprocessing pipeline's output and
retrain without the original dataset."""

from __future__ import annotations

import gzip
import pickle
from typing import List, Optional

import numpy as np

from znicz_tpu.core.units import Unit
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Array


class MinibatchesSaver(Unit):
    def __init__(self, workflow=None, name=None, file_path="minibatches.pgz",
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.file_path = file_path
        # linked from the loader:
        self.minibatch_data: Optional[Array] = None
        self.minibatch_labels: Optional[Array] = None
        self.minibatch_class = TRAIN
        self.minibatch_size = 0
        self._file = None

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self._file = gzip.open(self.file_path, "wb")

    def run(self):
        rec = {
            "data": np.array(self.minibatch_data.map_read()),
            "labels": (np.array(self.minibatch_labels.map_read())
                       if self.minibatch_labels else None),
            "class": int(self.minibatch_class),
            "size": int(self.minibatch_size),
        }
        pickle.dump(rec, self._file, protocol=pickle.HIGHEST_PROTOCOL)

    def stop(self):
        if self._file is not None:
            self._file.close()
            self._file = None


class MinibatchesLoader(Unit):
    """Replays a saved minibatch stream; exposes the Loader attribute
    surface (minibatch_data/labels/class/size, last_minibatch,
    epoch_number) so forwards/evaluators link against it unchanged."""

    def __init__(self, workflow=None, name=None, file_path="minibatches.pgz",
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.file_path = file_path
        self.records: List[dict] = []
        self._pos = 0
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_class = TRAIN
        self.minibatch_size = 0
        self.last_minibatch = False
        self.class_ended = False
        self.epoch_number = 0
        self.epoch_ended = False
        self.class_lengths = [0, 0, 0]

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self.records = []
        with gzip.open(self.file_path, "rb") as f:
            while True:
                try:
                    self.records.append(pickle.load(f))
                except EOFError:
                    break
        if not self.records:
            raise ValueError(f"{self.name}: empty minibatch stream")
        for rec in self.records:
            self.class_lengths[rec["class"]] += rec["size"]
        for arr in (self.minibatch_data, self.minibatch_labels):
            arr.initialize(device)

    def run(self):
        if self.last_minibatch:
            self._pos = 0
            self.epoch_number += 1
            self.last_minibatch = False
        self.epoch_ended = False
        rec = self.records[self._pos]
        self.minibatch_data.mem = rec["data"]
        if rec["labels"] is not None:
            self.minibatch_labels.mem = rec["labels"]
        self.minibatch_class = rec["class"]
        self.minibatch_size = rec["size"]
        self._pos += 1
        self.last_minibatch = (self._pos == len(self.records))
        self.epoch_ended = self.last_minibatch
        nxt = self.records[self._pos] if self._pos < len(self.records) \
            else None
        self.class_ended = (nxt is None
                            or nxt["class"] != self.minibatch_class)
