"""HDF5 loader (rebuild of ``veles/loader/hdf5.py``): serves an .h5/.hdf5
file with datasets ``data`` and ``labels`` plus optional attrs/datasets
``class_lengths`` ([test, valid, train]; default: all TRAIN)."""

from __future__ import annotations

import numpy as np

from znicz_tpu.loader.fullbatch import FullBatchLoader


class HDF5Loader(FullBatchLoader):
    def __init__(self, workflow=None, name=None, file_path=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.file_path = file_path

    def load_data(self):
        assert self.file_path, f"{self.name}: file_path required"
        import h5py

        with h5py.File(self.file_path, "r") as f:
            self.original_data.mem = np.asarray(f["data"], np.float32)
            if "labels" in f:
                self.original_labels.mem = np.asarray(f["labels"], np.int32)
            if "class_lengths" in f:
                self.class_lengths = [int(x) for x in f["class_lengths"][:]]
            elif "class_lengths" in f.attrs:
                self.class_lengths = [int(x)
                                      for x in f.attrs["class_lengths"]]
        super().load_data()
