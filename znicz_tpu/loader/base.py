"""Loader base: the epoch/minibatch state machine (rebuild of
``veles/loader/base.py``, SURVEY.md §2.1 "Loader base").

Reference semantics preserved:
  - three sample classes TEST=0, VALID=1, TRAIN=2 with ``class_lengths``;
  - one epoch = one full pass over test, then valid, then train;
  - minibatches never straddle class boundaries; the tail minibatch of a
    class is short (``minibatch_size < max_minibatch_size``) and consumers
    mask by ``minibatch_size`` (the reference padded instead — same math,
    masking is the jit-friendly form since buffer shapes stay static);
  - only the TRAIN segment is reshuffled, once per epoch, from the seeded
    "loader" PRNG stream;
  - ``last_minibatch`` marks the end of an epoch, ``class_ended`` the end of
    a class segment; ``epoch_number`` increments when the next epoch begins.

Subclasses implement ``load_data()`` (set class_lengths, allocate) and
``fill_minibatch()`` (write minibatch_data/labels for ``minibatch_indices``).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.units import Unit
from znicz_tpu.memory import Array

TEST, VALID, TRAIN = 0, 1, 2


class Loader(Unit):
    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.max_minibatch_size = int(kwargs.get("minibatch_size", 100))
        self.shuffle = kwargs.get("shuffle", True)
        #: reference parity (SURVEY §2.1 Loader base "class balancing"):
        #: resample each epoch's TRAIN segment so every label gets an
        #: equal share of slots (minorities oversampled with
        #: replacement); needs a subclass that knows labels
        self.balance_classes = kwargs.get("balance_classes", False)
        #: use the C++ xorshift128+ shuffler (native/znicz_native.cpp) —
        #: the reference's RNG family; opt-in because it changes the
        #: shuffle sequence vs the default numpy prng stream
        self.native_shuffle = kwargs.get(
            "native_shuffle", None)
        self._native_rng = None
        self.class_lengths: List[int] = [0, 0, 0]
        self.minibatch_data = Array()
        self.minibatch_labels = Array()
        self.minibatch_indices = Array()
        self.minibatch_size = 0
        self.minibatch_class = TRAIN
        self.minibatch_offset = 0
        self.last_minibatch = False
        self.class_ended = False
        self.epoch_number = 0
        self.epoch_ended = False
        self._shuffled_indices: Optional[np.ndarray] = None
        self._pos = 0
        self.samples_served = 0
        #: fast path for fused training: run() advances the index state
        #: machine but skips fill_minibatch (the fused step gathers on
        #: device from original_data itself, so filling minibatch_data is
        #: pure overhead — two extra dispatches per step)
        self.indices_only = False

    # -- derived geometry -----------------------------------------------------

    @property
    def total_samples(self) -> int:
        return int(sum(self.class_lengths))

    @property
    def class_end_offsets(self) -> List[int]:
        ends, acc = [], 0
        for n in self.class_lengths:
            acc += n
            ends.append(acc)
        return ends

    def class_of_offset(self, offset: int) -> int:
        for klass, end in enumerate(self.class_end_offsets):
            if offset < end:
                return klass
        raise ValueError(f"offset {offset} out of range")

    # -- subclass API ---------------------------------------------------------

    def load_data(self) -> None:
        """Set class_lengths and prepare storage.  Subclasses override."""
        raise NotImplementedError

    def create_minibatch_data(self) -> None:
        """Allocate minibatch buffers (called once, after load_data)."""
        raise NotImplementedError

    def fill_minibatch(self) -> None:
        """Fill minibatch_data/labels for the current minibatch_indices
        (first ``minibatch_size`` entries valid)."""
        raise NotImplementedError

    # -- lifecycle ------------------------------------------------------------

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        self.load_data()
        if self.total_samples == 0:
            raise ValueError(f"{self.name}: empty dataset")
        if self.class_lengths[TRAIN] == 0:
            raise ValueError(f"{self.name}: no TRAIN samples")
        self._shuffled_indices = np.arange(self.total_samples, dtype=np.int32)
        self.create_minibatch_data()
        idx = np.zeros(self.max_minibatch_size, np.int32)
        self.minibatch_indices.mem = idx
        for arr in (self.minibatch_data, self.minibatch_labels,
                    self.minibatch_indices):
            arr.initialize(device)
        self._shuffle_train()

    def _use_native_shuffle(self) -> bool:
        if self.native_shuffle is not None:
            return bool(self.native_shuffle)
        from znicz_tpu.core.config import root

        return bool(root.common.engine.get("native_shuffle", False))

    def _shuffle_train(self) -> None:
        start = self.class_end_offsets[VALID]
        if self.shuffle:
            seg = self._shuffled_indices[start:]
            shuffled = False
            if self._use_native_shuffle():
                from znicz_tpu import native

                if native.available():
                    if self._native_rng is None:
                        self._native_rng = native.XorShift128P(
                            prng.get("loader").seed)
                    seg = np.ascontiguousarray(seg)
                    self._native_rng.shuffle(seg)
                    self._shuffled_indices[start:] = seg
                    shuffled = True
            if not shuffled:
                perm = prng.get("loader").permutation(len(seg))
                self._shuffled_indices[start:] = seg[perm]
        # balancing applies with or without shuffling (it places samples
        # at randomized slots itself)
        self._balance_train(start)

    def train_labels(self):
        """Labels for balancing, indexable by sample index; subclasses
        that know labels override (FullBatchLoader)."""
        return None

    def _balance_train(self, start: int) -> None:
        if not self.balance_classes:
            return
        labels = self.train_labels()
        if labels is None:
            return
        # ALWAYS resample from the canonical train population (the
        # contiguous sample ids [start, total)) — resampling from the
        # previous epoch's with-replacement output would lose ~37% of
        # distinct samples per epoch, compounding
        population = np.arange(start, self.total_samples,
                               dtype=self._shuffled_indices.dtype)
        lab = np.asarray(labels)[population]
        rng = prng.get("loader.balance").state
        classes = np.unique(lab)
        n = len(population)
        members = {c: population[lab == c] for c in classes}
        slots = rng.permutation(n)
        out = np.empty(n, population.dtype)
        i = 0
        for c, block in zip(classes,
                            np.array_split(np.arange(n), len(classes))):
            k = len(block)
            pick = members[c][rng.integers(0, len(members[c]), size=k)]
            out[slots[i:i + k]] = pick
            i += k
        self._shuffled_indices[start:] = out

    def reset(self) -> None:
        """Restart from epoch 0 (used by tests and the genetics driver);
        clears every state field __init__ sets."""
        self._pos = 0
        self.epoch_number = 0
        self.last_minibatch = False
        self.epoch_ended = False
        self.class_ended = False
        self.minibatch_size = 0
        self.minibatch_class = TRAIN
        self.minibatch_offset = 0
        self.samples_served = 0
        self._shuffled_indices = np.arange(self.total_samples, dtype=np.int32)
        self._shuffle_train()

    # -- the state machine ----------------------------------------------------

    def run(self):
        if self.last_minibatch:
            # previous run served the epoch tail -> begin the next epoch
            self._pos = 0
            self.epoch_number += 1
            self.last_minibatch = False
            self._shuffle_train()
        self.epoch_ended = False
        klass = self.class_of_offset(self._pos)
        class_end = self.class_end_offsets[klass]
        end = min(self._pos + self.max_minibatch_size, class_end)
        count = end - self._pos
        idx = self.minibatch_indices.map_invalidate()
        chunk = self._shuffled_indices[self._pos:end]
        idx[:count] = chunk
        idx[count:] = chunk[-1] if count else 0   # pad with a valid index
        self.minibatch_size = count
        self.minibatch_class = klass
        self.minibatch_offset = self._pos
        self.class_ended = (end == class_end)
        self.last_minibatch = (end == self.total_samples)
        self.epoch_ended = self.last_minibatch
        self._pos = end
        self.samples_served += count
        if not self.indices_only:
            self.fill_minibatch()
