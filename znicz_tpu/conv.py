"""Convolution forward units (rebuild of ``znicz/conv.py``).

Reference surface preserved: ``n_kernels``, ``kx``/``ky``, ``sliding``
(stride), 4-sided ``padding`` (left, top, right, bottom), fused activation
variants (``ConvTanh``, ``ConvRELU`` = softplus, ``ConvStrictRELU``).

TPU-native execution: the reference's hand-tiled OCL/CUDA direct-conv kernels
(SURVEY.md §2.3) become one ``lax.conv_general_dilated`` in NHWC — XLA lowers
it onto the MXU; no im2col staging buffer exists because XLA fuses it.
Weights are stored ``(n_kernels, ky, kx, channels)`` like the reference's
flattened filter rows.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from znicz_tpu.nn_units import ForwardBase
from znicz_tpu.ops import activations


def conv_output_hw(h: int, w: int, ky: int, kx: int,
                   sliding: Tuple[int, int],
                   padding: Tuple[int, int, int, int]) -> Tuple[int, int]:
    left, top, right, bottom = padding
    sy, sx = sliding
    return ((h + top + bottom - ky) // sy + 1,
            (w + left + right - kx) // sx + 1)


class Conv(ForwardBase):
    ACTIVATION = staticmethod(activations.identity)

    def __init__(self, workflow=None, name=None, n_kernels=8, kx=3, ky=3,
                 sliding=(1, 1), padding=(0, 0, 0, 0), **kwargs):
        if kwargs.get("weights_transposed"):
            raise ValueError("weights_transposed is an All2All storage "
                             "option; Conv weights are always (K, ky, kx, C)")
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.n_kernels = int(n_kernels)
        self.kx = int(kx)
        self.ky = int(ky)
        self.sliding = tuple(sliding)
        self.padding = tuple(padding)      # (left, top, right, bottom)

    def output_shape_for(self, in_shape):
        b, h, w, c = in_shape
        oh, ow = conv_output_hw(h, w, self.ky, self.kx, self.sliding,
                                self.padding)
        return (b, oh, ow, self.n_kernels)

    def apply_linear(self, params, x):
        """The convolution alone — no bias, no activation.  The fused
        conv-block path (pallas_fused_block) composes this with its own
        single-pass bias+ReLU+LRN+pool kernel; ``apply`` composes it with
        the unit's bias/activation.  One home for the conv math."""
        import jax.lax as lax

        w = params["weights"]                       # (K, ky, kx, C)
        left, top, right, bottom = self.padding
        # f32 accumulation: explicit for f32 operands; bf16 operands keep a
        # bf16 output (MXU accumulates f32 internally) so vjp cotangent
        # dtypes stay consistent in mixed precision
        pref = np.float32 if x.dtype == np.float32 else None
        return lax.conv_general_dilated(
            x, jnp_transpose_hwio(w),
            window_strides=self.sliding,
            padding=((top, bottom), (left, right)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=pref)

    def apply(self, params, x):
        y = self.apply_linear(params, x)
        if self.include_bias:
            y = y + params["bias"]
        return type(self).ACTIVATION(y)

    def initialize(self, device=None, **kwargs):
        b, h, w, c = self.input.shape
        if self.weights.mem is None:
            self.init_weights((self.n_kernels, self.ky, self.kx, int(c)),
                              (self.n_kernels,))
        self.create_output()
        super().initialize(device=device, **kwargs)


def jnp_transpose_hwio(w):
    """(K, ky, kx, C) -> (ky, kx, C, K) for lax conv HWIO."""
    return w.transpose(1, 2, 3, 0)


class ConvTanh(Conv):
    ACTIVATION = staticmethod(activations.tanh_scaled)


class ConvRELU(Conv):
    ACTIVATION = staticmethod(activations.relu_log)


class ConvStrictRELU(Conv):
    ACTIVATION = staticmethod(activations.strict_relu)
