"""Admission primitives shared by every ingress (ISSUE 14).

:class:`TokenBucket` is the serving batcher's per-client rate limiter
(PR 6), moved to the transport core so the MASTER's ingress meters
per-slave message rates with the same primitive instead of forking it
(ROADMAP item 4: "the admission-control policy core in serving/
batcher.py lifts to every ingress").  ``serving/batcher.py`` re-exports
it under its historical name.

:class:`AdmissionTable` is the bounded per-peer bucket table both
ingresses need: lazily-built buckets, lossless full-bucket sweep (a
refilled-to-capacity bucket is indistinguishable from a fresh one, so
dropping it loses nothing), oldest-first eviction past the hard cap.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict


class TokenBucket:
    """Per-client rate limiter: ``rate`` units/s refill into a bucket
    of ``burst`` capacity; a submit takes its unit count or is refused.
    Burst admits a cold client's first flurry; sustained traffic is
    capped at ``rate``."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.t_last = time.perf_counter()

    def try_take(self, n: int) -> bool:
        now = time.perf_counter()
        self.tokens = min(self.burst,
                          self.tokens + (now - self.t_last) * self.rate)
        self.t_last = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def refund(self, n: int) -> None:
        """Return ``n`` taken tokens (a later admission stage refused
        the request): a shed must not ALSO burn the client's rate
        budget, or a recovering client gets rate_limited refusals it
        never earned."""
        self.tokens = min(self.burst, self.tokens + n)

    def is_full(self, now: float) -> bool:
        """True when the bucket has refilled to capacity — state
        identical to a freshly built bucket, so it can be dropped and
        lazily rebuilt without the client noticing."""
        return min(self.burst,
                   self.tokens + (now - self.t_last) * self.rate) \
            >= self.burst


class AdmissionTable:
    """Bounded ``{peer_id: TokenBucket}`` (the PR 6 table discipline,
    one home): ``try_take`` builds buckets lazily; at the soft bound a
    LOSSLESS sweep drops refilled-to-capacity buckets first, and past
    the hard cap the oldest entry goes (a re-arriving peer just gets a
    fresh full bucket — strictly more permissive, never less)."""

    def __init__(self, rate: float, burst: float = 0.0,
                 max_peers: int = 4096):
        self.rate = float(rate)
        #: 0 = auto: one second of sustained rate (so burst admission
        #: and sustained metering meet at the same number)
        self.burst = float(burst) if burst else max(self.rate, 1.0)
        self.max_peers = int(max_peers)
        self._buckets: "OrderedDict[str, TokenBucket]" = OrderedDict()

    @property
    def enabled(self) -> bool:
        return self.rate > 0.0

    def try_take(self, peer: str, n: int = 1) -> bool:
        """True when ``peer`` may pass ``n`` units right now; always
        True while the limiter is disabled (rate <= 0)."""
        if not self.enabled:
            return True
        bucket = self._buckets.get(peer)
        if bucket is None:
            if len(self._buckets) >= self.max_peers:
                now = time.perf_counter()
                full = [p for p, b in self._buckets.items()
                        if b.is_full(now)]
                for p in full:
                    del self._buckets[p]
                while len(self._buckets) >= self.max_peers:
                    self._buckets.popitem(last=False)
            bucket = self._buckets[peer] = TokenBucket(self.rate,
                                                       self.burst)
        return bucket.try_take(n)

    def refund(self, peer: str, n: int) -> None:
        """Return ``n`` taken units (a later admission stage refused
        the request — the serving batcher's shed-refund rule): the
        refusal must not ALSO burn the peer's rate budget.  A no-op
        for an unknown/swept peer (its next bucket starts full, which
        is strictly more permissive)."""
        bucket = self._buckets.get(peer)
        if bucket is not None:
            bucket.refund(n)

    def snapshot(self) -> Dict[str, float]:
        """{peer: tokens remaining} for status panels."""
        return {p: round(b.tokens, 2) for p, b in self._buckets.items()}

    def __len__(self) -> int:
        return len(self._buckets)
