"""Unified async transport core (ISSUE 14, ROADMAP item 4): the ONE
event-loop + client fault model every ZMQ plane rides — master, relays,
serving frontend, replica balancer, chaos drivers, and both clients.

  - :class:`TransportLoop` (core.py): poller-driven REP/ROUTER/DEALER
    dispatch, bind/registration conventions, idle ticks, per-plane
    telemetry, built-in seeded fault injection;
  - :class:`RetryPolicy` / :class:`CircuitBreaker` (retry.py): the one
    backoff curve + the rolling-window breaker, constants preserved
    per plane;
  - :class:`Endpoint` (endpoint.py): fresh-socket reconnect,
    resend-same-bytes, breaker fail-fast and deadline budget helpers
    for every REQ-style client link;
  - :class:`TokenBucket` / :class:`AdmissionTable` (admission.py): the
    per-peer admission primitive, lifted from the serving plane to
    every ingress.

znicz-lint's ``transport-core`` rule keeps new planes here: any raw
poller dispatch loop, hand-rolled reconnect cycle, or ``2 **`` backoff
sleep outside this package is flagged.
"""

from .admission import AdmissionTable, TokenBucket        # noqa: F401
from .core import (TransportLoop, bad_frame_reply,        # noqa: F401
                   corrupt_message, corrupt_payload)
from .endpoint import (BadReply, Endpoint, PeerTimeout,   # noqa: F401
                       TransportFault, local_deadline, remaining_ms)
from .retry import (CircuitBreaker, CircuitOpenError,     # noqa: F401
                    RetryPolicy)

__all__ = ["AdmissionTable", "TokenBucket", "TransportLoop",
           "bad_frame_reply", "corrupt_message", "corrupt_payload",
           "BadReply", "Endpoint", "PeerTimeout", "TransportFault",
           "local_deadline", "remaining_ms", "CircuitBreaker",
           "CircuitOpenError", "RetryPolicy"]
