"""The unified async transport core (ISSUE 14, ROADMAP item 4).

Every ZMQ dataplane loop in the stack — master REP, relay REP, serving
ROUTER frontend, replica-balancer ROUTER, chaos proxy, scripted
replica — is ONE shape: create sockets, bind with the EADDRINUSE retry,
register them POLLIN, then loop {poll -> drain ready sockets -> idle
ticks} until told to stop.  Before this module each plane hand-rolled
that shape (five forks, each with its own conventions); this is the one
home.  :class:`TransportLoop` owns the poller, the socket factories,
the dispatch order, the idle ticks, the per-plane message/fault
telemetry, and the built-in seeded fault-injection hook — so chaos
coverage, accounting, and (via :mod:`.endpoint` on the client side)
retries/backoff/breakers/deadlines come FREE on every existing and
future plane instead of being re-forked onto it.

Refusal discipline: :func:`bad_frame_reply` is the one home for the
``bad_frame`` refusal payload every plane answers undecodable traffic
with — the cross-plane chaos soak (tests/test_transport.py) asserts
the slug comes from here on master, relay, frontend AND balancer.

Fault injection: ``inject_faults(schedule)`` applies a
:class:`~znicz_tpu.parallel.chaos.FaultSchedule`'s TRANSPORT stream
(``decide_transport`` — salted, so wire/compute/preempt decisions of
the same seed replay byte-identically) to every inbound message:
``drop`` discards it, ``corrupt`` mutates one payload frame (never the
routing envelope) so the plane's own refusal path fires.  On a
lockstep REP socket a drop would wedge the state machine, so drops are
remapped to corrupt there — counted as what was DONE.  Faults are
counted per plane in the ``znicz_transport_faults_total`` family.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


def bad_frame_reply(exc) -> dict:
    """The shared ``bad_frame`` refusal payload (one home for the slug
    + wording every plane's clients pattern-match on)."""
    return {"ok": False, "bad_frame": True, "error": f"bad frame: {exc}"}


def corrupt_payload(payload: bytes) -> bytes:
    """Deterministic frame corruption (moved here from parallel/chaos
    so the proxy and the built-in hook share one mutation): truncate to
    a third and flip the first byte — reliably undecodable (a torn
    pickle, or a tensor frame whose length no longer matches its v3
    manifest entry).  An empty frame grows a poison byte instead —
    still a guaranteed manifest-length mismatch."""
    if not payload:
        return b"\xff"
    cut = max(1, len(payload) // 3)
    head = bytearray(payload[:cut])
    head[0] ^= 0xFF
    return bytes(head)


def corrupt_message(frames: List[bytes], pick_seed) -> List[bytes]:
    """Corrupt exactly ONE payload frame of a multipart message —
    metadata or any tensor buffer, picked as a pure function of
    ``pick_seed`` — and never the routing envelope (peer identity /
    REQ correlate id / empty delimiter), so a refusal reply can still
    be routed back."""
    import numpy as np

    from znicz_tpu.parallel.wire import split_envelope

    envelope, payload = split_envelope(frames)
    if not payload:                     # degenerate: nothing to corrupt
        return frames
    pick = int(np.random.default_rng(pick_seed).integers(len(payload)))
    payload[pick] = corrupt_payload(payload[pick])
    return envelope + payload


class _Entry:
    """One registered socket: its handler and dispatch discipline."""

    __slots__ = ("sock", "handler", "reply", "drain", "priority", "seq")

    def __init__(self, sock, handler, reply: bool, drain: bool,
                 priority: int, seq: int):
        self.sock = sock
        self.handler = handler
        self.reply = reply              # REP lockstep: send handler()'s
        self.drain = drain              # NOBLOCK-drain all queued msgs
        self.priority = priority
        self.seq = seq


class TransportLoop:
    """Poller-driven serve loop every plane rides (module docstring).

    Usage::

        loop = TransportLoop("master", stop=stop_event)
        sock = loop.bind_rep(endpoint)
        loop.register(sock, reply_fn, reply=True)
        loop.add_tick(idle_fn)          # reap/evict/flush/heartbeat...
        loop.run(poll_ms=100)           # blocks until stop()/stop event
        loop.close()                    # in the caller's finally

    Handlers receive the raw multipart frame list.  ``reply=True``
    registers REP lockstep dispatch: the handler RETURNS the reply
    frames and the loop sends them (``copy=False``).  ``drain=True``
    NOBLOCK-drains every queued message per wake (ROUTER/DEALER
    convention); handlers on such sockets send their own replies.
    ``priority`` orders dispatch within one poll wake (lower first —
    the balancer drains replica replies before new client requests so
    its load view is never one tick stale).  Sockets may be registered
    and unregistered while the loop runs (the balancer's dynamic
    replica DEALERs).
    """

    def __init__(self, plane: str,
                 stop: Optional[threading.Event] = None,
                 instance: str = ""):
        from znicz_tpu import telemetry

        self.plane = str(plane)
        self._stop = stop if stop is not None else threading.Event()
        self._entries: List[_Entry] = []
        self._ticks: List[Callable[[], None]] = []
        self._poller = None
        self._ctx = None
        self._owned: List[object] = []      # sockets this loop created
        self._seq = 0
        self._chaos = None
        self._chaos_no = 0
        # ``instance`` disambiguates SAME-plane loops in one process
        # (two relays of a tree, several replicas): the registry is
        # latest-instance-wins per label set, so without it one loop's
        # exported series would shadow the other's.  Planes pass their
        # bind/endpoint/replica id — the label churn the relay's own
        # bind= label already set the precedent for.
        labels = {"plane": self.plane}
        if instance:
            labels["instance"] = str(instance)
        _sc = telemetry.scope("transport")
        self._m_messages = _sc.counter(
            "transport_messages",
            "messages dispatched by the transport loop", **labels)
        self._m_faults: Dict[str, object] = {
            action: _sc.counter(
                "transport_faults", "ingress faults injected by the "
                "transport loop's built-in hook", action=action,
                **labels)
            for action in ("drop", "corrupt")}

    # -- socket factories (the one home for build + bind conventions) ---------

    def _context(self):
        import zmq

        if self._ctx is None:
            self._ctx = zmq.Context.instance()
        return self._ctx

    def _bound(self, kind: int, endpoint: str):
        import zmq

        from znicz_tpu.network_common import bind_with_retry

        sock = self._context().socket(kind)
        sock.setsockopt(zmq.LINGER, 0)
        bind_with_retry(sock, endpoint)
        self._owned.append(sock)
        return sock

    def _connected(self, kind: int, endpoint: str):
        import zmq

        sock = self._context().socket(kind)
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(endpoint)
        self._owned.append(sock)
        return sock

    def bind_rep(self, endpoint: str):
        import zmq

        return self._bound(zmq.REP, endpoint)

    def bind_router(self, endpoint: str):
        import zmq

        return self._bound(zmq.ROUTER, endpoint)

    def bind_pull(self, endpoint: str):
        import zmq

        return self._bound(zmq.PULL, endpoint)

    def connect_dealer(self, endpoint: str):
        import zmq

        return self._connected(zmq.DEALER, endpoint)

    @staticmethod
    def resolved_endpoint(sock) -> str:
        """The concrete address of a (possibly wildcard) bind."""
        import zmq

        return sock.getsockopt(zmq.LAST_ENDPOINT).decode()

    # -- registration ----------------------------------------------------------

    def register(self, sock, handler, *, reply: bool = False,
                 drain: bool = False, priority: int = 100) -> None:
        self._seq += 1
        self._entries.append(_Entry(sock, handler, reply, drain,
                                    priority, self._seq))
        self._entries.sort(key=lambda e: (e.priority, e.seq))
        if self._poller is not None:
            import zmq

            self._poller.register(sock, zmq.POLLIN)

    def unregister(self, sock, close: bool = True) -> None:
        self._entries = [e for e in self._entries if e.sock is not sock]
        if self._poller is not None:
            self._poller.unregister(sock)
        if close:
            sock.close(0)
            if sock in self._owned:
                self._owned.remove(sock)

    def add_tick(self, fn: Callable[[], None]) -> None:
        """Idle work run once per lap AFTER socket dispatch: reaping,
        eviction, flushes, heartbeats, resume snapshots, stop
        predicates (a tick may call :meth:`stop`)."""
        self._ticks.append(fn)

    # -- chaos (built in, ISSUE 14) --------------------------------------------

    def inject_faults(self, schedule) -> None:
        """Install a seeded ingress fault hook: every inbound message
        gets one ``schedule.decide_transport(i)`` decision (module
        docstring).  ``None`` uninstalls."""
        self._chaos = schedule
        self._chaos_no = 0

    @property
    def messages(self) -> int:
        """Messages dispatched by this plane's loop (== transport-fault
        stream indices consumed while a fault hook is installed)."""
        return int(self._m_messages.value)

    def fault_counts(self) -> Dict[str, int]:
        """{action: count} injected by the built-in hook on THIS plane
        — what the cross-plane soak holds the schedule replay to."""
        return {action: int(c.value)
                for action, c in self._m_faults.items()}

    def _apply_chaos(self, frames: List[bytes],
                     entry: _Entry) -> Optional[List[bytes]]:
        """One ingress decision; None = message dropped."""
        if self._chaos is None:
            return frames
        i = self._chaos_no
        self._chaos_no += 1
        action, _ = self._chaos.decide_transport(i)
        if action == "drop" and entry.reply:
            action = "corrupt"          # a REP drop would wedge lockstep
        if action == "drop":
            self._m_faults["drop"].inc()
            return None
        if action == "corrupt":
            self._m_faults["corrupt"].inc()
            return corrupt_message(frames,
                                   (self._chaos.seed, i, 0xC0DE))
        return frames

    # -- lifecycle -------------------------------------------------------------

    def stop(self) -> None:
        self._stop.set()

    @property
    def stopping(self) -> bool:
        return self._stop.is_set()

    def run(self, poll_ms: int = 20,
            timeout_fn: Optional[Callable[[], int]] = None) -> None:
        """Blocks until :meth:`stop` (or the shared stop event).  One
        lap = poll (``timeout_fn()`` ms when given, else ``poll_ms``)
        -> dispatch ready sockets in priority order -> run ticks."""
        import zmq

        from znicz_tpu.network_common import make_poller

        self._poller = make_poller(*[e.sock for e in self._entries])
        try:
            while not self._stop.is_set():
                timeout = timeout_fn() if timeout_fn is not None \
                    else poll_ms
                events = dict(self._poller.poll(timeout))
                if events:
                    for entry in list(self._entries):
                        if entry.sock not in events:
                            continue
                        if entry.reply:
                            self._dispatch_rep(entry)
                        elif entry.drain:
                            while True:
                                try:
                                    frames = entry.sock.recv_multipart(
                                        zmq.NOBLOCK)
                                except zmq.Again:
                                    break
                                self._dispatch(entry, frames)
                        else:
                            self._dispatch(
                                entry, entry.sock.recv_multipart())
                for tick in self._ticks:
                    tick()
        finally:
            self._poller = None

    def _dispatch_rep(self, entry: _Entry) -> None:
        """REP lockstep: recv one message, send the handler's reply.
        The chaos hook may corrupt (never drop) it first — the plane's
        own refusal path answers, keeping the lockstep intact."""
        frames = entry.sock.recv_multipart()
        self._m_messages.inc()
        frames = self._apply_chaos(frames, entry)
        entry.sock.send_multipart(entry.handler(frames), copy=False)

    def _dispatch(self, entry: _Entry, frames: List[bytes]) -> None:
        self._m_messages.inc()
        frames = self._apply_chaos(frames, entry)
        if frames is not None:
            entry.handler(frames)

    def close(self) -> None:
        """Close every socket this loop's factories created (call from
        the serving plane's ``finally``; idempotent)."""
        for sock in self._owned:
            sock.close(0)
        self._owned = []
        self._entries = []
