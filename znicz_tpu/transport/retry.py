"""One fault model, one home (ISSUE 14): the retry/backoff/breaker
policies every plane's client rides.

Before this module the stack held TWO divergent backoff
implementations — the training client's capped-exponential-with-jitter
(``min(cap, base * 2**min(n-1, 16))`` slept at ``delay * (0.5 +
rng.random())``) and the serving client's breaker backoff (stateful
doubling ``min(backoff * 2, cap)``) — plus a third inline variant on
the relay's upstream link.  They are all the same curve with different
constants; :class:`RetryPolicy` is that curve, constants preserved per
plane via the ``for_*`` presets, and the znicz-lint ``transport-core``
rule refuses any NEW raw ``2 **`` backoff sleep outside this package.

:class:`CircuitBreaker` is the serving client's rolling-outcome-window
breaker (PR 6) extracted standalone so the TRAINING client (and any
future plane) gets the same fail-fast path: enough failures in the
recent window open the breaker and calls refuse locally — no connect,
no recv-timeout wait — until a capped-exponential backoff admits one
half-open probe.  All state is lock-guarded: the training client's
prefetcher thread shares its owner's breaker by design (a dead master
is detected ONCE, both sockets fail fast).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional


class CircuitOpenError(RuntimeError):
    """The circuit breaker is open: the call was refused LOCALLY
    (fail-fast, no wire traffic) because the peer recently failed too
    often.  Retry after the breaker's backoff."""


class RetryPolicy:
    """Capped exponential backoff with deterministic jitter — the ONE
    backoff curve (ISSUE 14 satellite).

    ``delay(n)`` for the n-th consecutive failure (1-based) is
    ``min(cap, base * 2**min(n-1, exp_cap))``; ``jittered(n)``
    multiplies by ``0.5 + U[0, 1)`` from a per-owner deterministic RNG
    (``jitter_key``), exactly the training client's historical fleet
    de-synchronization; ``jitter=False`` gives the serving breaker's
    un-jittered doubling.  ``spent(n)`` is the give-up test
    (``n > max_attempts``; ``max_attempts=None`` never gives up).
    """

    def __init__(self, base: float, cap: float,
                 max_attempts: Optional[int] = None, exp_cap: int = 16,
                 jitter: bool = True, jitter_key: str = ""):
        self.base = float(base)
        self.cap = float(cap)
        self.max_attempts = None if max_attempts is None \
            else int(max_attempts)
        self.exp_cap = int(exp_cap)
        self.jitter = bool(jitter)
        self._rng = random.Random(jitter_key or None)

    # -- the per-plane constants, preserved (ISSUE 14 satellite) -------------

    @classmethod
    def for_training_client(cls, base: float = 0.25, cap: float = 5.0,
                            max_attempts: Optional[int] = 8,
                            jitter_key: str = "") -> "RetryPolicy":
        """client.py's historical reconnect curve (PR 2): base 0.25s
        doubling to a 5s cap, exponent capped at 16, jittered per
        slave."""
        return cls(base, cap, max_attempts, exp_cap=16,
                   jitter_key=jitter_key)

    @classmethod
    def for_relay_upstream(cls, max_attempts: Optional[int] = 8,
                           jitter_key: str = "") -> "RetryPolicy":
        """relay.py's historical upstream curve (PR 9): base 0.05s
        doubling to a 2s cap, exponent capped at 5, jittered per
        relay."""
        return cls(0.05, 2.0, max_attempts, exp_cap=5,
                   jitter_key=jitter_key)

    @classmethod
    def for_breaker(cls, reset_s: float = 0.5,
                    cap_s: float = 30.0) -> "RetryPolicy":
        """serving/client.py's historical breaker backoff (PR 6):
        ``reset_s`` doubling to ``cap_s``, no jitter."""
        return cls(reset_s, cap_s, None, exp_cap=16, jitter=False)

    def delay(self, failures: int) -> float:
        return min(self.cap,
                   self.base * (2 ** min(max(0, int(failures) - 1),
                                         self.exp_cap)))

    def jittered(self, failures: int) -> float:
        d = self.delay(failures)
        return d * (0.5 + self._rng.random()) if self.jitter else d

    def sleep(self, failures: int) -> float:
        """Back off for the n-th consecutive failure; returns the
        slept delay."""
        d = self.jittered(failures)
        time.sleep(d)
        return d

    def spent(self, failures: int) -> bool:
        return (self.max_attempts is not None
                and int(failures) > self.max_attempts)


class CircuitBreaker:
    """Rolling-outcome-window circuit breaker (PR 6's serving breaker,
    extracted): ``record(token, ok)`` files outcomes; once the recent
    window holds >= ``threshold`` failures the breaker OPENS and
    ``admit()`` raises :class:`CircuitOpenError` until the
    :class:`RetryPolicy` backoff expires, when exactly ONE half-open
    probe is admitted (``arm_probe(token)`` marks it; its outcome
    closes or re-opens the breaker).  ``threshold=0`` disables — every
    method is a cheap no-op, so planes toggle the feature per
    config without code forks.

    ``on_event(name)`` receives ``"open"`` / ``"short_circuit"`` /
    ``"probe"`` so each plane counts transitions in its own telemetry
    family.  Thread-safe: one lock guards all state (the training
    client's prefetcher thread shares the main loop's breaker), and
    ``admit()`` RESERVES the half-open probe slot atomically — two
    threads racing past the backoff cannot both send a probe (the
    winner arms via :meth:`arm_probe`; a caller whose send dies
    between admit and arm must :meth:`release_probe`).

    ``consecutive=True`` trips on ``threshold`` failures IN A ROW
    instead of threshold-among-window — the training client's
    historical reconnect semantics (any success resets the count), so
    a sustained-but-survivable fault rate keeps making progress and
    only a DEAD peer opens the breaker.  The serving client keeps the
    density semantics (its historical behavior)."""

    #: reservation sentinel: admit() holds the half-open probe slot
    #: with this until arm_probe()/release_probe() resolves it
    _RESERVED = object()

    def __init__(self, window: int = 16, threshold: int = 8,
                 backoff: Optional[RetryPolicy] = None,
                 on_event: Optional[Callable[[str], None]] = None,
                 peer: str = "", consecutive: bool = False):
        import collections

        self._outcomes = collections.deque(maxlen=max(int(window), 1))
        #: rolling-window length (readable: sibling windows — the
        #: serving client's per-replica tables — size themselves off it)
        self.window = self._outcomes.maxlen
        # clamp: a threshold above the window could never be reached
        # (count(False) <= maxlen) — the breaker would be silently
        # disarmed while the operator believes it is armed
        self.threshold = min(int(threshold), self.window)
        self.backoff = backoff or RetryPolicy.for_breaker()
        self.peer = peer
        self.consecutive = bool(consecutive)
        self._on_event = on_event or (lambda name: None)
        self._lock = threading.Lock()
        self._state = "closed"
        self._until = 0.0
        self._opens = 0                 # consecutive opens: backoff curve
        self._streak = 0                # consecutive failures (mode above)
        self._probe: Optional[object] = None

    @property
    def state(self) -> str:
        """``closed`` / ``open`` / ``half_open`` (open flips to
        half_open lazily, at the first post-backoff admit)."""
        with self._lock:
            return self._state

    @property
    def enabled(self) -> bool:
        return self.threshold > 0

    @property
    def probe(self):
        """The armed half-open probe's token (None when none or merely
        reserved) — owners that must exempt the probe from other
        accounting key on it."""
        with self._lock:
            return None if self._probe is self._RESERVED \
                else self._probe

    def failure_counts(self):
        """(failures, window length) of the rolling window."""
        with self._lock:
            return self._outcomes.count(False), len(self._outcomes)

    def remaining(self) -> float:
        """Seconds until the next half-open probe is admitted (0 when
        not open) — what a retrying caller sleeps instead of spinning
        on :class:`CircuitOpenError`."""
        with self._lock:
            if self._state != "open":
                return 0.0
            return max(0.0, self._until - time.perf_counter())

    def admit(self) -> None:
        """Call-side gate: fail fast while open; after the backoff,
        let exactly ONE probe through (half-open).  Passing RESERVES
        the probe slot atomically (two threads racing past the backoff
        cannot both probe); the admitted caller must resolve the
        reservation with :meth:`arm_probe` — or
        :meth:`release_probe` if its send dies first."""
        if self.threshold <= 0:
            return
        with self._lock:
            if self._state == "open":
                now = time.perf_counter()
                if now < self._until:
                    self._on_event("short_circuit")
                    raise CircuitOpenError(
                        f"circuit open to {self.peer}: "
                        f"{self._outcomes.count(False)} failures in the "
                        f"last {len(self._outcomes)} outcomes; next "
                        f"probe in {self._until - now:.2f}s")
                self._state = "half_open"
                self._probe = None
            if self._state == "half_open":
                if self._probe is not None:
                    self._on_event("short_circuit")
                    raise CircuitOpenError(
                        f"circuit half-open to {self.peer}: probe "
                        f"still in flight")
                self._probe = self._RESERVED

    def arm_probe(self, token) -> bool:
        """Mark ``token`` as the half-open probe (resolving
        ``admit()``'s reservation); True when it was armed."""
        if self.threshold <= 0:
            return False
        with self._lock:
            if self._state == "half_open" \
                    and self._probe is self._RESERVED:
                self._probe = token
                self._on_event("probe")
                return True
        return False

    def release_probe(self) -> None:
        """Release an UNARMED reservation (the caller's send failed
        between admit and arm — no probe ever hit the wire, so the
        slot must not stay wedged)."""
        if self.threshold <= 0:
            return
        with self._lock:
            if self._probe is self._RESERVED:
                self._probe = None

    def _open(self) -> None:
        # caller holds the lock
        self._state = "open"
        self._opens += 1
        self._until = time.perf_counter() + self.backoff.delay(
            self._opens)
        self._on_event("open")
        # structured journal (ISSUE 20): the breaker-open TRANSITION
        # with the numbers that drove it — on_event above only counts.
        # Imported lazily: the journal must stay optional to transport
        from znicz_tpu import telemetry

        telemetry.emit(
            "breaker_open", "transport", peer=self.peer,
            failures=self._outcomes.count(False),
            window=len(self._outcomes), opens=self._opens,
            backoff_s=round(self._until - time.perf_counter(), 3))

    def record(self, token, ok: bool) -> None:
        """File one outcome.  The armed probe's outcome closes (window
        cleared, backoff reset) or re-opens (doubled backoff) the
        breaker; ordinary outcomes feed the rolling window (density
        mode) or the failure streak (``consecutive`` mode)."""
        if self.threshold <= 0:
            return
        with self._lock:
            if self._state == "half_open" and token is not None \
                    and token == self._probe:
                self._probe = None
                if ok:
                    self._state = "closed"
                    self._outcomes.clear()
                    self._streak = 0
                    self._opens = 0
                else:
                    self._open()
                return
            self._outcomes.append(bool(ok))
            self._streak = 0 if ok else self._streak + 1
            if self._state != "closed":
                return
            tripped = (self._streak >= self.threshold
                       if self.consecutive
                       else (len(self._outcomes) >= self.threshold
                             and self._outcomes.count(False)
                             >= self.threshold))
            if tripped:
                self._open()
