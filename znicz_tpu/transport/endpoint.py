"""The ONE client fault model (ISSUE 14): every REQ/REP-style peer link
in the stack — training slave -> master, slave prefetcher -> master,
relay -> upstream, the chaos harness's doomed slave — rides this class
instead of forking its own socket/retry/backoff machinery.

The fault model, feature-toggled per plane:

  - **fresh-socket reconnect** (PR 2): a timed-out REQ socket is stuck
    in a broken EFSM state and can NEVER be reused — every fault closes
    it; the next call connects a fresh one (REQ_RELAXED +
    REQ_CORRELATE, so duplicated/stale replies are discarded);
  - **capped-exp backoff with jitter**: :class:`~.retry.RetryPolicy`,
    constants preserved per plane (``backoff(n)`` sleeps the n-th
    consecutive failure's jittered delay);
  - **resend-same-bytes**: :meth:`rpc` takes already-encoded frames, so
    a caller that keeps them re-sends BYTES after a reconnect — no
    re-pickling, no re-quantization (the PR 3 discipline);
  - **circuit breaker** (PR 6, now fleet-wide): with a
    :class:`~.retry.CircuitBreaker` attached, a peer that failed
    ``threshold`` consecutive calls is refused LOCALLY
    (:class:`~.retry.CircuitOpenError`, no connect, no recv-timeout
    wait) until the breaker's backoff admits a probe — a dead master
    costs one detection, not a full reconnect budget per call site
    (the prefetcher SHARES its owner's breaker for exactly this);
  - **deadline propagation**: :func:`local_deadline` /
    :func:`remaining_ms` convert wire ``deadline_ms`` BUDGETS (never
    timestamps — clocks differ) to local absolute deadlines and back,
    the PR 6 serving contract now stamped on training jobs too.

Faults surface as :class:`PeerTimeout` (starved receive) or
:class:`BadReply` (undecodable reply) — both :class:`TransportFault`;
ANY decoded reply counts as peer-alive for the breaker (a ``bad_frame``
refusal means the peer is up and answering).
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Optional

from .retry import CircuitBreaker, CircuitOpenError, RetryPolicy

__all__ = ["TransportFault", "PeerTimeout", "BadReply", "Endpoint",
           "CircuitOpenError", "local_deadline", "remaining_ms"]


class TransportFault(Exception):
    """A transport-layer fault on one exchange; the socket has already
    been closed (fresh-socket discipline) when this reaches the
    caller."""


class PeerTimeout(TransportFault):
    """The peer never answered within the receive timeout."""


class BadReply(TransportFault):
    """The reply frame stack did not decode to a dict (truncated or
    corrupt) — handled exactly like a timeout: fresh socket, backoff,
    re-register."""


def local_deadline(budget_ms, now: Optional[float] = None,
                   cap_s: Optional[float] = None) -> Optional[float]:
    """A wire ``deadline_ms`` BUDGET -> a local absolute deadline
    (``time.monotonic`` clock), ``cap_s`` bounding it; None for an
    absent/garbage/non-finite budget (a broken peer must not disable
    deadlines with one bad float — the PR 6 ingress rule)."""
    if budget_ms is None:
        return None
    try:
        budget_s = float(budget_ms) / 1e3
    except (TypeError, ValueError):
        return None
    if not math.isfinite(budget_s):
        return None
    if cap_s is not None:
        budget_s = min(budget_s, float(cap_s))
    return (time.monotonic() if now is None else now) + budget_s


def remaining_ms(deadline: Optional[float],
                 now: Optional[float] = None) -> Optional[float]:
    """A local absolute deadline -> the remaining wire budget in ms
    (what a relay re-stamps on a job it re-serves); None when no
    deadline, <= 0 when expired."""
    if deadline is None:
        return None
    return (deadline - (time.monotonic() if now is None else now)) * 1e3


class Endpoint:
    """One fault-modeled REQ link to a REP-style peer (module
    docstring).  NOT thread-safe — one instance per thread (the
    prefetcher gets its own, sharing only the lock-guarded breaker).

    ``endpoint`` is mutable: re-homing/fallback flips it and the next
    call connects there (the old socket is already closed by the fault
    that motivated the move)."""

    def __init__(self, endpoint: str, recv_timeout_s: float = 15.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None,
                 count_out: Optional[Callable[[int], None]] = None,
                 count_in: Optional[Callable[[int], None]] = None):
        self.endpoint = str(endpoint)
        self.recv_timeout_s = float(recv_timeout_s)
        self.retry = retry or RetryPolicy.for_training_client()
        self.breaker = breaker
        self._count_out = count_out
        self._count_in = count_in
        self._sock = None

    # -- socket lifecycle ------------------------------------------------------

    def _connect(self):
        import zmq

        sock = zmq.Context.instance().socket(zmq.REQ)
        # duplicate tolerance: RELAXED lets a fresh request follow a
        # failed cycle; CORRELATE stamps request ids so a duplicated or
        # stale reply (chaos proxy, restarted master) is DISCARDED
        # instead of being returned for the NEXT request
        sock.setsockopt(zmq.REQ_RELAXED, 1)
        sock.setsockopt(zmq.REQ_CORRELATE, 1)
        sock.setsockopt(zmq.RCVTIMEO, int(self.recv_timeout_s * 1000))
        sock.setsockopt(zmq.LINGER, 0)
        sock.connect(self.endpoint)
        return sock

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def reset(self) -> None:
        """Close the socket (EFSM: unusable after any fault); the next
        :meth:`rpc` connects fresh."""
        if self._sock is not None:
            self._sock.close(0)
            self._sock = None

    def close(self) -> None:
        self.reset()

    # -- the exchange ----------------------------------------------------------

    def rpc(self, frames: List) -> dict:
        """One REQ/REP exchange of already-encoded frames (the resend
        path re-sends these exact bytes).  Raises
        :class:`CircuitOpenError` locally while the breaker is open
        (no wire traffic), :class:`PeerTimeout`/:class:`BadReply` on a
        transport fault (socket already reset)."""
        import zmq

        from znicz_tpu.parallel import wire

        if self.breaker is not None:
            self.breaker.admit()
            token = object()
            self.breaker.arm_probe(token)
        else:
            token = None
        try:
            if self._sock is None:
                self._sock = self._connect()
            if self._count_out is not None:
                self._count_out(sum(
                    f.nbytes if isinstance(f, memoryview) else len(f)
                    for f in frames))
            self._sock.send_multipart(frames, copy=False)
            raw = self._sock.recv_multipart()
        except zmq.Again:
            self.reset()
            if self.breaker is not None:
                self.breaker.record(token, False)
            raise PeerTimeout(
                f"no reply from {self.endpoint} within "
                f"{self.recv_timeout_s:g}s") from None
        except Exception:
            # connect/send faults beyond a starved receive (bad
            # endpoint string after a re-home, terminated context,
            # EINTR): the socket state is unknown AND the armed
            # half-open probe must not leak — an un-recorded probe
            # would wedge the shared breaker in "probe still in
            # flight" forever
            self.reset()
            if self.breaker is not None:
                self.breaker.record(token, False)
            raise
        if self._count_in is not None:
            self._count_in(sum(len(f) for f in raw))
        try:
            rep, _ = wire.decode_message(raw)
            if not isinstance(rep, dict):
                raise TypeError(f"reply decodes to {type(rep).__name__}")
        except Exception as exc:
            self.reset()
            if self.breaker is not None:
                self.breaker.record(token, False)
            raise BadReply(str(exc)) from None
        # ANY decoded reply = the peer is alive (a bad_frame refusal is
        # an answering peer; content-level refusals are not transport
        # failures)
        if self.breaker is not None:
            self.breaker.record(token, True)
        return rep

    def rpc_message(self, msg: dict) -> dict:
        """Encode + :meth:`rpc` (callers that need resend-same-bytes
        keep their own frames and call :meth:`rpc` directly)."""
        from znicz_tpu.parallel import wire

        frames, _ = wire.encode_message(msg)
        return self.rpc(frames)

    # -- retry pacing ----------------------------------------------------------

    def backoff(self, failures: int) -> float:
        """Sleep the n-th consecutive failure's jittered delay."""
        return self.retry.sleep(failures)

    def spent(self, failures: int) -> bool:
        return self.retry.spent(failures)

    def breaker_wait(self, cap_s: float = 1.0) -> float:
        """Sleep until the breaker's next probe window (bounded) — what
        a retrying caller does with :class:`CircuitOpenError` instead
        of spinning or burning its failure budget.  The 0.2s floor
        covers the half-open case: ``remaining()`` is 0 while another
        thread's probe is in flight (its duration is unknowable —
        bounded only by that socket's recv timeout), and a 10ms floor
        would spin the refused caller at 100Hz for the whole probe."""
        wait = min(max(self.breaker.remaining() if self.breaker
                       else 0.0, 0.2), float(cap_s))
        time.sleep(wait)
        return wait
