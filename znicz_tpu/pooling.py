"""Pooling forward units (rebuild of ``znicz/pooling.py``).

``MaxPooling`` / ``MaxAbsPooling`` / ``AvgPooling`` / ``StochasticPooling`` /
``StochasticAbsPooling`` over NHWC, with the reference's geometry: ``sliding``
defaults to the kernel size (non-overlapping), partial windows at the
right/bottom edges are processed (output = ceil-style
``(H - ky) // sy + 1`` after implicit edge padding), and the max/stochastic
variants record per-output *offsets* (flat window-relative argmax / sampled
position) that their GD twins use to scatter err_output back — exactly the
reference's forward/backward contract (SURVEY.md §2.2 "Pooling").

Implementation: windows are materialized by strided advanced indexing
(an XLA gather with static index grids — shapes are all static, jit-safe).
Stochastic pooling samples position ∝ activation (∝|activation| for the Abs
variant) from the device PRNG (SURVEY.md hard part 4: the sampled offsets are
unit state reused by the backward, not resampled).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase


def pool_output_hw(h: int, w: int, ky: int, kx: int,
                   sliding: Tuple[int, int]) -> Tuple[int, int]:
    sy, sx = sliding
    return (max(1, -(-max(h - ky, 0) // sy) + 1),
            max(1, -(-max(w - kx, 0) // sx) + 1))


class PoolingBase(ForwardBase):
    has_weights = False
    #: value used to pad partial edge windows (max: -inf, avg: 0)
    PAD_VALUE = 0.0

    def __init__(self, workflow=None, name=None, kx=2, ky=2, sliding=None,
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.kx = int(kx)
        self.ky = int(ky)
        self.sliding = tuple(sliding) if sliding else (self.ky, self.kx)
        #: flat window-relative position chosen per output element
        #: (max/stochastic variants; avg leaves it empty)
        self.input_offset = Array()

    def output_shape_for(self, in_shape):
        b, h, w, c = in_shape
        oh, ow = pool_output_hw(h, w, self.ky, self.kx, self.sliding)
        return (b, oh, ow, c)

    # -- window extraction (shared by subclasses & GD twins) ------------------

    def _window_geometry(self):
        b, h, w, c = self.input.shape
        oh, ow = pool_output_hw(h, w, self.ky, self.kx, self.sliding)
        sy, sx = self.sliding
        ph = (oh - 1) * sy + self.ky       # padded extent covering all windows
        pw = (ow - 1) * sx + self.kx
        return (int(b), int(h), int(w), int(c), oh, ow, sy, sx, ph, pw)

    def exact_tiling(self) -> bool:
        """True when every pooling window is full — the padded extent the
        windows cover equals the input plane, so no partial edge windows
        exist.  Geometry precondition of the single-pass fused conv-block
        kernel (pallas_fused_block): AlexNet's 55/27/13 planes with 3x3/s2
        overlapping pools all tile exactly; anything else falls back to
        the composed ops."""
        _, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
        return ph == h and pw == w

    def windows(self, x):
        """(B, OH, OW, C, ky*kx) view of all pooling windows.  Spatial
        geometry is the unit's static config; the batch dim follows ``x``
        so eval-time batches of any size reuse the same unit."""
        import jax.numpy as jnp

        _, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
        b = x.shape[0]
        xp = jnp.pad(x, ((0, 0), (0, ph - h), (0, pw - w), (0, 0)),
                     constant_values=type(self).PAD_VALUE)
        ys = (np.arange(oh) * sy)[:, None] + np.arange(self.ky)[None, :]
        xs = (np.arange(ow) * sx)[:, None] + np.arange(self.kx)[None, :]
        # advanced indexing broadcast -> (B, OH, OW, ky, kx, C)
        win = xp[:, ys[:, None, :, None], xs[None, :, None, :], :]
        win = win.transpose(0, 1, 2, 5, 3, 4)       # (B, OH, OW, C, ky, kx)
        return win.reshape(b, oh, ow, c, self.ky * self.kx)

    def _offset_grids(self, offsets):
        """(bidx, ay, ax, cidx) absolute padded-input coordinates for
        window-relative ``offsets`` — the single home of the offset
        convention shared by the GD scatter and Depooling (adjointness
        depends on all users agreeing on this math)."""
        import jax.numpy as jnp

        b, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
        oy = np.arange(oh)[None, :, None, None]
        ox = np.arange(ow)[None, None, :, None]
        ay = oy * sy + offsets // self.kx
        ax = ox * sx + offsets % self.kx
        bidx = jnp.arange(b)[:, None, None, None]
        cidx = jnp.arange(c)[None, None, None, :]
        return bidx, ay, ax, cidx

    def scatter_at_offsets(self, values, offsets):
        """Input-shaped array with ``values`` scatter-added at the recorded
        positions (the max/stochastic backward and Depooling forward)."""
        import jax.numpy as jnp

        b, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
        bidx, ay, ax, cidx = self._offset_grids(offsets)
        padded = jnp.zeros((b, ph, pw, c), values.dtype)
        padded = padded.at[bidx, ay, ax, cidx].add(values)
        return padded[:, :h, :w, :]

    def gather_at_offsets(self, full, offsets):
        """Output-shaped gather of an input-shaped array at the recorded
        positions (the Depooling backward — exact adjoint of the scatter)."""
        import jax.numpy as jnp

        b, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
        bidx, ay, ax, cidx = self._offset_grids(offsets)
        padded = jnp.pad(full, ((0, 0), (0, ph - h), (0, pw - w), (0, 0)))
        return padded[bidx, ay, ax, cidx]

    def initialize(self, device=None, **kwargs):
        self.create_output()
        self.input_offset.initialize(device)
        super().initialize(device=device, **kwargs)

    def _select(self, win):
        """(output, offsets|None) from windows; subclasses implement."""
        raise NotImplementedError

    def _reduce_window(self, x, init, op):
        """TPU-native pooling: one ``lax.reduce_window`` (XLA lowers its
        gradient to select_and_scatter) — the ``windows()`` gather is kept
        only where offsets must be RECORDED (unit path / stochastic /
        Depooling); as a forward op inside the fused step the gather was
        ~50x slower than reduce_window on real v5e hardware (bench r3)."""
        from jax import lax

        _, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
        return lax.reduce_window(
            x, x.dtype.type(init), op,
            window_dimensions=(1, self.ky, self.kx, 1),
            window_strides=(1, sy, sx, 1),
            padding=((0, 0), (0, ph - h), (0, pw - w), (0, 0)))

    def apply(self, params, x):
        y, _ = self._select(self.windows(x))
        return y

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(
                lambda x: self._select(self.windows(x)))
        y, off = self._compiled(self.input.devmem)
        self.output.devmem = y
        if off is not None:
            self.input_offset.devmem = off


import functools


@functools.lru_cache(maxsize=None)
def _masked_maxpool(ky: int, kx: int, sy: int, sx: int):
    """Max pooling with a SCATTER-FREE custom-vjp backward (opt-in —
    ``root.common.engine.pool_bwd = "mask"``): XLA lowers reduce_window's
    max gradient to select_and_scatter, which measured ~7% of the whole
    AlexNet train step on v5e (r5 avg-pool-swap probe).  The masked
    backward is ky*kx strided compares + interior-padded adds — pure
    elementwise+pad work XLA fuses.

    TIE SEMANTICS differ from select_and_scatter: dy is split EQUALLY
    among a window's tied maxima (mass-conserving) instead of routed to
    the first one.  Ties are common after ReLU (all-zero windows), so
    this is a (slightly) different subgradient — which is why it is an
    opt-in lever, not the default, until an anchor-grade side-by-side
    justifies flipping it (BASELINE.md r5)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _fwd_pool(x):
        oh, ow = pool_output_hw(x.shape[1], x.shape[2], ky, kx, (sy, sx))
        ph, pw = (oh - 1) * sy + ky, (ow - 1) * sx + kx
        return lax.reduce_window(
            x, x.dtype.type(-np.inf), lax.max,
            window_dimensions=(1, ky, kx, 1), window_strides=(1, sy, sx, 1),
            padding=((0, 0), (0, ph - x.shape[1]), (0, pw - x.shape[2]),
                     (0, 0)))

    @jax.custom_vjp
    def f(x):
        return _fwd_pool(x)

    def fwd(x):
        y = _fwd_pool(x)
        return y, (x, y)

    def bwd(res, g):
        x, y = res
        b, h, w, c = x.shape
        oh, ow = y.shape[1], y.shape[2]
        ph, pw = (oh - 1) * sy + ky, (ow - 1) * sx + kx
        xp = jnp.pad(x, ((0, 0), (0, ph - h), (0, pw - w), (0, 0)),
                     constant_values=x.dtype.type(-np.inf))

        def win_slice(i, j):
            return lax.slice(xp, (0, i, j, 0),
                             (b, i + (oh - 1) * sy + 1,
                              j + (ow - 1) * sx + 1, c),
                             (1, sy, sx, 1))

        masks, nt = [], None
        for i in range(ky):
            for j in range(kx):
                m = (win_slice(i, j) == y).astype(g.dtype)
                masks.append(m)
                nt = m if nt is None else nt + m
        inv = g / nt                     # dy split equally among ties
        dxp, mi = None, 0
        for i in range(ky):
            for j in range(kx):
                contrib = inv * masks[mi]
                mi += 1
                # interior padding re-dilates the strided slice back to
                # padded-input coordinates — pure lax.pad, no scatter
                part = lax.pad(
                    contrib, jnp.zeros((), g.dtype),
                    ((0, 0, 0),
                     (i, ph - (i + (oh - 1) * sy + 1), sy - 1),
                     (j, pw - (j + (ow - 1) * sx + 1), sx - 1),
                     (0, 0, 0)))
                dxp = part if dxp is None else dxp + part
        return (dxp[:, :h, :w, :].astype(x.dtype),)

    f.defvjp(fwd, bwd)
    return f


class MaxPooling(PoolingBase):
    PAD_VALUE = -np.inf

    def _select(self, win):
        import jax.numpy as jnp

        off = jnp.argmax(win, axis=-1)
        y = jnp.take_along_axis(win, off[..., None], axis=-1)[..., 0]
        return y, off

    def apply(self, params, x):
        from jax import lax

        from znicz_tpu.core.config import root

        if str(root.common.engine.get("pool_bwd", "sas")) == "mask":
            sy, sx = self.sliding
            return _masked_maxpool(self.ky, self.kx, sy, sx)(x)
        return self._reduce_window(x, -np.inf, lax.max)


class MaxAbsPooling(PoolingBase):
    """Selects the element with the largest |value| but outputs its signed
    value (reference semantics)."""

    PAD_VALUE = 0.0

    def _select(self, win):
        import jax.numpy as jnp

        off = jnp.argmax(jnp.abs(win), axis=-1)
        y = jnp.take_along_axis(win, off[..., None], axis=-1)[..., 0]
        return y, off

    def apply(self, params, x):
        import jax.numpy as jnp
        from jax import lax

        mx = self._reduce_window(x, -np.inf, lax.max)
        mn = self._reduce_window(x, np.inf, lax.min)
        # signed value with the larger magnitude; on an exact tie the
        # positive branch wins (the gather path's argmax(|.|) picks the
        # first window position instead — indistinguishable on real data)
        return jnp.where(-mn > mx, mn, mx)


class AvgPooling(PoolingBase):
    PAD_VALUE = 0.0

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self._counts: Optional[np.ndarray] = None   # real elems per window

    def window_counts(self):
        """(OH, OW) count of real (non-pad) elements in each window — edge
        windows are partial; the reference averaged over real elements."""
        if self._counts is None:
            b, h, w, c, oh, ow, sy, sx, ph, pw = self._window_geometry()
            ones = np.zeros((ph, pw), np.float32)
            ones[:h, :w] = 1.0
            counts = np.zeros((oh, ow), np.float32)
            for oy in range(oh):
                for ox in range(ow):
                    counts[oy, ox] = ones[oy * sy:oy * sy + self.ky,
                                          ox * sx:ox * sx + self.kx].sum()
            self._counts = counts
        return self._counts

    def _select(self, win):
        import jax.numpy as jnp

        counts = jnp.asarray(self.window_counts())
        y = jnp.sum(win, axis=-1) / counts[None, :, :, None]
        return y, None

    def apply(self, params, x):
        import jax.numpy as jnp
        from jax import lax

        s = self._reduce_window(x, 0.0, lax.add)
        counts = jnp.asarray(self.window_counts(), x.dtype)
        return s / counts[None, :, :, None]


class StochasticPoolingBase(PoolingBase):
    PAD_VALUE = 0.0

    def __init__(self, workflow=None, name=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self._step_counter = 0
        #: eval-time behavior: deterministic expectation (weighted mean)
        self.minibatch_class = TRAIN               # link from loader

    def _weights_from(self, win):
        raise NotImplementedError

    def _select_stochastic(self, win, key):
        import jax
        import jax.numpy as jnp

        p = self._weights_from(win)
        total = jnp.sum(p, axis=-1, keepdims=True)
        # all-zero window -> pick position 0 (matches reference kernels)
        safe = jnp.where(total > 0, p / jnp.maximum(total, 1e-30),
                         jnp.zeros_like(p).at[..., 0].set(1.0))
        off = jax.random.categorical(key, jnp.log(jnp.maximum(safe, 1e-30)),
                                     axis=-1)
        y = jnp.take_along_axis(win, off[..., None], axis=-1)[..., 0]
        return y, off

    def _select_expected(self, win):
        """Deterministic eval-time output: probability-weighted mean
        (the reference's testing-mode behavior)."""
        import jax.numpy as jnp

        p = self._weights_from(win)
        total = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
        y = jnp.sum(win * (p / total), axis=-1)
        off = jnp.argmax(p, axis=-1)
        return y, off

    def run(self):
        import jax

        if self._compiled is None:
            self._compiled = (
                jax.jit(lambda x, k: self._select_stochastic(
                    self.windows(x), k)),
                jax.jit(lambda x: self._select_expected(self.windows(x))))
        train = (int(self.minibatch_class) == TRAIN)
        if train:
            key = prng.get(self.name).jax_key(self._step_counter)
            self._step_counter += 1
            y, off = self._compiled[0](self.input.devmem, key)
        else:
            y, off = self._compiled[1](self.input.devmem)
        self.output.devmem = y
        self.input_offset.devmem = off


class StochasticPooling(StochasticPoolingBase):
    """Position sampled ∝ max(value, 0) (reference samples over positive
    activations)."""

    def _weights_from(self, win):
        import jax.numpy as jnp

        return jnp.maximum(win, 0.0)


class StochasticAbsPooling(StochasticPoolingBase):
    def _weights_from(self, win):
        import jax.numpy as jnp

        return jnp.abs(win)
