"""Dropout fwd+bwd (rebuild of ``znicz/dropout.py``).

``DropoutForward`` samples an inverted-scale Bernoulli mask on TRAIN
minibatches (keep-prob ``1 - dropout_ratio``, survivors scaled by
``1/(1-ratio)``), is the identity on TEST/VALID, and *stores the mask*;
``DropoutBackward`` multiplies err_output by that same mask (SURVEY.md §7
hard part 4: mask reuse between fwd and bwd, never resampled).  Device RNG
is the seeded per-unit jax key stream (documented divergence from the
reference's xorshift kernels — parity is distributional).
"""

from __future__ import annotations

from znicz_tpu.core import prng
from znicz_tpu.loader.base import TRAIN
from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase, GradientDescentBase


class DropoutForward(ForwardBase):
    has_weights = False

    def __init__(self, workflow=None, name=None, dropout_ratio=0.5, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.dropout_ratio = float(dropout_ratio)
        self.mask = Array()
        self.minibatch_class = TRAIN               # link from loader
        self._step_counter = 0

    def output_shape_for(self, in_shape):
        return tuple(in_shape)

    def apply(self, params, x):
        # Fused-trainer path uses sample_mask() explicitly; unit-at-a-time
        # identity here is the eval path.
        return x

    def initialize(self, device=None, **kwargs):
        self.create_output()
        self.mask.initialize(device)
        super().initialize(device=device, **kwargs)

    @staticmethod
    def make_mask(key, shape, ratio):
        import jax

        keep = 1.0 - ratio
        return jax.random.bernoulli(key, keep, shape).astype("float32") / keep

    def run(self):
        if self._compiled is None:
            import jax

            def train_step(x, key):
                m = self.make_mask(key, x.shape, self.dropout_ratio)
                return x * m, m

            self._compiled = jax.jit(train_step)
        if int(self.minibatch_class) == TRAIN:
            key = prng.get(self.name).jax_key(self._step_counter)
            self._step_counter += 1
            y, m = self._compiled(self.input.devmem, key)
            self.output.devmem = y
            self.mask.devmem = m
        else:
            self.output.devmem = self.input.devmem
            self.mask.reset(None)


class DropoutBackward(GradientDescentBase):
    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(lambda e, m: e * m)
        mask = self.forward.mask
        if mask:                                    # TRAIN: mask stored
            self.err_input.devmem = self._compiled(self.err_output.devmem,
                                                   mask.devmem)
        else:                                       # eval: identity
            self.err_input.devmem = self.err_output.devmem
