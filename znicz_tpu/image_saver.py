"""ImageSaver (rebuild of ``znicz/image_saver.py``): dumps misclassified
samples as PNGs each epoch, named ``<dir>/<epoch>/<true>_as_<pred>_<i>.png``
— the reference's worst-sample debugging artifact.  Linked after the
evaluator; collects this minibatch's misclassifications (host side, capped)
and flushes at epoch end."""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from znicz_tpu.core.config import root
from znicz_tpu.core.units import Unit

root.common.dirs.defaults({"image_saver": "saved_images"})


class ImageSaver(Unit):
    def __init__(self, workflow=None, name=None, limit=32, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.limit = int(limit)
        # linked attrs:
        self.input = None             # minibatch_data (Array)
        self.labels = None            # minibatch_labels (Array)
        self.output = None            # softmax probs (Array)
        self.batch_size = 0           # minibatch_size
        self.epoch_number = 0
        self.last_minibatch = False
        self._pending: List[tuple] = []

    def directory(self) -> str:
        d = os.path.join(root.common.dirs.get("image_saver", "saved_images"),
                         f"epoch_{int(self.epoch_number)}")
        os.makedirs(d, exist_ok=True)
        return d

    def run(self):
        if len(self._pending) < self.limit:
            probs = np.asarray(self.output.map_read())
            labels = np.asarray(self.labels.map_read())
            data = np.asarray(self.input.map_read())
            pred = probs.argmax(-1)
            n = int(self.batch_size)
            wrong = np.nonzero((pred[:n] != labels[:n]))[0]
            for i in wrong[:self.limit - len(self._pending)]:
                self._pending.append((data[i].copy(), int(labels[i]),
                                      int(pred[i])))
        if self.last_minibatch and self._pending:
            self.flush()

    def flush(self):
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        d = self.directory()
        for i, (img, true, pred) in enumerate(self._pending):
            img = np.asarray(img, np.float32)
            if img.ndim == 1:
                side = int(np.sqrt(img.size))
                img = img[:side * side].reshape(side, side)
            if img.ndim == 3 and img.shape[-1] == 1:
                img = img[..., 0]
            lo, hi = float(img.min()), float(img.max())
            if hi > lo:
                img = (img - lo) / (hi - lo)
            plt.imsave(os.path.join(d, f"{true}_as_{pred}_{i}.png"), img,
                       cmap=None if img.ndim == 3 else "gray")
        self.info("saved %d misclassified images -> %s",
                  len(self._pending), d)
        self._pending.clear()
