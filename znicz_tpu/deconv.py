"""Deconvolution (transpose conv) forward unit (rebuild of
``znicz/deconv.py``).

The reference's Deconv is the exact adjoint of a Conv with the same
geometry: it maps a (B, OH, OW, K) feature map back to the conv's input
shape (B, H, W, C).  It is defined here literally as the vjp of the conv
forward — under jit the unused primal is dead-code-eliminated and XLA emits
the same transposed-conv HLO the hand-written reference kernels computed.

Autoencoder weight tying (the reference's pattern): pass
``weights_from=conv_unit`` to share the encoder's weight Array; GDDeconv
then trains the shared tensor.  The target spatial shape comes from
``output_shape_from`` (an Array — usually the paired conv's ``input``) or an
explicit ``output_sample_shape=(H, W, C)``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase
from znicz_tpu.ops import activations


class Deconv(ForwardBase):
    ACTIVATION = staticmethod(activations.identity)

    def __init__(self, workflow=None, name=None, n_kernels=8, kx=3, ky=3,
                 sliding=(1, 1), padding=(0, 0, 0, 0),
                 output_sample_shape: Optional[Tuple[int, int, int]] = None,
                 weights_from: Optional[ForwardBase] = None, **kwargs):
        if kwargs.get("weights_transposed"):
            raise ValueError("weights_transposed does not apply to Deconv")
        if kwargs.get("include_bias"):
            raise ValueError("Deconv has no bias term (reference parity); "
                             "follow with an activation/bias unit if needed")
        kwargs.setdefault("include_bias", False)
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.n_kernels = int(n_kernels)
        self.kx = int(kx)
        self.ky = int(ky)
        self.sliding = tuple(sliding)
        self.padding = tuple(padding)
        self.output_sample_shape = (tuple(output_sample_shape)
                                    if output_sample_shape else None)
        self.output_shape_from: Optional[Array] = None
        if weights_from is not None:
            self.weights = weights_from.weights    # shared Array object
            self.n_kernels = weights_from.n_kernels
            self.kx, self.ky = weights_from.kx, weights_from.ky
            self.sliding = weights_from.sliding
            self.padding = weights_from.padding

    def _target_hwc(self) -> Tuple[int, int, int]:
        if self.output_shape_from is not None:
            _, h, w, c = self.output_shape_from.shape
            return int(h), int(w), int(c)
        if self.output_sample_shape is not None:
            return self.output_sample_shape
        # infer minimal cover: H = (OH-1)*sy + ky - pads
        _, oh, ow, _ = self.input.shape
        left, top, right, bottom = self.padding
        sy, sx = self.sliding
        c = self.weights.shape[3] if self.weights else 1
        return ((oh - 1) * sy + self.ky - top - bottom,
                (ow - 1) * sx + self.kx - left - right, int(c))

    def output_shape_for(self, in_shape):
        h, w, c = self._target_hwc()
        return (in_shape[0], h, w, c)

    def apply(self, params, x):
        import jax
        import jax.lax as lax

        w = params["weights"]                       # (K, ky, kx, C)
        h, wdt, c = self._target_hwc()
        left, top, right, bottom = self.padding

        # same mixed-precision rule as conv.py: f32 output only for f32
        # operands, else the vjp cotangent dtypes diverge under bf16
        pref = np.float32 if x.dtype == np.float32 else None

        def conv_fwd(ximg):
            return lax.conv_general_dilated(
                ximg, w.transpose(1, 2, 3, 0),
                window_strides=self.sliding,
                padding=((top, bottom), (left, right)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=pref)

        zeros = jax.numpy.zeros((x.shape[0], h, wdt, c), x.dtype)
        _, vjp = jax.vjp(conv_fwd, zeros)
        y = vjp(x)[0]
        return type(self).ACTIVATION(y)

    def initialize(self, device=None, **kwargs):
        if self.weights.mem is None:
            h, w, c = self._target_hwc()
            self.init_weights((self.n_kernels, self.ky, self.kx, c),
                              (self.n_kernels,))
        self.create_output()
        super().initialize(device=device, **kwargs)


class DeconvTanh(Deconv):
    ACTIVATION = staticmethod(activations.tanh_scaled)


class DeconvSigmoid(Deconv):
    ACTIVATION = staticmethod(activations.sigmoid)
