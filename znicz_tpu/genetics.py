"""Genetic hyperparameter optimization (rebuild of ``veles/genetics/``).

The reference wrapped numeric config leaves in ``Tune`` ranges and ran a GA
whose individuals are full workflow runs (multiprocess fan-out).  Rebuild
keeps the surface:

  - ``Tune(default, min, max)`` — mark a config leaf as tunable::

        root.mnist.learning_rate = Tune(0.1, 0.01, 1.0)

  - ``GeneticsOptimizer(evaluate, config_root, generations, population)``
    — finds all Tune leaves under ``config_root``, evolves real-valued
    chromosomes (tournament selection, blend crossover, gaussian mutation),
    writes each individual's values into the config tree and calls
    ``evaluate() -> fitness`` (lower is better: final validation error).

Evaluation modes (the reference fanned individuals out to a multiprocess
pool — SURVEY.md §2.1 "Genetics"):

  - in-process sequential (default): ``evaluate()`` runs in this process;
  - multiprocess: pass ``subprocess_evaluator=SubprocessEvaluator(...)`` and
    ``workers=N`` — each individual becomes an independent launcher run
    (``python -m znicz_tpu <workflow> root.x=... --fitness``) in its own
    process with its own device/config state, up to N at a time.  With a
    single-claim TPU keep N=1 or point workers at CPU via ``env``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import Config


class Tune:
    """A tunable numeric config leaf."""

    def __init__(self, default, minimum, maximum):
        self.default = float(default)
        self.min = float(minimum)
        self.max = float(maximum)

    def __float__(self):
        return self.default

    def __repr__(self):
        return f"Tune({self.default}, [{self.min}, {self.max}])"


def find_tunes(cfg: Config, prefix: str = "") -> List[Tuple[str, Tune]]:
    out = []
    for key, value in cfg.items():
        path = f"{prefix}.{key}" if prefix else key
        if isinstance(value, Tune):
            out.append((path, value))
        elif isinstance(value, Config):
            out.extend(find_tunes(value, path))
    return out


class SubprocessEvaluator:
    """Evaluates one chromosome as an independent ``python -m znicz_tpu``
    run, passing the chromosome as dotted config overrides and reading the
    fitness from the launcher's ``--fitness`` JSON line.

    ``prefix`` maps the optimizer's tune paths (relative to its
    ``config_root``) onto the global config tree, e.g. ``"root.mnist"``.
    """

    def __init__(self, workflow: str, config: str = "",
                 overrides: Sequence[str] = (), prefix: str = "root",
                 env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None, timeout: float = 3600.0):
        self.workflow = workflow
        self.config = config
        self.overrides = list(overrides)
        self.prefix = prefix.rstrip(".")
        self.env = env
        # 'python -m znicz_tpu' must resolve regardless of the caller's cwd:
        # default to the directory containing the znicz_tpu package
        if cwd is None:
            import znicz_tpu

            cwd = os.path.dirname(os.path.dirname(
                os.path.abspath(znicz_tpu.__file__)))
        self.cwd = cwd
        self.timeout = float(timeout)

    def launch(self, assignments: Dict[str, float]) -> subprocess.Popen:
        cmd = [sys.executable, "-m", "znicz_tpu", self.workflow]
        if self.config:
            cmd.append(self.config)
        cmd += self.overrides
        cmd += [f"{self.prefix}.{path}={value!r}"
                for path, value in assignments.items()]
        cmd.append("--fitness")
        env = dict(os.environ, **self.env) if self.env else None
        proc = subprocess.Popen(cmd, env=env, cwd=self.cwd,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        # the timeout budget runs from LAUNCH, so a batch of hung
        # individuals clears in ~timeout total, not workers x timeout
        proc.deadline = time.monotonic() + self.timeout
        return proc

    def fitness_from(self, proc: subprocess.Popen) -> float:
        import json

        left = getattr(proc, "deadline",
                       time.monotonic() + self.timeout) - time.monotonic()
        try:
            stdout, stderr = proc.communicate(timeout=max(0.0, left))
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            raise RuntimeError(
                f"genetics individual timed out after {self.timeout}s")
        if proc.returncode:
            raise RuntimeError(
                f"genetics individual failed (rc={proc.returncode}):\n"
                f"{stderr[-2000:]}")
        for line in reversed(stdout.strip().splitlines()):
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if isinstance(record, dict) and "genetics_fitness" in record:
                return float(record["genetics_fitness"])
        raise RuntimeError("launcher printed no genetics_fitness line")


class GeneticsOptimizer:
    def __init__(self, evaluate: Optional[Callable[[], float]] = None,
                 config_root: Config = None,
                 generations: int = 5, population: int = 8,
                 mutation_rate: float = 0.25, elite: int = 1,
                 workers: int = 1,
                 subprocess_evaluator: Optional[SubprocessEvaluator] = None):
        if evaluate is None and subprocess_evaluator is None:
            raise ValueError("need evaluate() or a subprocess_evaluator")
        if config_root is None:
            raise ValueError("config_root (the Config subtree holding the "
                             "Tune leaves) is required")
        self.evaluate = evaluate
        self.config_root = config_root
        self.workers = max(1, int(workers))
        self.subprocess_evaluator = subprocess_evaluator
        self.max_parallel = 0              # observed batch width (tests)
        self.tunes = find_tunes(config_root)
        if not self.tunes:
            raise ValueError("no Tune leaves found under the config root")
        self.generations = int(generations)
        self.population_size = int(population)
        self.mutation_rate = float(mutation_rate)
        self.elite = int(elite)
        self.rng = prng.get("genetics").state
        self.best_chromo = None
        self.best_fitness = np.inf
        self.history: List[float] = []

    # -- chromosome plumbing ---------------------------------------------------

    def _random_chromo(self) -> np.ndarray:
        return np.array([self.rng.uniform(t.min, t.max)
                         for _, t in self.tunes])

    def _default_chromo(self) -> np.ndarray:
        return np.array([t.default for _, t in self.tunes])

    def _apply(self, chromo: np.ndarray) -> None:
        for (path, tune), val in zip(self.tunes, chromo):
            self.config_root.set_by_path(path, float(val))

    def _fitness(self, chromo: np.ndarray) -> float:
        self._apply(chromo)
        return float(self.evaluate())

    def _assignments(self, chromo: np.ndarray) -> Dict[str, float]:
        return {path: float(v) for (path, _), v in zip(self.tunes, chromo)}

    def _score_population(self, pop):
        """Fill in missing fitnesses — sequential in-process, or batches of
        up to ``workers`` concurrent launcher subprocesses."""
        import logging

        pending = [(i, c) for i, (c, f) in enumerate(pop) if f is None]
        fits: Dict[int, float] = {}
        evaluator = self.subprocess_evaluator
        if evaluator is not None:
            log = logging.getLogger("genetics")
            for start in range(0, len(pending), self.workers):
                batch = pending[start:start + self.workers]
                procs = []
                try:
                    # launch INSIDE the try: a failed launch mid-batch must
                    # still reap the already-started siblings
                    for i, c in batch:
                        procs.append((i, evaluator.launch(
                            self._assignments(c))))
                    self.max_parallel = max(self.max_parallel, len(procs))
                    for i, proc in procs:
                        try:
                            fits[i] = evaluator.fitness_from(proc)
                        except RuntimeError as exc:
                            # one bad individual must not abort the GA (or
                            # leak its batch): penalize and move on
                            log.warning("individual %d failed: %s", i, exc)
                            fits[i] = float("inf")
                finally:
                    for _, proc in procs:       # hard-failure path cleanup
                        if proc.poll() is None:
                            proc.kill()
                            proc.communicate()
        else:
            for i, c in pending:
                fits[i] = self._fitness(c)
        return [(c, fits[i] if f is None else f)
                for i, (c, f) in enumerate(pop)]

    # -- GA operators ----------------------------------------------------------

    def _tournament(self, scored) -> np.ndarray:
        k = min(3, len(scored))
        picks = self.rng.choice(len(scored), size=k, replace=False)
        best = min(picks, key=lambda i: scored[i][1])
        return scored[best][0]

    def _crossover(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        alpha = self.rng.uniform(0.0, 1.0, size=a.shape)
        return alpha * a + (1.0 - alpha) * b

    def _mutate(self, c: np.ndarray) -> np.ndarray:
        c = c.copy()
        for i, (_, t) in enumerate(self.tunes):
            if self.rng.random() < self.mutation_rate:
                span = t.max - t.min
                c[i] = np.clip(c[i] + self.rng.normal(0, 0.15 * span),
                               t.min, t.max)
        return c

    # -- main loop -------------------------------------------------------------

    def run(self) -> Tuple[np.ndarray, float]:
        # population entries are (chromo, fitness|None); elites carry their
        # fitness forward so a full workflow run is never repeated for an
        # unchanged chromosome
        pop = [(self._default_chromo(), None)]
        while len(pop) < self.population_size:
            pop.append((self._random_chromo(), None))
        for gen in range(self.generations):
            scored = self._score_population(pop)
            scored.sort(key=lambda cf: cf[1])
            if scored[0][1] < self.best_fitness:
                self.best_fitness = scored[0][1]
                self.best_chromo = scored[0][0].copy()
            self.history.append(scored[0][1])
            nxt = [(c.copy(), f) for c, f in scored[:self.elite]]
            while len(nxt) < self.population_size:
                child = self._crossover(self._tournament(scored),
                                        self._tournament(scored))
                nxt.append((self._mutate(child), None))
            pop = nxt
        if self.best_chromo is None:      # every individual was penalized
            raise RuntimeError("genetics: every individual failed; see the "
                               "'genetics' logger for per-run errors")
        self._apply(self.best_chromo)     # leave config at the winner
        return self.best_chromo, self.best_fitness
