"""Genetic hyperparameter optimization (rebuild of ``veles/genetics/``).

The reference wrapped numeric config leaves in ``Tune`` ranges and ran a GA
whose individuals are full workflow runs (multiprocess fan-out).  Rebuild
keeps the surface:

  - ``Tune(default, min, max)`` — mark a config leaf as tunable::

        root.mnist.learning_rate = Tune(0.1, 0.01, 1.0)

  - ``GeneticsOptimizer(evaluate, config_root, generations, population)``
    — finds all Tune leaves under ``config_root``, evolves real-valued
    chromosomes (tournament selection, blend crossover, gaussian mutation),
    writes each individual's values into the config tree and calls
    ``evaluate() -> fitness`` (lower is better: final validation error).

Runs are sequential here (one accelerator); the reference's multiprocess
evaluation maps onto launching independent runs per chip at the CLI level.
"""

from __future__ import annotations

from typing import Callable, List, Tuple

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.config import Config


class Tune:
    """A tunable numeric config leaf."""

    def __init__(self, default, minimum, maximum):
        self.default = float(default)
        self.min = float(minimum)
        self.max = float(maximum)

    def __float__(self):
        return self.default

    def __repr__(self):
        return f"Tune({self.default}, [{self.min}, {self.max}])"


def find_tunes(cfg: Config, prefix: str = "") -> List[Tuple[str, Tune]]:
    out = []
    for key, value in cfg.items():
        path = f"{prefix}.{key}" if prefix else key
        if isinstance(value, Tune):
            out.append((path, value))
        elif isinstance(value, Config):
            out.extend(find_tunes(value, path))
    return out


class GeneticsOptimizer:
    def __init__(self, evaluate: Callable[[], float], config_root: Config,
                 generations: int = 5, population: int = 8,
                 mutation_rate: float = 0.25, elite: int = 1):
        self.evaluate = evaluate
        self.config_root = config_root
        self.tunes = find_tunes(config_root)
        if not self.tunes:
            raise ValueError("no Tune leaves found under the config root")
        self.generations = int(generations)
        self.population_size = int(population)
        self.mutation_rate = float(mutation_rate)
        self.elite = int(elite)
        self.rng = prng.get("genetics").state
        self.best_chromo = None
        self.best_fitness = np.inf
        self.history: List[float] = []

    # -- chromosome plumbing ---------------------------------------------------

    def _random_chromo(self) -> np.ndarray:
        return np.array([self.rng.uniform(t.min, t.max)
                         for _, t in self.tunes])

    def _default_chromo(self) -> np.ndarray:
        return np.array([t.default for _, t in self.tunes])

    def _apply(self, chromo: np.ndarray) -> None:
        for (path, tune), val in zip(self.tunes, chromo):
            self.config_root.set_by_path(path, float(val))

    def _fitness(self, chromo: np.ndarray) -> float:
        self._apply(chromo)
        return float(self.evaluate())

    # -- GA operators ----------------------------------------------------------

    def _tournament(self, scored) -> np.ndarray:
        k = min(3, len(scored))
        picks = self.rng.choice(len(scored), size=k, replace=False)
        best = min(picks, key=lambda i: scored[i][1])
        return scored[best][0]

    def _crossover(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        alpha = self.rng.uniform(0.0, 1.0, size=a.shape)
        return alpha * a + (1.0 - alpha) * b

    def _mutate(self, c: np.ndarray) -> np.ndarray:
        c = c.copy()
        for i, (_, t) in enumerate(self.tunes):
            if self.rng.random() < self.mutation_rate:
                span = t.max - t.min
                c[i] = np.clip(c[i] + self.rng.normal(0, 0.15 * span),
                               t.min, t.max)
        return c

    # -- main loop -------------------------------------------------------------

    def run(self) -> Tuple[np.ndarray, float]:
        # population entries are (chromo, fitness|None); elites carry their
        # fitness forward so a full workflow run is never repeated for an
        # unchanged chromosome
        pop = [(self._default_chromo(), None)]
        while len(pop) < self.population_size:
            pop.append((self._random_chromo(), None))
        for gen in range(self.generations):
            scored = [(c, f if f is not None else self._fitness(c))
                      for c, f in pop]
            scored.sort(key=lambda cf: cf[1])
            if scored[0][1] < self.best_fitness:
                self.best_fitness = scored[0][1]
                self.best_chromo = scored[0][0].copy()
            self.history.append(scored[0][1])
            nxt = [(c.copy(), f) for c, f in scored[:self.elite]]
            while len(nxt) < self.population_size:
                child = self._crossover(self._tournament(scored),
                                        self._tournament(scored))
                nxt.append((self._mutate(child), None))
            pop = nxt
        self._apply(self.best_chromo)     # leave config at the winner
        return self.best_chromo, self.best_fitness
