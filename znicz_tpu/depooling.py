"""Depooling: inverse-pooling forward op for decoder stacks (rebuild of
``znicz/depooling.py``).

Routes each input value to the position its paired *pooling* unit selected
on the current minibatch (``get_output_shape_from`` + offsets contract of
the reference): construct with ``pooling_from=<MaxPooling unit>``; forward
scatters through the recorded ``input_offset``; ``GDDepooling`` gathers back
(the exact adjoint).  AvgPooling has no offsets — average depooling spreads
uniformly (vjp of the average)."""

from __future__ import annotations

from znicz_tpu.nn_units import ForwardBase, GradientDescentBase


class Depooling(ForwardBase):
    has_weights = False

    def __init__(self, workflow=None, name=None, pooling_from=None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        assert pooling_from is not None, \
            "Depooling needs pooling_from=<pooling unit>"
        self.pooling = pooling_from

    def output_shape_for(self, in_shape):
        return tuple(self.pooling.input.shape)

    def initialize(self, device=None, **kwargs):
        self.create_output()
        super().initialize(device=device, **kwargs)

    def _has_offsets(self) -> bool:
        return bool(self.pooling.input_offset)

    def run(self):
        if self._compiled is None:
            import jax

            if self._has_offsets():
                self._compiled = jax.jit(self.pooling.scatter_at_offsets)
            else:
                # AvgPooling records no offsets: spread uniformly — the
                # exact adjoint of the average (vjp of the pooling forward)
                import jax.numpy as jnp

                pool = self.pooling
                in_shape = tuple(pool.input.shape)

                def spread(values, _offsets_unused=None):
                    zeros = jnp.zeros(in_shape, values.dtype)
                    _, vjp = jax.vjp(lambda x: pool.apply({}, x), zeros)
                    return vjp(values)[0]

                self._compiled = jax.jit(spread)
        if self._has_offsets():
            self.output.devmem = self._compiled(
                self.input.devmem, self.pooling.input_offset.devmem)
        else:
            self.output.devmem = self._compiled(self.input.devmem)


class GDDepooling(GradientDescentBase):
    """Adjoint of Depooling: gather err_output at the recorded offsets
    (shared geometry on PoolingBase.gather_at_offsets)."""

    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self.forward.pooling.gather_at_offsets)
        self.err_input.devmem = self._compiled(
            self.err_output.devmem, self.forward.pooling.input_offset.devmem)
