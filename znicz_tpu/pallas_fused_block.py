"""Single-pass fused Pallas kernel for the conv1/conv2 elementwise block:
bias-add -> StrictRELU -> cross-channel LRN -> overlapping maxpool, forward
AND backward, each as ONE VMEM-resident pass over the activation planes.

Why (BASELINE.md r4 profile / VERDICT r5 weak #1): the composed ops lower to
several XLA fusions that each stream the 55x55x96-class conv1/conv2 tensors
through HBM — 4.39 ms of the 10.75 ms AlexNet step at a measured
320–490 GB/s against the chip's 819, and the one lever behind three rounds
of flat ~39.5% MFU.  The r5 masked-pool-backward experiment proved that
MULTI-pass reformulations lose (more passes, more HBM traffic); this kernel
is the single-pass counterpart: the forward reads x once and writes the
pooled output once; the backward reads (x, bias, d_pool) once and writes
(dx, dbias) once, with every intermediate (ReLU mask, LRN window sums, pool
argmax masks) living only in VMEM.

Grid: one image per grid step — a (1, H, W, C) block is VMEM-resident
(conv1: 55*55*96*4 B = 1.2 MB f32).  The channel-window sum is unrolled
static lane shifts (identical summation order to ops/lrn_pallas.py); the
pool is unrolled ky*kx strided max/compare; the pool backward re-dilates
window contributions with interior padding (lax.pad) — the same formulation
``pooling._masked_maxpool`` uses, but fused in VMEM where its ~18
intermediate tensors are free instead of 18 HBM round trips.

Semantics vs the composed ops:
  - forward is bit-for-tolerance identical (same rsqrt-based ``s^-0.75``,
    same shift summation order as the LRN oracle);
  - pool-backward TIES split d_y equally among a window's tied maxima
    (mass-conserving) where select_and_scatter routes to the first.  After
    StrictRELU the only systematic ties are all-zero windows, whose
    gradient the ReLU mask zeroes either way, so the two subgradients agree
    everywhere it matters (tests assert parity on random data);
  - internal arithmetic is f32 even for bf16 operands (outputs cast back),
    at least as accurate as the composed bf16 chain.

Engagement (``plan_fused_blocks``): opt-in via
``root.common.engine.fused_elementwise`` (default OFF until a TPU-attached
bench records the with/without numbers — BASELINE.md "Fused elementwise
block"), and only where the graph shape matches exactly:
Conv(+bias)+StrictRELU (fused or as a standalone activation unit) ->
LRNormalizerForward (odd window) -> MaxPooling whose windows tile the plane
exactly (AlexNet's 55/27/13 planes all do; partial edge windows fall back
to the composed ops).  The LRN-formulation experiment knobs
(``lrn_pow`` / ``lrn_autodiff`` / ``pallas_lrn``) disable fusion so their
side-by-side re-runs stay pure.

Backward wiring: ``fused_block`` carries a ``jax.custom_vjp``, so wherever
the fused trainer's forward_pass routes through it, ``jax.grad`` of the
train step executes the fused backward kernel in place of the
``GradientDescent*`` chain (GDStrictRELUConv's activation term,
LRNormalizerBackward, GDMaxPooling's offset scatter).  The unit-at-a-time
engine keeps the composed units — it cannot fuse across unit boundaries by
construction.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np


class FusedBlockSpec(NamedTuple):
    """One matched conv-block occurrence in a forwards list."""

    span: int                      # units consumed (3, or 4 with a
    #                                standalone StrictRELU unit)
    n: int                         # LRN channel window
    alpha: float
    beta: float
    k: float
    pool: Tuple[int, int, int, int]   # (ky, kx, sy, sx)


def _use_interpret() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def _relu_lrn(x, b, n, alpha, beta, k):
    """The pre-pool part shared by both kernels: f32 a/mask/r/s/y.  The
    window sum and ``s^-beta`` come from ops/lrn_pallas — the ONE home of
    that order-sensitive math (the parity guarantees depend on the exact
    summation order and rsqrt formulation)."""
    import jax.numpy as jnp

    from znicz_tpu.ops.lrn_pallas import (inv_pow_rsqrt,
                                          windowed_channel_sum)

    a = x + b
    r = jnp.maximum(a, 0.0)
    s = k + alpha * windowed_channel_sum(r * r, n)
    return a, r, s, r * inv_pow_rsqrt(s, beta)


def _pool_windows(y, ky, kx, sy, sx, oh, ow):
    """ky*kx strided (OH, OW, C) window views of an exactly-tiling plane."""
    from jax import lax

    C = y.shape[-1]
    wins = []
    for i in range(ky):
        for j in range(kx):
            wins.append(lax.slice(
                y, (i, j, 0),
                (i + (oh - 1) * sy + 1, j + (ow - 1) * sx + 1, C),
                (sy, sx, 1)))
    return wins


def _fwd_kernel(n, alpha, beta, k, ky, kx, sy, sx, x_ref, b_ref, out_ref):
    import jax.numpy as jnp

    x = x_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    _, _, _, y = _relu_lrn(x, b, n, alpha, beta, k)
    oh, ow = out_ref.shape[1], out_ref.shape[2]
    p = None
    for win in _pool_windows(y, ky, kx, sy, sx, oh, ow):
        p = win if p is None else jnp.maximum(p, win)
    out_ref[0] = p.astype(out_ref.dtype)


def _bwd_kernel(n, alpha, beta, k, ky, kx, sy, sx,
                x_ref, b_ref, dp_ref, dx_ref, db_ref):
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    from znicz_tpu.ops.lrn_pallas import (inv_pow_rsqrt,
                                          windowed_channel_sum)

    x = x_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    dp = dp_ref[0].astype(jnp.float32)
    a, r, s, y = _relu_lrn(x, b, n, alpha, beta, k)
    sb = inv_pow_rsqrt(s, beta)
    H, W, _ = y.shape
    oh, ow = dp.shape[0], dp.shape[1]
    # pool backward: recompute window maxima, split dp among ties
    # (mass-conserving; see module docstring for the tie semantics)
    wins = _pool_windows(y, ky, kx, sy, sx, oh, ow)
    p = None
    for win in wins:
        p = win if p is None else jnp.maximum(p, win)
    masks, nt = [], None
    for win in wins:
        mk = (win == p).astype(jnp.float32)
        masks.append(mk)
        nt = mk if nt is None else nt + mk
    g = dp / nt
    dy, mi = None, 0
    for i in range(ky):
        for j in range(kx):
            contrib = g * masks[mi]
            mi += 1
            # interior padding re-dilates the strided window back to
            # plane coordinates — pure pad, no scatter, all in VMEM
            part = lax.pad(
                contrib, jnp.zeros((), jnp.float32),
                ((i, H - (i + (oh - 1) * sy + 1), sy - 1),
                 (j, W - (j + (ow - 1) * sx + 1), sx - 1),
                 (0, 0, 0)))
            dy = part if dy is None else dy + part
    # LRN backward — the closed form from znicz_tpu/lrn.py:
    #   dr = dy*s^-beta - 2*alpha*beta * r * W(dy * r * s^(-beta-1))
    t = dy * r * (sb / s)
    dr = dy * sb - (2.0 * alpha * beta) * r * windowed_channel_sum(t, n)
    # StrictRELU mask + bias reduction
    da = dr * (a > 0.0).astype(jnp.float32)
    dx_ref[0] = da.astype(dx_ref.dtype)
    partial = jnp.sum(da, axis=(0, 1))
    bi = pl.program_id(0)

    @pl.when(bi == 0)
    def _():
        db_ref[0] = partial

    @pl.when(bi > 0)
    def _():
        db_ref[0] = db_ref[0] + partial


#: generous VMEM cap: the backward holds ~20 plane-sized intermediates
#: live before Mosaic's buffer reuse (conv1 plane ~1.2 MB f32)
_VMEM_LIMIT = 100 * 1024 * 1024


def _pool_out_hw(h, w, ky, kx, sy, sx):
    return (h - ky) // sy + 1, (w - kx) // sx + 1


def _img_spec(shape):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec((1,) + tuple(shape[1:]),
                        lambda bi: (bi, 0, 0, 0), memory_space=pltpu.VMEM)


def _bias_spec(c):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.BlockSpec((1, c), lambda bi: (0, 0),
                        memory_space=pltpu.VMEM)


def _compiler_params():
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(vmem_limit_bytes=_VMEM_LIMIT)


def _call_fwd(x, bias, n, alpha, beta, k, pool):
    import jax
    from jax.experimental import pallas as pl

    ky, kx, sy, sx = pool
    B, H, W, C = x.shape
    oh, ow = _pool_out_hw(H, W, ky, kx, sy, sx)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, n, alpha, beta, k, ky, kx, sy, sx),
        grid=(B,),
        in_specs=[_img_spec(x.shape), _bias_spec(C)],
        out_specs=_img_spec((B, oh, ow, C)),
        out_shape=jax.ShapeDtypeStruct((B, oh, ow, C), x.dtype),
        compiler_params=_compiler_params(),
        interpret=_use_interpret(),
    )(x, bias.reshape(1, C))


def _call_bwd(x, bias, dp, n, alpha, beta, k, pool):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ky, kx, sy, sx = pool
    B, H, W, C = x.shape
    dx, db = pl.pallas_call(
        functools.partial(_bwd_kernel, n, alpha, beta, k, ky, kx, sy, sx),
        grid=(B,),
        in_specs=[_img_spec(x.shape), _bias_spec(C),
                  _img_spec(dp.shape)],
        out_specs=(_img_spec(x.shape), _bias_spec(C)),
        out_shape=(jax.ShapeDtypeStruct((B, H, W, C), x.dtype),
                   jax.ShapeDtypeStruct((1, C), jnp.float32)),
        compiler_params=_compiler_params(),
        interpret=_use_interpret(),
    )(x, bias.reshape(1, C), dp)
    return dx, db.reshape(bias.shape).astype(bias.dtype)


def _make():
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
    def fused_block(x, bias, n, alpha, beta, k, pool):
        return _call_fwd(x, bias, n, alpha, beta, k, pool)

    def fwd(x, bias, n, alpha, beta, k, pool):
        # residual is (x, bias) only — everything else is recomputed in
        # VMEM by the backward kernel (same policy as lrn.py's closed vjp)
        return fused_block(x, bias, n, alpha, beta, k, pool), (x, bias)

    def bwd(n, alpha, beta, k, pool, res, dp):
        x, bias = res
        return _call_bwd(x, bias, dp, n, alpha, beta, k, pool)

    fused_block.defvjp(fwd, bwd)
    return fused_block


_fused = None


def fused_block(x, bias, n=5, alpha=1e-4, beta=0.75, k=2.0,
                pool=(3, 3, 2, 2)):
    """Fused bias+StrictRELU+LRN+maxpool with the fused backward as its
    custom vjp.  ``x`` is the RAW conv output (``Conv.apply_linear``) of
    shape (B, H, W, C); ``pool`` = (ky, kx, sy, sx) and must tile (H, W)
    exactly — ``plan_fused_blocks`` guarantees this."""
    global _fused
    if _fused is None:
        _fused = _make()
    ky, kx, sy, sx = (int(v) for v in pool)
    _, H, W, _ = x.shape
    assert (H - ky) % sy == 0 and (W - kx) % sx == 0, \
        f"pool {pool} does not tile ({H}, {W}) exactly"
    return _fused(x, bias, int(n), float(alpha), float(beta), float(k),
                  (ky, kx, sy, sx))


def match_fused_block(forwards: Sequence, i: int) -> Optional[FusedBlockSpec]:
    """The FusedBlockSpec for a conv-block starting at ``forwards[i]``, or
    None.  Patterns: ConvStrictRELU -> norm -> max_pooling (span 3), or
    plain Conv -> StrictRELU activation unit -> norm -> max_pooling
    (span 4).  Units must be initialized (geometry comes from live
    shapes)."""
    from znicz_tpu.activation import is_strict_relu_unit
    from znicz_tpu.conv import Conv
    from znicz_tpu.lrn import LRNormalizerForward
    from znicz_tpu.ops import activations
    from znicz_tpu.pooling import MaxPooling

    conv = forwards[i]
    if not isinstance(conv, Conv) or not conv.include_bias:
        return None
    j = i + 1
    if conv.ACTIVATION is activations.strict_relu:
        pass
    elif conv.ACTIVATION is activations.identity and j < len(forwards) \
            and is_strict_relu_unit(forwards[j]):
        j += 1
    else:
        return None
    if j + 1 >= len(forwards):
        return None
    lrn_u, pool_u = forwards[j], forwards[j + 1]
    if not isinstance(lrn_u, LRNormalizerForward):
        return None
    hypers = lrn_u.fused_block_hypers
    if hypers is None:
        return None
    # exact class: MaxAbs/stochastic/avg pooling have different math
    if type(pool_u) is not MaxPooling or not pool_u.exact_tiling():
        return None
    n, alpha, beta, k = hypers
    sy, sx = pool_u.sliding
    return FusedBlockSpec(span=j + 2 - i, n=n, alpha=alpha, beta=beta,
                          k=k, pool=(pool_u.ky, pool_u.kx, sy, sx))


def plan_fused_blocks(forwards: Sequence) -> Dict[int, FusedBlockSpec]:
    """start-index -> FusedBlockSpec for every fusable conv block, or {}
    when the ``fused_elementwise`` flag is off / an LRN-formulation
    experiment knob is active (their side-by-side re-runs must stay
    pure — BASELINE.md anchor-defense protocol)."""
    from znicz_tpu.core.config import root

    if not bool(root.common.engine.get("fused_elementwise", False)):
        return {}
    if any(bool(root.common.engine.get(knob, False))
           for knob in ("lrn_pow", "lrn_autodiff", "pallas_lrn")):
        return {}
    plan: Dict[int, FusedBlockSpec] = {}
    i = 0
    while i < len(forwards):
        spec = match_fused_block(forwards, i)
        if spec is not None:
            plan[i] = spec
            i += spec.span
        else:
            i += 1
    return plan


# -- the AlexNet tail (ISSUE 7) ------------------------------------------------
#
# The conv1/conv2 block kernel above left the TAIL of the network on the
# composed path: conv3-5's bias+StrictRELU, the fc6/fc7
# bias+StrictRELU+dropout epilogues, and the softmax-CE loss head.  Each
# of those is elementwise work whose AUTODIFF residuals (ReLU gates,
# dropout masks, softmax probabilities) round-trip HBM between the
# forward and backward passes — for AlexNet at batch 128 that is
# ~27 MB/step of pure mask traffic on top of the activations.  The three
# tail stages below each carry a ``jax.custom_vjp`` whose residual is
# ONLY what already exists (the stage's raw linear input + params): the
# backward recomputes every mask in-register instead of loading it.
#
# Engagement: ``root.common.engine.fused_tail`` (default OFF — same
# BASELINE.md hand-off discipline as ``fused_elementwise``; bench.py
# ``--fused-tail`` is the labeled with/without protocol).  Where BOTH
# knobs are on, the conv1/conv2 BLOCK matcher wins its span and the tail
# matcher takes everything else.


class FusedTailSpec(NamedTuple):
    """One matched tail-stage occurrence in a forwards list."""

    kind: str                  # "conv_bias_relu" | "fc_epilogue"
    span: int                  # units consumed
    ratio: float = 0.0         # dropout ratio (fc_epilogue only)
    dropout_index: int = -1    # forwards index of the absorbed dropout
    #                            unit (-1 = no dropout); the fused mask
    #                            key is fold_in(key, dropout_index) —
    #                            bit-identical to the unit path's draw


def _bias_relu_fwd_kernel(x_ref, b_ref, out_ref):
    import jax.numpy as jnp

    x = x_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    out_ref[0] = jnp.maximum(x + b, 0.0).astype(out_ref.dtype)


def _bias_relu_bwd_kernel(x_ref, b_ref, dp_ref, dx_ref, db_ref):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    x = x_ref[0].astype(jnp.float32)
    b = b_ref[0].astype(jnp.float32)
    dp = dp_ref[0].astype(jnp.float32)
    da = dp * ((x + b) > 0.0).astype(jnp.float32)
    dx_ref[0] = da.astype(dx_ref.dtype)
    partial = jnp.sum(da, axis=(0, 1))
    bi = pl.program_id(0)

    @pl.when(bi == 0)
    def _():
        db_ref[0] = partial

    @pl.when(bi > 0)
    def _():
        db_ref[0] = db_ref[0] + partial


def _call_bias_relu_fwd(x, bias):
    import jax
    from jax.experimental import pallas as pl

    B, H, W, C = x.shape
    return pl.pallas_call(
        _bias_relu_fwd_kernel,
        grid=(B,),
        in_specs=[_img_spec(x.shape), _bias_spec(C)],
        out_specs=_img_spec(x.shape),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_compiler_params(),
        interpret=_use_interpret(),
    )(x, bias.reshape(1, C))


def _call_bias_relu_bwd(x, bias, dp):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    B, H, W, C = x.shape
    dx, db = pl.pallas_call(
        _bias_relu_bwd_kernel,
        grid=(B,),
        in_specs=[_img_spec(x.shape), _bias_spec(C), _img_spec(x.shape)],
        out_specs=(_img_spec(x.shape), _bias_spec(C)),
        out_shape=(jax.ShapeDtypeStruct(x.shape, x.dtype),
                   jax.ShapeDtypeStruct((1, C), jnp.float32)),
        compiler_params=_compiler_params(),
        interpret=_use_interpret(),
    )(x, bias.reshape(1, C), dp)
    return dx, db.reshape(bias.shape).astype(bias.dtype)


def _make_bias_relu():
    import jax

    @jax.custom_vjp
    def bias_relu(x, bias):
        return _call_bias_relu_fwd(x, bias)

    def fwd(x, bias):
        # residual is (x, bias) only — the ReLU gate is recomputed by
        # the backward kernel in VMEM, never written to HBM
        return bias_relu(x, bias), (x, bias)

    def bwd(res, dp):
        x, bias = res
        return _call_bias_relu_bwd(x, bias, dp)

    bias_relu.defvjp(fwd, bwd)
    return bias_relu


_bias_relu = None


def fused_bias_relu(x, bias):
    """Fused bias+StrictRELU over a (B, H, W, C) conv output — the
    conv3-5 tail stage (no LRN, no pool there) as ONE Pallas pass each
    way: forward reads x once and writes relu(x+b) once; backward reads
    (x, bias, d_out) once and writes (dx, dbias) once, the ReLU gate
    living only in VMEM.  Internal arithmetic is f32 even for bf16
    operands (outputs cast back), matching the block kernel's policy."""
    global _bias_relu
    if _bias_relu is None:
        _bias_relu = _make_bias_relu()
    assert x.ndim == 4, f"fused_bias_relu expects NHWC, got {x.shape}"
    return _bias_relu(x, bias)


def fused_fc_epilogue(y, bias, key, ratio, train):
    """Fused FC-layer epilogue — bias+StrictRELU(+inverted-scale dropout)
    over the raw GEMM output ``y`` as ONE custom-vjp stage.  The forward
    is a single elementwise fusion; the backward recomputes the ReLU gate
    from (y, bias) and the dropout mask FROM THE KEY instead of loading
    either from HBM (the 4096-wide fc6/fc7 masks are the dominant
    non-GEMM autodiff residual).  The mask is ``DropoutForward.
    make_mask``'s own bernoulli draw with the caller's key, so fused and
    unfused paths apply BIT-IDENTICAL masks — e2e trainer parity is
    exact, not distributional.  ``key`` may be None when no mask applies
    (eval, or ratio 0)."""
    import jax
    import jax.numpy as jnp

    from znicz_tpu.dropout import DropoutForward

    use_mask = bool(train) and float(ratio) > 0.0 and key is not None
    shape, ratio = y.shape, float(ratio)

    def mask_of():
        return DropoutForward.make_mask(key, shape, ratio)

    @jax.custom_vjp
    def epilogue(y, b):
        r = jnp.maximum(y + b, 0.0)
        return r * mask_of() if use_mask else r

    def fwd(y, b):
        return epilogue(y, b), (y, b)

    def bwd(res, g):
        y, b = res
        da = g * ((y + b) > 0.0).astype(g.dtype)
        if use_mask:
            da = da * mask_of().astype(g.dtype)
        # bias grad sums every leading axis (a seq epilogue's y is
        # (B, T, F); for the classic (B, F) this is the same axis-0 sum)
        return (da.astype(y.dtype),
                jnp.sum(da, axis=tuple(range(da.ndim - 1))).astype(b.dtype))

    epilogue.defvjp(fwd, bwd)
    return epilogue(y, bias)


def fused_softmax_xent(logits, labels, valid, denom):
    """Softmax-CE loss + gradient as ONE custom-vjp epilogue.  Forward is
    the max-subtracted logsumexp CE — the IDENTICAL formula the composed
    trainer loss uses (``logsumexp(logits) - logits[label]``, masked and
    batch-mean scaled).  Backward writes ``(softmax(logits) - onehot) *
    valid / denom`` in a single fusion that re-reads the logits (which
    must exist anyway — they are the FC head's output) instead of
    consuming saved logsumexp/softmax residuals; for the 1000-class
    AlexNet head that is the difference between one HBM read and three.
    ``labels``/``valid``/``denom`` are closed over (non-differentiable
    operands)."""
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def loss_of(lg):
        logz = jax.nn.logsumexp(lg, axis=-1)
        ll = jnp.take_along_axis(lg, labels[:, None], axis=-1)[:, 0]
        return jnp.sum(jnp.where(valid, logz - ll, 0.0)) / denom

    def fwd(lg):
        return loss_of(lg), (lg,)

    def bwd(res, g):
        lg, = res
        p = jax.nn.softmax(lg, axis=-1)
        onehot = jax.nn.one_hot(labels, lg.shape[-1], dtype=lg.dtype)
        d = (p - onehot) * valid[:, None].astype(lg.dtype) / denom * g
        return (d,)

    loss_of.defvjp(fwd, bwd)
    return loss_of(logits)


def match_conv_bias_relu(forwards: Sequence, i: int) \
        -> Optional[FusedTailSpec]:
    """Conv(+bias) with a StrictRELU — fused into the class (span 1) or a
    standalone activation unit (span 2) — with NO LRN/pool requirement:
    the conv3-5 shape.  (Where the full conv-block matcher also fires,
    ``plan_fused_tail`` lets the block win its span.)"""
    from znicz_tpu.activation import is_strict_relu_unit
    from znicz_tpu.conv import Conv
    from znicz_tpu.ops import activations

    conv = forwards[i]
    if not isinstance(conv, Conv) or not conv.include_bias:
        return None
    if conv.ACTIVATION is activations.strict_relu:
        return FusedTailSpec("conv_bias_relu", 1)
    if conv.ACTIVATION is activations.identity and i + 1 < len(forwards) \
            and is_strict_relu_unit(forwards[i + 1]):
        return FusedTailSpec("conv_bias_relu", 2)
    return None


def match_fc_epilogue(forwards: Sequence, i: int) -> Optional[FusedTailSpec]:
    """All2AllStrictRELU(+bias), optionally followed by a DropoutForward
    it absorbs (span 2) — the fc6/fc7 shape.  The softmax head is NOT
    matched here (its epilogue is the loss head, ``fused_softmax_xent``,
    routed by the trainer's loss function)."""
    from znicz_tpu.all2all import All2All, All2AllSoftmax
    from znicz_tpu.dropout import DropoutForward
    from znicz_tpu.ops import activations

    f = forwards[i]
    if not isinstance(f, All2All) or isinstance(f, All2AllSoftmax):
        return None
    if type(f).ACTIVATION is not activations.strict_relu \
            or not f.include_bias:
        return None
    if i + 1 < len(forwards) and isinstance(forwards[i + 1],
                                            DropoutForward):
        return FusedTailSpec("fc_epilogue", 2,
                             float(forwards[i + 1].dropout_ratio), i + 1)
    return FusedTailSpec("fc_epilogue", 1)


def match_seq_epilogue(forwards: Sequence, i: int) -> Optional[FusedTailSpec]:
    """SeqAll2AllStrictRELU(+bias) — the position-wise transformer-FFN
    shape (ISSUE 15; span 1).  The softmax head is NOT matched here
    (its epilogue is the loss head, like the All2All case), and no
    dropout is absorbed (the charlm FFN carries none)."""
    from znicz_tpu.attention import SeqAll2All, SeqAll2AllSoftmax
    from znicz_tpu.ops import activations

    f = forwards[i]
    if not isinstance(f, SeqAll2All) or isinstance(f, SeqAll2AllSoftmax):
        return None
    if type(f).ACTIVATION is not activations.strict_relu \
            or not f.include_bias:
        return None
    return FusedTailSpec("seq_epilogue", 1)


def fused_tail_enabled() -> bool:
    """The ``root.common.engine.fused_tail`` gate (default OFF — engages
    per the BASELINE.md r12 protocol; bench.py ``--fused-tail``)."""
    from znicz_tpu.core.config import root

    return bool(root.common.engine.get("fused_tail", False))


def plan_fused_tail(forwards: Sequence,
                    block_plan: Optional[Dict[int, FusedBlockSpec]] = None
                    ) -> Dict[int, FusedTailSpec]:
    """start-index -> FusedTailSpec for every fusable tail stage, or {}
    when ``fused_tail`` is off.  Indices covered by a conv-block span
    (``block_plan``) are skipped — the single-pass block kernel already
    owns their bias+ReLU."""
    if not fused_tail_enabled():
        return {}
    covered = set()
    for i, spec in (block_plan or {}).items():
        covered.update(range(i, i + spec.span))
    plan: Dict[int, FusedTailSpec] = {}
    i = 0
    while i < len(forwards):
        if i in covered:
            i += 1
            continue
        spec = match_conv_bias_relu(forwards, i)
        if spec is None:
            spec = match_fc_epilogue(forwards, i)
        if spec is None:
            spec = match_seq_epilogue(forwards, i)
        if spec is not None:
            plan[i] = spec
            i += spec.span
        else:
            i += 1
    return plan
