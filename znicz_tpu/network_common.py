"""Shared master/slave wire protocol (rebuild of ``veles/network_common.py``,
SURVEY.md §2.1 "Network common": handshake, endpoint IDs).

The reference's NetworkAgent performed a handshake before any job traffic;
the rebuild's equivalent is a version + workflow-digest exchange on the
``register`` command: a slave built against a different protocol revision
or a different trainable graph is refused with a human-readable reason
instead of corrupting weights mid-training (VERDICT r2 missing #5).

Payloads stay pickle-over-ZMQ like the reference (trusted-cluster
assumption, documented in server.py).
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

#: bump on any incompatible change to the job/update message schema
PROTOCOL_VERSION = 1


def workflow_digest(workflow) -> str:
    """Stable short digest of the BUILT trainable graph — the actual
    weight-delta compatibility contract: layer names, unit classes, param
    shapes, and each GD twin's hyperparameters.  Deliberately NOT a digest
    of the global config tree: that tree also carries host-local paths and
    the defaults of whichever sample modules happen to be imported, which
    made legitimately-identical deployments mismatch."""
    desc = []
    for f in workflow.forwards:
        if f.has_weights:
            desc.append([f.name, type(f).__name__,
                         sorted((k, list(a.shape))
                                for k, a in f.params().items())])
    for gd in getattr(workflow, "gds", []) or []:
        if gd.forward.has_weights:
            desc.append([gd.forward.name, type(gd).__name__,
                         [round(float(v), 12) for v in (
                             gd.learning_rate, gd.learning_rate_bias,
                             gd.weights_decay, gd.weights_decay_bias,
                             gd.l1_vs_l2, gd.gradient_moment,
                             gd.gradient_moment_bias, gd.gradient_clip)]])
    blob = json.dumps(desc, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def is_loopback_host(host: str) -> bool:
    """Shared trust guard for pickled-payload services (graphics client,
    remote forge): one home so loopback policy cannot drift per-module."""
    return host in ("127.0.0.1", "localhost", "::1", "0.0.0.0")


def handshake_request(workflow) -> dict:
    """The slave's first message (the Client's ``register``)."""
    return {"cmd": "register", "version": PROTOCOL_VERSION,
            "workflow_digest": workflow_digest(workflow)}


def check_handshake(req: dict, workflow) -> Optional[str]:
    """Server-side validation of a register request; returns the refusal
    reason, or None when the peer is compatible."""
    v = req.get("version")
    if v != PROTOCOL_VERSION:
        return (f"protocol version mismatch: master speaks "
                f"{PROTOCOL_VERSION}, slave sent {v!r}")
    theirs = req.get("workflow_digest")
    mine = workflow_digest(workflow)
    if theirs != mine:
        return (f"workflow digest mismatch: master runs {mine}, "
                f"slave runs {theirs!r} — same trainable graph "
                f"(layer names/shapes/hyperparameters) required")
    return None
