"""Shared master/slave wire protocol (rebuild of ``veles/network_common.py``,
SURVEY.md §2.1 "Network common": handshake, endpoint IDs).

The reference's NetworkAgent performed a handshake before any job traffic;
the rebuild's equivalent is a version + config-digest exchange on the
``register`` command: a slave built against a different protocol revision or
a different ``root`` config tree is refused with a human-readable reason
instead of failing confusingly mid-training (VERDICT r2 missing #5).

Payloads stay pickle-over-ZMQ like the reference (trusted-cluster
assumption, documented in server.py).
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

#: bump on any incompatible change to the job/update message schema
PROTOCOL_VERSION = 1

#: config keys that are legitimately host-local (each peer has its own
#: paths/dirs) and must not make otherwise-identical configs "mismatch"
_HOST_LOCAL_KEYS = frozenset({"dirs", "data_path", "snapshot",
                              "file_path", "base_dir"})


def _scrub(node):
    """Drop host-local keys recursively before digesting."""
    if isinstance(node, dict):
        return {k: _scrub(v) for k, v in sorted(node.items())
                if k not in _HOST_LOCAL_KEYS}
    return node


def config_digest(tree=None) -> str:
    """Stable short digest of the *workflow-relevant* config tree — master
    and slaves must run the same model/training config for weight deltas
    to be meaningful, but host-local paths (snapshot dirs, data_path) may
    differ per machine and are excluded."""
    if tree is None:
        from znicz_tpu.core.config import root

        tree = root
    blob = json.dumps(_scrub(tree.to_dict()), sort_keys=True,
                      default=repr).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def is_loopback_host(host: str) -> bool:
    """Shared trust guard for pickled-payload services (graphics client,
    remote forge): one home so loopback policy cannot drift per-module."""
    return host in ("127.0.0.1", "localhost", "::1", "0.0.0.0")


def handshake_request() -> dict:
    """The slave's first message (the Client's ``register``)."""
    return {"cmd": "register", "version": PROTOCOL_VERSION,
            "config_digest": config_digest()}


def check_handshake(req: dict) -> Optional[str]:
    """Server-side validation of a register request; returns the refusal
    reason, or None when the peer is compatible."""
    v = req.get("version")
    if v != PROTOCOL_VERSION:
        return (f"protocol version mismatch: master speaks "
                f"{PROTOCOL_VERSION}, slave sent {v!r}")
    theirs = req.get("config_digest")
    mine = config_digest()
    if theirs != mine:
        return (f"config digest mismatch: master runs {mine}, "
                f"slave runs {theirs!r} — same workflow config required")
    return None
