"""Shared master/slave wire protocol (rebuild of ``veles/network_common.py``,
SURVEY.md §2.1 "Network common": handshake, endpoint IDs).

The reference's NetworkAgent performed a handshake before any job traffic;
the rebuild's equivalent is a version + workflow-digest exchange on the
``register`` command: a slave built against a different protocol revision
or a different trainable graph is refused with a human-readable reason
instead of corrupting weights mid-training (VERDICT r2 missing #5).

Since protocol v3, payloads are MULTIPART tensor frames (metadata +
zero-copy buffers, parallel/wire.py); only the small metadata frame
stays pickle (trusted-cluster assumption, documented in server.py).
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

#: bump on any incompatible change to the job/update message schema.
#: v2 (fault-tolerance rev): an unregistered peer's job/update gets
#: ``{"unregistered": True}`` instead of ``{"done": True}`` (a slave must
#: re-register after a master restart, not exit); refused frames reply
#: ``{"bad_frame": True}``; quarantined deltas reply
#: ``{"quarantined": True}``; the register reply carries ``resumed`` and
#: ``epoch`` so a reconnecting slave can tell a crash-resumed master from
#: a fresh one.
#: v3 (wire rev, parallel/wire.py): messages are ZMQ multipart — one
#: metadata frame (command + tensor manifest: names/shapes/dtypes/
#: scales) plus one raw zero-copy buffer frame per tensor; deltas may be
#: bf16/int8 with per-tensor absmax scales + client-side error-feedback
#: residuals; params broadcasts may be zlib/lz4-compressed.  A v2 peer
#: (single-pickle framing, version 2) is refused at register with a
#: reason it can still decode (the master answers legacy-framed requests
#: in legacy framing).
PROTOCOL_VERSION = 3


#: structural attributes that define a unit's computation (beyond its
#: param shapes): conv/pool geometry, dropout rate, LRN constants, …
_UNIT_STRUCT_ATTRS = ("kx", "ky", "sliding", "padding", "n_kernels",
                      "dropout_ratio", "alpha", "beta", "n", "k",
                      "output_sample_shape", "heads", "head_dim", "causal",
                      "weights_transposed")


def _unit_fingerprint(f) -> list:
    """The unit's computational identity: class, IO shapes, structural
    attributes, and (for weighted units) param shapes."""
    attrs = []
    for a in _UNIT_STRUCT_ATTRS:
        v = getattr(f, a, None)
        if v is not None:
            attrs.append([a, list(v) if isinstance(v, (tuple, list))
                          else v])
    shapes = sorted((k, list(arr.shape)) for k, arr in f.params().items()) \
        if f.has_weights else []
    io = [list(f.input.shape) if getattr(f, "input", None) is not None
          else None,
          list(f.output.shape) if getattr(f, "output", None) is not None
          and f.output.mem is not None else None]
    return [f.name, type(f).__name__, io, attrs, shapes]


def workflow_digest(workflow) -> str:
    """Stable short digest of the BUILT graph — the compatibility contract
    for shipping weights/deltas between peers: every forward unit's class,
    IO shapes, structural attributes (conv/pool geometry, dropout rate,
    LRN constants) and param shapes, plus each GD twin's hyperparameters.
    A mismatch anywhere means the two peers compute different functions,
    so their gradients must not be mixed.  Deliberately NOT a digest of
    the global config tree: that tree also carries host-local paths and
    the defaults of whichever sample modules happen to be imported, which
    made legitimately-identical deployments mismatch."""
    desc = [_unit_fingerprint(f) for f in workflow.forwards]
    for gd in getattr(workflow, "gds", []) or []:
        if gd.forward.has_weights:
            # the CONFIGURED hypers (frozen at initialize), not the live
            # fields: a LearningRateAdjust schedule mutates learning_rate
            # every step, and hashing the mutated value made a legitimate
            # peer (slave re-registering mid-training) mismatch a fresh
            # replica of the identical graph (ADVICE r3)
            hypers = gd.initial_hypers
            if hypers is None:
                import numpy as _np

                # same float32 round-trip as _hypers()/initial_hypers, so
                # a digest computed before initialize matches one computed
                # after on the identical graph
                hypers = tuple(float(_np.float32(v)) for v in (
                    gd.learning_rate, gd.learning_rate_bias,
                    gd.weights_decay, gd.weights_decay_bias,
                    gd.l1_vs_l2, gd.gradient_moment,
                    gd.gradient_moment_bias, gd.gradient_clip))
            desc.append([gd.forward.name, type(gd).__name__,
                         [round(float(v), 12) for v in hypers]])
    blob = json.dumps(desc, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def bind_with_retry(sock, endpoint: str, attempts: int = 40,
                    delay_s: float = 0.05) -> None:
    """Bind a ZMQ socket, retrying ONLY the EADDRINUSE race a restarted
    peer has with its dying predecessor's port release — any other bind
    error (bad host, EACCES) is permanent and surfaces immediately.
    One home for the policy (master's REP loop and relay nodes)."""
    import time

    import zmq

    for attempt in range(attempts):
        try:
            sock.bind(endpoint)
            return
        except zmq.error.ZMQError as exc:
            if exc.errno != zmq.EADDRINUSE or attempt == attempts - 1:
                raise
            time.sleep(delay_s)


def make_poller(*sockets):
    """One home for the poll-loop registration convention (the first
    concrete step toward ROADMAP item 4's single dataplane, now landed
    as ``znicz_tpu/transport`` — ISSUE 14): every ZMQ serve loop rides
    ``transport.TransportLoop``, which registers its sockets POLLIN
    through here, and znicz-lint's ``transport-core`` rule flags any
    NEW raw ``zmq.Poller()``/``.bind()``/poller dispatch loop forked
    outside the transport package."""
    import zmq

    poller = zmq.Poller()
    for sock in sockets:
        poller.register(sock, zmq.POLLIN)
    return poller


def is_loopback_host(host: str) -> bool:
    """Shared trust guard for pickled-payload services (graphics client,
    remote forge): one home so loopback policy cannot drift per-module."""
    return host in ("127.0.0.1", "localhost", "::1", "0.0.0.0")


def handshake_request(workflow, mesh=None) -> dict:
    """The slave's first message (the Client's ``register``).  ``mesh``
    (``{"data": dp, "model": mp}``, pod-sliced slaves only) piggybacks
    the leaf's slice shape for web_status; absent for single-device
    slaves and ignored by older masters (check_handshake validates only
    version + digest)."""
    req = {"cmd": "register", "version": PROTOCOL_VERSION,
           "workflow_digest": workflow_digest(workflow)}
    if mesh:
        req["mesh"] = dict(mesh)
    return req


def check_handshake(req: dict, workflow) -> Optional[str]:
    """Server-side validation of a register request; returns the refusal
    reason, or None when the peer is compatible."""
    v = req.get("version")
    if v != PROTOCOL_VERSION:
        hint = (" — v2 speaks the single-frame pickle wire; upgrade the "
                "slave to the v3 multipart tensor-frame wire"
                if v == 2 else "")
        return (f"protocol version mismatch: master speaks "
                f"{PROTOCOL_VERSION}, slave sent {v!r}{hint}")
    theirs = req.get("workflow_digest")
    mine = workflow_digest(workflow)
    if theirs != mine:
        return (f"workflow digest mismatch: master runs {mine}, "
                f"slave runs {theirs!r} — same trainable graph "
                f"(layer names/shapes/hyperparameters) required")
    return None
