"""Kohonen self-organizing map units (rebuild of ``znicz/kohonen.py``,
SURVEY.md §2.2 "Kohonen / SOM").

The reference pair:

  - ``KohonenForward`` — winner-take-all: per sample, the index of the
    nearest neuron on an (sx, sy) grid (argmin L2); accumulates per-neuron
    hit counts (the ``KohonenHits`` plot input).
  - ``KohonenTrainer`` — unsupervised batch update with a gaussian
    neighborhood whose radius and learning rate decay over time:
        w += lr(t) · Σ_b gravity(i, winner_b; σ(t)) · (x_b − w_i) / B
    No GD chain, no evaluator — the trainer IS the learning rule
    (SURVEY.md §1: non-GD learner).

TPU-native: one jitted step does distances (a single (B,N) matmul-style
reduction on the MXU), argmin, neighborhood weighting and the batched
outer-product update — the reference's four OCL kernels fused by XLA.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from znicz_tpu.core import prng
from znicz_tpu.core.units import Unit
from znicz_tpu.memory import Array
from znicz_tpu.nn_units import ForwardBase


def grid_coords(sy: int, sx: int) -> np.ndarray:
    """(N, 2) float coords of the SOM grid, row-major."""
    yy, xx = np.mgrid[0:sy, 0:sx]
    return np.stack([yy.reshape(-1), xx.reshape(-1)], axis=1).astype(
        np.float32)


class KohonenBase:
    @staticmethod
    def distances(x, w):
        """(B, N) squared L2 distances; expanded form runs the x·wᵀ term on
        the MXU instead of materializing (B, N, D) diffs in HBM."""
        import jax.numpy as jnp

        x2 = jnp.sum(jnp.square(x), axis=1, keepdims=True)      # (B, 1)
        w2 = jnp.sum(jnp.square(w), axis=1)[None, :]            # (1, N)
        cross = x @ w.T                                          # MXU
        return x2 + w2 - 2.0 * cross


class KohonenForward(ForwardBase, KohonenBase):
    """Winner indices + hit accumulation.  ``weights`` are linked from the
    trainer (shared Array) or owned if standalone."""

    def __init__(self, workflow=None, name=None, shape=(8, 8),
                 weights_from: Optional[Unit] = None, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.sy, self.sx = int(shape[0]), int(shape[1])
        self.n_neurons = self.sy * self.sx
        self.include_bias = False
        if weights_from is not None:
            self.weights = weights_from.weights
        self.hits = Array()
        self.total = 0                       # samples accumulated into hits
        #: link from loader.minibatch_size so padded tail rows aren't counted
        self.batch_size: Optional[int] = None

    def output_shape_for(self, in_shape):
        return (in_shape[0],)

    def apply(self, params, x):
        import jax.numpy as jnp

        d = self.distances(x.reshape(x.shape[0], -1), params["weights"])
        return jnp.argmin(d, axis=1)

    def initialize(self, device=None, **kwargs):
        if self.weights.mem is None:
            self.init_weights((self.n_neurons, self.input.sample_size), ())
        self.hits.mem = np.zeros(self.n_neurons, np.int64)
        self.create_output()
        super().initialize(device=device, **kwargs)
        self.hits.initialize(device)

    def create_output(self):
        self.output.mem = np.zeros(self.input.shape[0], np.int32)

    def reset_hits(self):
        self.hits.map_invalidate()[...] = 0
        self.total = 0

    def run(self):
        super().run()
        winners = np.asarray(self.output.map_read())
        if self.batch_size is not None:
            winners = winners[:int(self.batch_size)]
        hits = self.hits.map_write()
        np.add.at(hits, winners, 1)
        self.total += len(winners)


class KohonenTrainer(Unit, KohonenBase):
    """Batch SOM trainer with exponentially decaying radius and lr."""

    def __init__(self, workflow=None, name=None, shape=(8, 8),
                 learning_rate=0.1, radius: Optional[float] = None,
                 decay_epochs=20, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        self.sy, self.sx = int(shape[0]), int(shape[1])
        self.n_neurons = self.sy * self.sx
        self.input: Optional[Array] = None     # linked: minibatch_data
        self.batch_size = 0                    # linked: minibatch_size
        self.weights = Array()
        self.learning_rate = float(learning_rate)
        self.radius0 = float(radius if radius is not None
                             else max(self.sy, self.sx) / 2.0)
        self.decay_epochs = float(decay_epochs)
        self.epoch_number = 0                  # link from loader; drives decay
        #: mean squared quantization error of the last minibatch
        self.qerror = 0.0
        self._coords = grid_coords(self.sy, self.sx)
        self._compiled = None

    def current_lr_sigma(self):
        t = float(self.epoch_number)
        decay = np.exp(-t / self.decay_epochs)
        lr = self.learning_rate * decay
        sigma = max(self.radius0 * decay, 0.5)
        return np.float32(lr), np.float32(sigma)

    @staticmethod
    def _step(w, x, coords, batch_size, lr, sigma):
        import jax.numpy as jnp

        xf = x.reshape(x.shape[0], -1)
        n = xf.shape[0]
        valid = (jnp.arange(n) < batch_size)[:, None]
        d = KohonenBase.distances(xf, w)
        winners = jnp.argmin(d, axis=1)                       # (B,)
        qerr = jnp.sum(jnp.min(d, axis=1) * valid[:, 0]) / \
            jnp.maximum(batch_size, 1)
        # gravity: (B, N) gaussian of grid distance to each winner
        gd = jnp.sum(jnp.square(coords[winners][:, None, :]
                                - coords[None, :, :]), axis=-1)
        g = jnp.exp(-gd / (2.0 * sigma * sigma)) * valid
        # batched update: w_i += lr * sum_b g[b,i] (x_b - w_i) / B
        num = g.T @ xf                                         # (N, D) MXU
        den = jnp.sum(g, axis=0)[:, None]                      # (N, 1)
        w_new = w + lr * (num - den * w) / jnp.maximum(batch_size, 1)
        return w_new, qerr

    def initialize(self, device=None, **kwargs):
        super().initialize(device=device, **kwargs)
        if self.weights.mem is None:
            gen = prng.get(self.name)
            self.weights.mem = gen.uniform(
                -0.1, 0.1, (self.n_neurons, self.input.sample_size))
        self.weights.initialize(device)

    def run(self):
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self._step)
        lr, sigma = self.current_lr_sigma()
        w_new, qerr = self._compiled(
            self.weights.devmem, self.input.devmem,
            np.asarray(self._coords), np.int32(int(self.batch_size)),
            lr, sigma)
        self.weights.devmem = w_new
        self.qerror = float(qerr)


class KohonenDecision(Unit):
    """Training control for the SOM loop: tracks mean quantization error
    per epoch; completes on max_epochs (the reference stopped on epochs /
    weight-delta)."""

    def __init__(self, workflow=None, name=None, max_epochs=10, **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        from znicz_tpu.core.mutable import Bool

        self.max_epochs = int(max_epochs)
        self.complete = Bool(False)
        self.epoch_ended = Bool(False)
        self.last_minibatch = False            # link from loader
        self.epoch_number = 0                  # link from loader
        self.qerror = 0.0                      # link from trainer
        self._acc = 0.0
        self._batches = 0
        self.epoch_qerror = []
        self.on_epoch_end = []

    def run(self):
        self._acc += float(self.qerror)
        self._batches += 1
        self.epoch_ended.set(False)
        if self.last_minibatch:
            self.epoch_qerror.append(self._acc / max(1, self._batches))
            self._acc, self._batches = 0.0, 0
            self.epoch_ended.set(True)
            self.complete.set(self.epoch_number + 1 >= self.max_epochs)
            self.info("epoch %d  qerror=%.6g", self.epoch_number,
                      self.epoch_qerror[-1])
            for cb in self.on_epoch_end:
                cb(self)
