"""Pooling backward units (rebuild of ``znicz/gd_pooling.py``).

``GDMaxPooling`` / ``GDMaxAbsPooling`` (and the stochastic twins) route
err_output to the input positions *recorded by the forward* (the reference's
offset arrays) via a scatter-add; ``GDAvgPooling`` is the vjp of the forward
average (uniform spread over each window's real elements).  Pooling has no
params, so these GDs only produce err_input.
"""

from __future__ import annotations

from znicz_tpu.nn_units import GradientDescentBase


class GDPooling(GradientDescentBase):
    """Base: no params; err_input only."""

    def __init__(self, workflow=None, name=None, forward=None, **kwargs):
        kwargs.setdefault("apply_gradient", False)
        super().__init__(workflow=workflow, name=name, forward=forward,
                         **kwargs)


class GDAvgPooling(GDPooling):
    """vjp of the forward average — uniform spread / real-element count."""


class GDMaxPoolingBase(GDPooling):
    """Scatter err_output to the forward-recorded offsets (shared geometry
    lives on PoolingBase.scatter_at_offsets).

    NOTE: on the fused engine with ``root.common.engine.fused_elementwise``
    the conv1/conv2 pool backward runs inside the fused block kernel's
    custom vjp (pallas_fused_block) — this unit is bypassed there along
    with LRNormalizerBackward and the conv GD's activation term.  Offsets
    exist only where a forward ``run()`` recorded them (the unit path)."""

    def run(self):
        if not self.forward.input_offset:
            raise RuntimeError(
                f"{self.name}: the paired forward recorded no pooling "
                "offsets — run the forward unit first (offsets are unit-"
                "path state; the fused engine's pool backward never "
                "materializes them)")
        if self._compiled is None:
            import jax
            self._compiled = jax.jit(self.forward.scatter_at_offsets)
        self.err_input.devmem = self._compiled(
            self.err_output.devmem, self.forward.input_offset.devmem)


class GDMaxPooling(GDMaxPoolingBase):
    pass


class GDMaxAbsPooling(GDMaxPoolingBase):
    pass


class GDStochasticPooling(GDMaxPoolingBase):
    pass


class GDStochasticAbsPooling(GDMaxPoolingBase):
    pass


GD_BY_FORWARD_POOLING = {
    "MaxPooling": GDMaxPooling,
    "MaxAbsPooling": GDMaxAbsPooling,
    "AvgPooling": GDAvgPooling,
    "StochasticPooling": GDStochasticPooling,
    "StochasticAbsPooling": GDStochasticAbsPooling,
}
