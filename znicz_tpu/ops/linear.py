"""Dense (fully-connected) op — the reference's clBLAS/cuBLAS GEMM path
(SURVEY.md §2.3 row "GEMM") becomes one ``jnp.dot`` that XLA lowers onto the
MXU.  bfloat16 matmul with float32 accumulation is the TPU-native precision
policy; params stay float32.
"""

from __future__ import annotations

import jax.numpy as jnp


def linear(x, w, b=None, *, weights_transposed: bool = False,
           compute_dtype=None):
    """``y = x @ W^T + b`` over flattened trailing dims.

    The reference stored weights as (out, in) and ran GEMM with transpose
    flags (``weights_transposed`` flips storage to (in, out) — kept for
    parity with its config surface).
    """
    x2 = x.reshape(x.shape[0], -1)
    if compute_dtype is not None:
        x2 = x2.astype(compute_dtype)
        w = w.astype(compute_dtype)
    # bf16 operands keep a bf16 output (the MXU still accumulates f32
    # internally); forcing an f32 output would make the vjp cotangents f32
    # against bf16 weights and break mixed-precision backward convs/dots.
    pref = jnp.float32 if x2.dtype == jnp.float32 else None
    y = jnp.dot(x2, w if weights_transposed else w.T,
                preferred_element_type=pref)
    if b is not None:
        y = y + b
    return y


def seq_linear(x, w, b=None, *, weights_transposed: bool = False):
    """Position-wise dense: ``y = x @ W^T + b`` over the LAST dim only,
    leading (batch, seq, ...) dims preserved — the variable-length
    counterpart of :func:`linear`, whose flatten is exactly what a
    sequence input cannot have (ISSUE 15).  The ONE home of the
    transpose/bias convention for every seq unit and the fused
    trainer's seq branches."""
    y = x @ (w if weights_transposed else w.T)
    return y if b is None else y + b
