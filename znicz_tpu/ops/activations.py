"""Activation functions with the reference's exact constants.

The reference's elementwise kernels (SURVEY.md §2.3) implement:

  - ``tanh``        — LeCun's scaled tanh ``1.7159 * tanh(2/3 x)`` (the
    constants that make unit outputs have ~unit variance at init);
  - ``RELU``        — the *soft* relu ``log(1 + e^x)`` (reference's "RELU");
  - ``StrictRELU``  — ``max(0, x)`` (what everyone else calls relu);
  - ``sigmoid``     — logistic;
  - ``log``         — ``log(x + sqrt(x^2 + 1))`` (asinh-style);
  - ``sincos``      — alternating sin/cos by element parity;
  - ``mul``         — elementwise product with a second operand (used by
    gating constructions).

Derivatives are NOT hand-written here: backward units take ``jax.vjp`` of
these functions, so constants can never drift between fwd and bwd.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# LeCun tanh constants (reference kernels hard-code these).
TANH_A = 1.7159
TANH_B = 0.6666


def tanh_scaled(x):
    return TANH_A * jnp.tanh(TANH_B * x)


def relu_log(x):
    """The reference's "RELU": softplus ``log(1 + e^x)`` (numerically safe)."""
    return jax.nn.softplus(x)


def strict_relu(x):
    return jnp.maximum(x, 0.0)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def log_act(x):
    return jnp.log(x + jnp.sqrt(jnp.square(x) + 1.0))


def sincos(x):
    """Even elements -> sin, odd -> cos (reference's SinCos unit)."""
    flat = x.reshape(-1)
    idx = jnp.arange(flat.shape[0])
    out = jnp.where(idx % 2 == 0, jnp.sin(flat), jnp.cos(flat))
    return out.reshape(x.shape)


def softmax(x):
    """Row softmax with the max-subtraction the reference kernel did."""
    return jax.nn.softmax(x, axis=-1)


def identity(x):
    return x


#: name -> fn registry used by StandardWorkflow layer configs.
ACTIVATIONS = {
    "linear": identity,
    "tanh": tanh_scaled,
    "relu": relu_log,
    "strict_relu": strict_relu,
    "sigmoid": sigmoid,
    "log": log_act,
    "sincos": sincos,
    "softmax": softmax,
}
