"""Pure functional op library — the rebuild's replacement for the reference's
hand-written OpenCL/CUDA kernels (SURVEY.md §2.3).

Every op is a pure jax function of explicit arrays, usable three ways:
  1. wrapped by a Unit's per-step jitted ``run`` (unit-at-a-time mode),
  2. composed into one fused jitted train step (StandardWorkflow fast path),
  3. called with numpy inputs for golden-value tests (jax-on-cpu == oracle).
"""

from znicz_tpu.ops.activations import (  # noqa: F401
    ACTIVATIONS,
    relu_log,
    sigmoid,
    sincos,
    softmax,
    strict_relu,
    tanh_scaled,
)
from znicz_tpu.ops.linear import linear  # noqa: F401
