"""Attention ops, single-device and sequence-parallel (ring attention).

The reference has no attention anywhere (SURVEY.md §5 "long-context:
absent") — this is a beyond-reference, TPU-first capability so the
framework handles long sequences at the scale the task demands:

  - ``attention(q, k, v, causal)`` — standard scaled-dot-product MHA core,
    one fused jit (XLA flash-fuses the softmax chain on TPU);
  - ``cache_append(cache, row, t)`` / ``decode_attention(q1, k, v, t)`` —
    the KV-cache decode step (ISSUE 16): append this step's key/value row
    at per-row position ``t``, then attend a length-1 query over the
    prefix ``[0..t]`` of a preallocated cache, the unwritten tail masked
    by ``k_valid``.  O(cache) per emitted token instead of O(seq^2) for a
    re-prefill;
  - ``paged_gather`` / ``paged_append`` / ``paged_decode_attention`` —
    the BLOCK-PAGED pool forms of the above (ISSUE 19): one
    ``(num_pages + 1, page_size, heads, dim)`` pool per layer holds every
    request's cache as page-table-indexed blocks (the last page is pad
    scratch), so requests share read-only prefix pages by table entry
    instead of by copy.  Positions stay GLOBAL (``t`` -> page
    ``t // page_size``, offset ``t % page_size``), which keeps the
    contiguous path's masking — and its bit-exactness contract — intact;
  - ``ring_attention(q, k, v, axis_name, causal)`` — blockwise attention
    for SEQUENCE-PARALLEL inputs: every device of the mesh axis holds a
    sequence shard of q/k/v; k/v blocks rotate around the ring via
    ``lax.ppermute`` (ICI neighbor hops, bandwidth-optimal) while a running
    flash-style online softmax (max/denominator carried per query) keeps
    memory at one block — exact attention over sequences n_devices x
    longer than a chip could hold.  Call inside ``shard_map`` over the
    sequence axis.

Shapes: (batch, seq, heads, head_dim) throughout.
"""

from __future__ import annotations

import math


def attention(q, k, v, causal: bool = False, q_offset=0, k_offset=0,
              k_valid=None):
    """Exact attention; offsets give global positions for causal masking of
    sharded blocks.

    ``k_valid`` is an optional (batch, k) bool mask of which keys exist —
    the variable-length serving plane's padding mask (ISSUE 15): padded
    key positions carry exactly zero probability mass, making each row's
    output a pure function of its OWN unpadded length.

    ``q_offset``/``k_offset`` may be scalars (a sharded block's global
    start) or per-row (batch,) arrays (ISSUE 19's chunked prefill: each
    co-batched row's chunk sits at its own depth).  The scalar path's
    mask is unchanged bit for bit — the row axis merely broadcasts.

    A query row whose keys are ALL masked (the empty-cache decode edge)
    returns zeros rather than NaN: masked scores get a finite fill (not
    ``-inf``, whose ``exp(-inf - -inf)`` poisons the softmax), masked
    probabilities are zeroed explicitly, and the denominator is clamped.
    Rows with at least one valid key are bit-identical to the unguarded
    softmax — the row max is unchanged and the clamped denominator is
    already >= 1."""
    import jax.numpy as jnp

    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    dead = None                                        # (b, h, q, k) bcast
    if causal:
        qpos = jnp.asarray(q_offset)[..., None] + jnp.arange(q.shape[1])
        kpos = jnp.asarray(k_offset)[..., None] + jnp.arange(k.shape[1])
        if qpos.ndim == 1:                             # scalar offset
            qpos = qpos[None]
        if kpos.ndim == 1:
            kpos = kpos[None]
        dead = kpos[:, None, None, :] > qpos[:, None, :, None]
    if k_valid is not None:
        miss = ~k_valid[:, None, None, :]
        dead = miss if dead is None else (dead | miss)
    if dead is not None:
        s = jnp.where(dead, jnp.finfo(s.dtype).min, s)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    if dead is not None:
        p = jnp.where(dead, 0.0, p)
    denom = jnp.sum(p, axis=-1, keepdims=True)
    if dead is not None:
        denom = jnp.maximum(denom, jnp.finfo(p.dtype).tiny)
    p = p / denom
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def cache_append(cache, row, t):
    """Scatter one step's (batch, heads, dim) row into a preallocated
    (batch, cache_len, heads, dim) cache at per-row position ``t``
    ((batch,) int32).  Pure — returns the updated cache."""
    import jax.numpy as jnp

    b = cache.shape[0]
    return cache.at[jnp.arange(b), t].set(row)


def decode_attention(q1, k_cache, v_cache, t):
    """One autoregressive decode step: a length-1 query at per-row global
    position ``t`` attends over cache positions ``[0..t]``; the unwritten
    tail ``(t, cache_len)`` is excluded via ``k_valid``.  ``q1`` is
    (batch, 1, heads, dim), caches (batch, cache_len, heads, dim), ``t``
    (batch,) int32.  Callers append this step's k/v row first (so position
    ``t`` is valid and every row keeps >= 1 valid key).  Equivalent to the
    causal mask at row ``t`` of a full forward, without the O(seq^2)
    score matrix."""
    import jax.numpy as jnp

    cache_len = k_cache.shape[1]
    k_valid = jnp.arange(cache_len)[None, :] <= t[:, None]
    return attention(q1, k_cache, v_cache, k_valid=k_valid)


def paged_gather(pool, table):
    """Gather a per-request contiguous K/V view out of a block-paged
    pool (ISSUE 19).  ``pool`` is (num_pages + 1, page_size, heads, dim)
    — the LAST page is pad scratch — and ``table`` is (batch, P) int32
    page ids listing each row's pages in position order (slots past a
    row's allocation point at scratch).  Returns
    (batch, P * page_size, heads, dim): position ``t`` of row ``i``
    lives at page ``table[i, t // page_size]`` offset ``t % page_size``,
    so downstream masking keeps using GLOBAL positions unchanged."""
    b, npages = table.shape
    page_size = pool.shape[1]
    return pool[table].reshape(b, npages * page_size,
                               pool.shape[2], pool.shape[3])


def paged_append(pool, table, row, t):
    """Scatter one step's (batch, heads, dim) row into the paged pool at
    per-row GLOBAL position ``t``: page ``table[i, t // page_size]``,
    offset ``t % page_size``.  Pure — returns the updated pool.  Rows
    whose table entry is the scratch page (pad rows) scatter there and
    never touch a real page."""
    import jax.numpy as jnp

    b, npages = table.shape
    page_size = pool.shape[1]
    page = table[jnp.arange(b), jnp.clip(t // page_size, 0, npages - 1)]
    return pool.at[page, t % page_size].set(row)


def paged_decode_attention(q1, k_pool, v_pool, table, t):
    """:func:`decode_attention` over the block-paged pool: gather each
    row's pages into its contiguous view, then run the SAME masked
    softmax over ``[0..t]`` — the unwritten/stale page tail past ``t``
    (including scratch table slots) is excluded by ``k_valid`` exactly
    as the contiguous path excludes its unwritten tail, so paging
    preserves the per-decoded-token bit-exactness contract."""
    return decode_attention(q1, paged_gather(k_pool, table),
                            paged_gather(v_pool, table), t)


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Exact attention over a sequence sharded on ``axis_name``.

    Each step attends the local q block to the current k/v block, folds the
    result into flash-style accumulators, then passes the k/v block to the
    next device on the ring.  After n steps every q saw every k/v.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    neg = jnp.finfo(jnp.float32.dtype).min

    qpos = my * t + jnp.arange(t)                      # global q positions

    def step(i, carry):
        m, l, acc, k_blk, v_blk = carry
        src = (my - i) % n                             # who produced k_blk
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk) * scale
        kmask = None
        if causal:
            kpos = src * t + jnp.arange(t)
            kmask = (kpos[None, None, None, :]
                     > qpos[None, None, :, None])
            s = jnp.where(kmask, neg, s)
        blk_max = jnp.max(s, axis=-1)                  # (b, h, q)
        m_new = jnp.maximum(m, blk_max)
        p = jnp.exp(s - m_new[..., None])
        if kmask is not None:
            # fully-masked blocks leave m_new at neg; exp(neg-neg)=1 would
            # leak mass — zero masked entries explicitly
            p = jnp.where(kmask, 0.0, p)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = (acc * corr[..., None]
                   + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk))
        perm = [(j, (j + 1) % n) for j in range(n)]    # ring hop
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return m_new, l_new, acc_new, k_next, v_next

    def vary(x):
        """Mark a fresh array as varying over the mesh axis (newer jax
        shard_map tracks varying-axis types; loop carries must match)."""
        try:
            return lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):
            return x

    m0 = vary(jnp.full((b, h, t), neg, jnp.float32))
    l0 = vary(jnp.zeros((b, h, t), jnp.float32))
    acc0 = vary(jnp.zeros((b, h, t, d), jnp.float32))
    m, l, acc, _, _ = lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]       # (b, h, q, d)
    return out.transpose(0, 2, 1, 3)                   # (b, q, h, d)
