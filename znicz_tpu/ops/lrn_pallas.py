"""Pallas TPU kernel for across-channel LRN (AlexNet-style; the hot
normalization in SURVEY §2.2 "LRN" — reference shipped hand-written OCL/CU
kernels for it; this is the TPU-native equivalent, see
/opt/skills/guides/pallas_guide.md).

Forward:  y = x * (k + alpha * sum_{j in win(c)} x_j^2) ** (-beta)
Backward: dx = dy * s^(-beta) - 2*alpha*beta * x * W(dy * x * s^(-beta-1))
where s = k + alpha * W(x^2) and W is the same n-channel windowed sum.

The tensor is processed as (rows, C) tiles resident in VMEM: one pass for
the forward, one for the backward, with the windowed channel sum unrolled
(n is tiny and static).  The XLA fallback (`znicz_tpu/lrn.py`) remains the
oracle; `lrn(x, ...)` is exactly substitutable and carries a custom_vjp.
On non-TPU backends the kernel runs in interpreter mode (tests), or
callers just use the jnp path.

Measured honestly (bench.py, 1x v5e, 2026-07-30): the AlexNet step runs
8.1k img/s with this kernel vs 10.8k with the XLA path — XLA fuses its
LRN into neighboring ops and needs none of the flatten/pad reshapes, so
the jnp path stays the DEFAULT (`root.common.engine.pallas_lrn` opts in).
Kept as the Pallas example/capability with an exact custom-vjp, and as
the starting point if a future model makes LRN the actual bottleneck.
"""

from __future__ import annotations

import functools

import numpy as np

TILE_R = 1024          # rows per grid step (multiple of 8 for f32 tiling)


def windowed_channel_sum(t, n):
    """Sum over the n-channel window centered on the LAST axis (zero-padded
    ends), unrolled with static shifts — identical summation order to the
    jnp oracle in znicz_tpu/lrn.py.  Rank-general; the ONE home of the
    shift-unrolled window sum, shared by this kernel and the fused
    conv-block kernel (znicz_tpu/pallas_fused_block.py) whose parity
    guarantees depend on this exact order."""
    import jax.numpy as jnp

    half = n // 2
    acc = None
    for j in range(n):
        o = j - half                    # offset: acc_c += t_{c+o}
        if o == 0:
            part = t
        elif o > 0:
            part = jnp.concatenate(
                [t[..., o:], jnp.zeros(t.shape[:-1] + (o,), t.dtype)],
                axis=-1)
        else:
            part = jnp.concatenate(
                [jnp.zeros(t.shape[:-1] + (-o,), t.dtype), t[..., :o]],
                axis=-1)
        acc = part if acc is None else acc + part
    return acc


_windowed = windowed_channel_sum


def inv_pow_rsqrt(s, beta: float):
    """``s ** -beta`` via ``rsqrt(s)*sqrt(rsqrt(s))`` for the reference
    default beta=0.75 (two pipelined VPU ops instead of the exp/log
    ``pow`` expansion); plain ``pow`` otherwise.  Shared by lrn.py's jnp
    path (its config-gated wrapper) and the fused conv-block kernel."""
    import jax
    import jax.numpy as jnp

    if beta == 0.75:
        r = jax.lax.rsqrt(s)
        return r * jnp.sqrt(r)
    return jnp.power(s, -beta)


def _fwd_kernel(n, alpha, beta, k, x_ref, y_ref):
    import jax.numpy as jnp

    x = x_ref[:]
    s = k + alpha * _windowed(x * x, n)
    y_ref[:] = x * jnp.power(s, -beta)


def _bwd_kernel(n, alpha, beta, k, x_ref, dy_ref, dx_ref):
    import jax.numpy as jnp

    x = x_ref[:]
    dy = dy_ref[:]
    s = k + alpha * _windowed(x * x, n)
    sb = jnp.power(s, -beta)
    t = dy * x * sb / s                 # dy * x * s^(-beta-1)
    dx_ref[:] = dy * sb - (2.0 * alpha * beta) * x * _windowed(t, n)


def _pallas_2d(kernel, rows_c_arrays, interpret):
    """Run a rows x C kernel tiled over TILE_R-row blocks."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, C = rows_c_arrays[0].shape
    spec = pl.BlockSpec((TILE_R, C), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(R // TILE_R,),
        in_specs=[spec] * len(rows_c_arrays),
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((R, C), rows_c_arrays[0].dtype),
        interpret=interpret,
    )(*rows_c_arrays)


def _as_rows(x):
    """(..., C) -> (rows_padded, C), plus the original row count."""
    import jax.numpy as jnp

    C = x.shape[-1]
    flat = x.reshape(-1, C)
    R = flat.shape[0]
    pad = (-R) % TILE_R
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad, C), flat.dtype)], axis=0)
    return flat, R


def _use_interpret() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def _make():
    import jax

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
    def lrn(x, n, alpha, beta, k):
        flat, R = _as_rows(x)
        y = _pallas_2d(functools.partial(_fwd_kernel, n, alpha, beta, k),
                       [flat], _use_interpret())
        return y[:R].reshape(x.shape)

    def fwd(x, n, alpha, beta, k):
        return lrn(x, n, alpha, beta, k), x

    def bwd(n, alpha, beta, k, x, dy):
        import jax.numpy as jnp

        flat_x, R = _as_rows(x)
        flat_dy, _ = _as_rows(dy)
        dx = _pallas_2d(functools.partial(_bwd_kernel, n, alpha, beta, k),
                        [flat_x, flat_dy], _use_interpret())
        return (dx[:R].reshape(x.shape).astype(x.dtype),)

    lrn.defvjp(fwd, bwd)
    return lrn


_lrn = None


def lrn(x, n=5, alpha=1e-4, beta=0.75, k=2.0):
    """Pallas LRN with custom vjp; drop-in for the jnp forward in
    znicz_tpu/lrn.py (tested for forward and gradient agreement)."""
    global _lrn
    if _lrn is None:
        _lrn = _make()
    return _lrn(x, int(n), float(alpha), float(beta), float(k))
