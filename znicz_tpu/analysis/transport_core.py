"""transport-core: no NEW dataplane machinery outside znicz_tpu/transport.

The ``zmq-loop`` rule (PR 11) kept new planes on
``network_common.bind_with_retry``/``make_poller``; ISSUE 14 finished
ROADMAP item 4 — ONE event-loop transport core
(:mod:`znicz_tpu.transport`) that the master, relays, serving frontend,
replica balancer, chaos drivers and both clients all ride.  This rule
is the grown version: every way a plane used to re-fork the dataplane
is now flagged, with ZERO baseline entries (the codebase was converted,
not baselined).

Flagged (outside ``network_common.py`` and ``transport/``):

  - ``zmq.Poller()`` instantiation — ride
    ``transport.TransportLoop`` (or, at the lowest level,
    ``network_common.make_poller``);
  - ``.bind(...)`` on a ZMQ socket — a receiver assigned from a
    ``*.socket(...)`` call in the same function scope — use
    ``bind_with_retry`` / the TransportLoop bind factories;
  - ``.poll(...)`` on a POLLER (a receiver assigned from
    ``make_poller(...)`` or ``zmq.Poller()`` in the same scope) — a
    hand-rolled dispatch loop; ride ``TransportLoop.run`` with
    handlers and ticks;
  - ``time.sleep(...)`` of an expression containing a ``**`` power —
    a raw exponential backoff; use ``transport.RetryPolicy`` (one
    curve, constants per plane, deterministic jitter);
  - a socket created (``*.socket(...)``) AND ``.close()``d inside ONE
    loop body — the hand-rolled fresh-socket reconnect cycle; ride
    ``transport.Endpoint`` (reconnect + backoff + resend-same-bytes +
    breaker in one home).

Deliberately silent: ``.connect(...)`` (no restart race), ``.poll()``
on a bare SOCKET (a single-socket wait — graphics, the serving
client's pump — is not a dispatch loop), ``.bind`` on non-socket
receivers, and create/close in straight-line lifecycle code (creation
outside a loop never matches the reconnect signature).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Checker, Finding, Module

RULE = "transport-core"

#: the sanctioned homes for raw dataplane machinery
EXEMPT_FILES = ("network_common.py",)
EXEMPT_DIRS = ("transport/",)


def _exempt(rel: str) -> bool:
    return rel in EXEMPT_FILES or any(rel.startswith(d)
                                      for d in EXEMPT_DIRS)


def _receiver_key(node: ast.expr) -> str | None:
    """A trackable receiver: a bare name or a ``self.<attr>`` chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    return None


def _scope_nodes(body: Iterable[ast.stmt]):
    """Every node of one scope, PRUNING nested function bodies — they
    are their own scopes and are scanned separately (``ast.walk`` has
    no pruning, so a naive walk double-counts)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue                # a nested scope: scanned separately
        stack.extend(ast.iter_child_nodes(node))


def _assigns_from(body: Iterable[ast.stmt], match) -> set:
    """Receiver keys assigned from a call ``match(call)`` approves,
    anywhere in this scope (order-insensitive)."""
    out = set()
    for node in _scope_nodes(body):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and match(node.value)):
            for target in node.targets:
                key = _receiver_key(target)
                if key is not None:
                    out.add(key)
    return out


def _is_socket_call(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Attribute) \
        and call.func.attr == "socket"


def _is_poller_call(call: ast.Call) -> bool:
    """``zmq.Poller()`` or ``make_poller(...)`` (bare or attribute)."""
    func = call.func
    if isinstance(func, ast.Attribute):
        if func.attr == "Poller" and isinstance(func.value, ast.Name) \
                and func.value.id == "zmq":
            return True
        return func.attr == "make_poller"
    return isinstance(func, ast.Name) and func.id == "make_poller"


def _is_time_sleep(call: ast.Call) -> bool:
    func = call.func
    return (isinstance(func, ast.Attribute) and func.attr == "sleep"
            and isinstance(func.value, ast.Name)
            and func.value.id == "time")


def _has_power(node: ast.expr) -> bool:
    return any(isinstance(n, ast.BinOp) and isinstance(n.op, ast.Pow)
               for n in ast.walk(node))


class TransportCoreChecker(Checker):
    name = RULE

    def check(self, module: Module):
        if _exempt(module.rel):
            return []
        findings: List[Finding] = []
        # Poller instantiation + raw backoff sleeps: anywhere
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute)
                    and func.attr == "Poller"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "zmq"):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "raw zmq.Poller() forked outside the transport "
                    "core — ride transport.TransportLoop (ROADMAP "
                    "item 4, landed in ISSUE 14)"))
            elif _is_time_sleep(node) and node.args \
                    and _has_power(node.args[0]):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "raw exponential backoff sleep outside the "
                    "transport core — use transport.RetryPolicy (one "
                    "backoff curve, per-plane constants, deterministic "
                    "jitter)"))
        # per-scope checks
        scopes: List[Iterable[ast.stmt]] = [module.tree.body]
        scopes += [n.body for n in ast.walk(module.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        for body in scopes:
            sockets = _assigns_from(body, _is_socket_call)
            pollers = _assigns_from(body, _is_poller_call)
            for node in _scope_nodes(body):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                recv = _receiver_key(node.func.value)
                if node.func.attr == "bind" and recv in sockets:
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        "raw ZMQ socket .bind() outside the transport "
                        "core — use network_common.bind_with_retry / "
                        "TransportLoop's bind factories: a restarted "
                        "peer races its predecessor's port release "
                        "(EADDRINUSE), and the retry policy has ONE "
                        "home"))
                elif node.func.attr == "poll" and recv in pollers:
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        "hand-rolled poller dispatch loop outside the "
                        "transport core — ride transport."
                        "TransportLoop.run(handlers, ticks): chaos "
                        "hooks, telemetry and dispatch conventions "
                        "come free there (ISSUE 14)"))
            # reconnect cycle: socket created AND closed inside ONE
            # loop body — the fresh-socket retry idiom.  Deduped by
            # close-site line: nested loops both contain the same
            # close() node, and one violation is one finding.
            seen_closes: set = set()
            for node in _scope_nodes(body):
                if not isinstance(node, (ast.While, ast.For)):
                    continue
                loop_sockets = _assigns_from(node.body, _is_socket_call)
                if not loop_sockets:
                    continue
                for sub in _scope_nodes(node.body):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "close"
                            and _receiver_key(sub.func.value)
                            in loop_sockets
                            and sub.lineno not in seen_closes):
                        seen_closes.add(sub.lineno)
                        findings.append(Finding(
                            RULE, module.rel, sub.lineno,
                            "hand-rolled reconnect cycle (socket "
                            "created and closed inside one retry "
                            "loop) outside the transport core — ride "
                            "transport.Endpoint: fresh-socket "
                            "reconnect, capped-exp backoff, resend-"
                            "same-bytes and the breaker live in ONE "
                            "home (ISSUE 14)"))
                        break
        return findings
