"""znicz-lint core: the shared AST walk, findings, pragmas, baseline.

ISSUE 9 tentpole.  The package grew three regex lints
(tests/test_no_adhoc_counters.py) that were blind to aliasing, and PR 6
and PR 7 each needed a human review-hardening pass to catch the same
defect class (unlocked shared state touched by a worker thread).  This
module is the framework those checks now run on:

  - every ``*.py`` under the target is parsed ONCE into a :class:`Module`
    (source text + AST + suppression pragmas), shared by all checkers;
  - checkers yield :class:`Finding` records ``(rule, path, line,
    message, severity)``;
  - a finding is suppressed either by an inline pragma
    (``# znicz: ignore[rule]`` on the offending line or the line above)
    or by an entry in the committed baseline file
    (``znicz_tpu/analysis/baseline.json``) — the baseline is for
    findings that were TRIAGED and accepted, each with a one-line
    justification, so the tier-1 gate stays at zero *unbaselined*
    findings while accepted debt remains visible and counted;
  - baseline entries match on ``(rule, path, message)`` — deliberately
    line-free, so unrelated edits that shift line numbers do not
    invalidate the triage.

Run it as ``python -m znicz_tpu.analysis`` (text) or with ``--json``
(machine-readable counts for benches/dashboards).  The tier-1 test
``tests/test_analysis.py::test_package_is_clean_under_the_analyzer``
runs the same entry point in-process and fails on any unbaselined
finding, making the analysis a standing gate rather than a one-off
audit.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import pathlib
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: inline suppression: ``# znicz: ignore[rule]`` or ``ignore[r1, r2]``,
#: effective on its own line and on the line directly below it
PRAGMA = re.compile(r"#\s*znicz:\s*ignore\[([A-Za-z0-9_\-, ]+)\]")

#: default committed baseline, adjacent to this module
DEFAULT_BASELINE = pathlib.Path(__file__).resolve().parent / "baseline.json"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One checker hit.  ``path`` is posix-relative to the scanned
    package directory; ``key`` drops the line so baseline entries
    survive unrelated line drift."""

    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Module:
    """One parsed source file shared by every checker: path, text,
    AST, and the line -> suppressed-rules pragma map."""

    def __init__(self, path: pathlib.Path, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=str(path))
        self.pragmas: Dict[int, Set[str]] = {}
        for lineno, line in enumerate(text.splitlines(), 1):
            m = PRAGMA.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")
                         if r.strip()}
                self.pragmas.setdefault(lineno, set()).update(rules)
                if line.lstrip().startswith("#"):
                    # a STANDALONE pragma comment covers the next line;
                    # a trailing pragma covers only its own
                    self.pragmas.setdefault(lineno + 1, set()).update(
                        rules)

    def suppressed(self, rule: str, line: int) -> bool:
        """True when a ``# znicz: ignore[rule]`` pragma sits on the
        finding's line (trailing) or on a standalone comment line just
        above it."""
        return rule in self.pragmas.get(line, ())


class Checker:
    """Base: one rule, one ``check(module)`` pass.  Checkers needing
    package-level context (the config DEFAULTS tables) receive the
    package dir at construction."""

    name = "abstract"

    def check(self, module: Module) -> Iterable[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclasses.dataclass
class Analysis:
    """Result bundle of one run."""

    findings: List[Finding]                 # live, unbaselined
    baselined: List[Tuple[Finding, str]]    # (finding, justification)
    pragma_suppressed: List[Finding]
    stale_baseline: List[dict]              # entries that matched nothing
    parse_errors: List[Finding]

    @property
    def clean(self) -> bool:
        # stale baseline entries fail the gate too: a fixed-then-
        # regressed finding must not reopen behind a dead entry
        return (not self.findings and not self.parse_errors
                and not self.stale_baseline)

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {
            "clean": self.clean,
            "counts": self.counts(),
            "findings": [f.to_json() for f in self.findings],
            "baselined": [dict(f.to_json(), reason=reason)
                          for f, reason in self.baselined],
            "pragma_suppressed": [f.to_json()
                                  for f in self.pragma_suppressed],
            "stale_baseline": self.stale_baseline,
            "parse_errors": [f.to_json() for f in self.parse_errors],
        }

    def render_text(self) -> str:
        lines = [f.render() for f in self.parse_errors + self.findings]
        if self.clean:
            lines.append("znicz-lint: clean")
        per_rule = ", ".join(f"{rule}={n}"
                             for rule, n in sorted(self.counts().items()))
        lines.append(
            f"znicz-lint: {len(self.findings)} finding(s)"
            + (f" ({per_rule})" if per_rule else "")
            + f", {len(self.baselined)} baselined,"
            f" {len(self.pragma_suppressed)} pragma-suppressed")
        for entry in self.stale_baseline:
            lines.append(
                "znicz-lint: stale baseline entry (matched nothing): "
                f"{entry.get('rule')}: {entry.get('path')}: "
                f"{entry.get('message')}")
        return "\n".join(lines)


def load_baseline(path: Optional[pathlib.Path]) -> List[dict]:
    if path is None or not pathlib.Path(path).exists():
        return []
    data = json.loads(pathlib.Path(path).read_text())
    entries = data["entries"] if isinstance(data, dict) else data
    for e in entries:
        for field in ("rule", "path", "message", "reason"):
            if field not in e:
                raise ValueError(
                    f"baseline entry missing '{field}': {e}")
    return entries


def collect_modules(pkg_dir: pathlib.Path,
                    paths: Optional[Sequence[pathlib.Path]] = None,
                    ) -> Tuple[List[Module], List[Finding]]:
    """Parse every target ``*.py`` once.  Unparseable files become
    ``parse-error`` findings (never baselined away silently)."""
    pkg_dir = pathlib.Path(pkg_dir).resolve()
    files: List[pathlib.Path] = []
    for p in (paths if paths else [pkg_dir]):
        p = pathlib.Path(p).resolve()
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    modules, errors = [], []
    for path in files:
        try:
            rel = path.relative_to(pkg_dir).as_posix()
        except ValueError:
            rel = path.name
        text = path.read_text()
        try:
            modules.append(Module(path, rel, text))
        except SyntaxError as exc:
            errors.append(Finding(
                "parse-error", rel, exc.lineno or 0,
                f"cannot parse: {exc.msg}"))
    return modules, errors


def default_checkers(pkg_dir: pathlib.Path) -> List[Checker]:
    from .config_knob import ConfigKnobChecker
    from .counters import CounterRegistryChecker
    from .event_journal import EventJournalChecker
    from .jit_purity import JitPurityChecker
    from .threads import ThreadSharedStateChecker
    from .transport_core import TransportCoreChecker

    return [ThreadSharedStateChecker(), JitPurityChecker(),
            ConfigKnobChecker(pkg_dir), CounterRegistryChecker(),
            TransportCoreChecker(), EventJournalChecker()]


def run(pkg_dir: pathlib.Path,
        rules: Optional[Sequence[str]] = None,
        baseline_path: Optional[pathlib.Path] = DEFAULT_BASELINE,
        paths: Optional[Sequence[pathlib.Path]] = None,
        checkers: Optional[Sequence[Checker]] = None) -> Analysis:
    """One full analysis pass: parse once, run every (selected)
    checker, then split raw findings into live / pragma-suppressed /
    baselined."""
    pkg_dir = pathlib.Path(pkg_dir).resolve()
    modules, parse_errors = collect_modules(pkg_dir, paths)
    active = list(checkers) if checkers is not None \
        else default_checkers(pkg_dir)
    if rules:
        wanted = set(rules)
        unknown = wanted - {c.name for c in active}
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        active = [c for c in active if c.name in wanted]

    raw: List[Finding] = []
    for module in modules:
        for checker in active:
            raw.extend(checker.check(module))
    raw.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    entries = load_baseline(baseline_path)
    # each entry absorbs up to entry["count"] (default 1) findings with
    # its (rule, path, message) key — the key is line-free, so the
    # count is what keeps the gate tight: an N+1th identical finding in
    # the same file is LIVE, not silently absorbed
    budget: Dict[Tuple[str, str, str], List[List]] = {}
    for e in entries:
        key = (e["rule"], e["path"], e["message"])
        budget.setdefault(key, []).append(
            [int(e.get("count", 1)), e["reason"], e])
    live, baselined, pragma = [], [], []
    for f in raw:
        module = next((m for m in modules if m.rel == f.path), None)
        if module is not None and module.suppressed(f.rule, f.line):
            pragma.append(f)
            continue
        slot = next((s for s in budget.get(f.key, []) if s[0] > 0), None)
        if slot is not None:
            slot[0] -= 1
            baselined.append((f, slot[1]))
        else:
            live.append(f)
    # an entry is STALE only if this scan could have matched it: its
    # rule ran and its file was scanned (a --rules or path-subset run
    # must not cry stale over out-of-scope entries)
    scanned = {m.rel for m in modules}
    ran = {c.name for c in active}
    stale = [slot[2] for slots in budget.values() for slot in slots
             if slot[0] == int(slot[2].get("count", 1))
             and slot[2]["rule"] in ran and slot[2]["path"] in scanned]
    return Analysis(live, baselined, pragma, stale, parse_errors)
