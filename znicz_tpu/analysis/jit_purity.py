"""jit-purity: Python side effects, tracer leaks, and recompile hazards
inside jit/custom_vjp/pallas traced functions.

A function handed to ``jax.jit`` / ``jax.custom_vjp`` / ``pallas_call``
runs ONCE per compilation, not once per step: a ``print``, a telemetry
``.inc()``, or a ``self.x = ...`` inside it fires at trace time only
(silently wrong accounting), and ``float(x)`` / ``x.item()`` /
``np.asarray(x)`` on a traced value raises ``TracerConversionError`` at
best or silently constant-folds at worst.  Static-arg hygiene is the
recompile side of the same coin: an unhashable literal passed as a
static arg raises, and an f-string-derived static arg recompiles on
every new value.

Discovery (module-local, name-based):

  - defs decorated ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``
    / ``@jax.custom_vjp`` / ``@custom_vjp``;
  - ``g = jax.jit(f, ...)`` marks ``f`` (and records ``g``'s
    ``static_argnums``/``static_argnames`` for call-site checks);
  - ``pallas_call(kernel, ...)`` / ``pl.pallas_call(...)`` marks
    ``kernel``;
  - ``f.defvjp(fwd, bwd)`` marks ``fwd`` and ``bwd``.

Inside a marked function we flag:

  - side effects: ``print(...)``, telemetry ``.inc(...)``/
    ``.observe(...)``, and any attribute store ``obj.x = ...``;
  - tracer leaks: ``.item()`` calls, and ``float(...)``/``int(...)``/
    ``np.asarray(...)``/``np.array(...)`` whose argument is not a
    literal constant.

At call sites of a name wrapped by ``jax.jit`` in the same module we
flag list/dict/set literals bound to a declared static arg (unhashable
-> ``TypeError`` per call) and f-strings passed anywhere (a string
argument must be static, and an f-string derives a fresh value ->
recompile per call).

Functions that intentionally break the rules (host callbacks, debug
paths) carry ``# znicz: ignore[jit-purity]`` on the offending line, or
get baselined with a justification.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Checker, Finding, Module

RULE = "jit-purity"

_TRACING_WRAPPERS = {"jit", "custom_vjp", "pallas_call"}
_NUMPY_LEAKS = {"asarray", "array"}


def _terminal_name(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _wrapper_kind(expr: ast.expr) -> Optional[str]:
    """'jit' / 'custom_vjp' / 'pallas_call' if this expression is (a
    partial over) one of the tracing wrappers, else None."""
    name = _terminal_name(expr)
    if name in _TRACING_WRAPPERS:
        return name
    if isinstance(expr, ast.Call) and _terminal_name(expr.func) in (
            "partial",):
        if expr.args:
            return _wrapper_kind(expr.args[0])
    return None


def _static_names(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    """Declared static argnames / argnums of a jit(...) wrap call."""
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
    return names, nums


class _TracedBodyScan(ast.NodeVisitor):
    """Flag impurities inside one traced function body."""

    def __init__(self, module: Module, numpy_aliases: Set[str],
                 fn_name: str, out: List[Finding]) -> None:
        self.module = module
        self.np = numpy_aliases
        self.fn = fn_name
        self.out = out

    def _emit(self, line: int, what: str) -> None:
        self.out.append(Finding(
            RULE, self.module.rel, line,
            f"{what} inside jit-traced '{self.fn}' — runs at trace "
            f"time only (or leaks a tracer), not per step"))

    # -- side effects --------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            for t in ast.walk(target):
                if isinstance(t, ast.Attribute) and isinstance(
                        t.ctx, ast.Store):
                    self._emit(t.lineno,
                               f"attribute mutation '{ast.unparse(t)} ="
                               " ...' (Python side effect)")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if isinstance(node.target, ast.Attribute):
            self._emit(node.lineno,
                       f"attribute mutation "
                       f"'{ast.unparse(node.target)} op= ...' "
                       f"(Python side effect)")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Name) and func.id == "print":
            self._emit(node.lineno, "print() (Python side effect)")
        elif isinstance(func, ast.Name) and func.id in ("float", "int") \
                and node.args and not isinstance(node.args[0],
                                                 ast.Constant):
            self._emit(node.lineno,
                       f"{func.id}() on a non-literal value "
                       f"(tracer leak)")
        elif isinstance(func, ast.Attribute):
            if func.attr in ("inc", "observe"):
                self._emit(node.lineno,
                           f".{func.attr}() telemetry mutation "
                           f"(Python side effect)")
            elif func.attr == "item" and not node.args:
                self._emit(node.lineno, ".item() (tracer leak)")
            elif (func.attr in _NUMPY_LEAKS
                  and isinstance(func.value, ast.Name)
                  and func.value.id in self.np
                  and node.args
                  and not isinstance(node.args[0], ast.Constant)):
                self._emit(node.lineno,
                           f"{func.value.id}.{func.attr}() on a "
                           f"non-literal value (tracer leak)")
        self.generic_visit(node)


class JitPurityChecker(Checker):
    name = RULE

    def check(self, module: Module):
        numpy_aliases = self._numpy_aliases(module)
        # names referenced INTO a wrapper (g = jax.jit(f) / defvjp /
        # pallas_call(kernel)) are matched by name module-wide; defs
        # carrying the decorator themselves are marked by NODE, so a
        # public wrapper that shares its name with an inner decorated
        # def (ops/lrn_pallas.lrn) is not swept in by the collision
        marked: Dict[str, str] = {}        # referenced name -> kind
        marked_nodes: List[Tuple[ast.AST, str, str]] = []  # (fn, name, kind)
        statics: Dict[str, Tuple[Set[str], Set[int]]] = {}  # callee name
        jitted_names: Set[str] = set()     # for call-site hazards

        for node in ast.walk(module.tree):
            # decorated defs
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    kind = _wrapper_kind(dec)
                    if kind:
                        marked_nodes.append((node, node.name, kind))
                        if kind == "jit":
                            jitted_names.add(node.name)
                        if isinstance(dec, ast.Call):
                            statics[node.name] = _static_names(dec)
            # g = jax.jit(f, ...): remember g's static args for the
            # call-site hazard checks
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                kind = _wrapper_kind(node.value.func)
                if kind == "jit" and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name):
                    statics[node.targets[0].id] = _static_names(
                        node.value)
            if isinstance(node, ast.Call):
                # jax.jit(f) / custom_vjp(f) / pallas_call(kernel, ...)
                # in ANY position (assignment, return, nested call)
                # marks the referenced function
                kind = _wrapper_kind(node.func)
                if kind and node.args:
                    inner = node.args[0]
                    if isinstance(inner, ast.Name):
                        marked.setdefault(inner.id, kind)
                    elif isinstance(inner, ast.Lambda):
                        marked_nodes.append((inner, "<lambda>", kind))
                # f.defvjp(fwd, bwd)
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "defvjp":
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            marked.setdefault(arg.id, "custom_vjp")

        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()

        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in marked:
                marked_nodes.append((node, node.name, marked[node.name]))
        for fn, name, _kind in marked_nodes:
            out: List[Finding] = []
            scan = _TracedBodyScan(module, numpy_aliases, name, out)
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                scan.visit(stmt)
            for f in out:
                if (f.rule, f.line, f.message) not in seen:
                    seen.add((f.rule, f.line, f.message))
                    findings.append(f)

        jitted_names |= {n for n, k in marked.items() if k == "jit"}
        findings.extend(
            self._call_site_hazards(module, statics, jitted_names))
        return findings

    @staticmethod
    def _numpy_aliases(module: Module) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "numpy":
                        out.add(alias.asname or "numpy")
        return out

    def _call_site_hazards(self, module: Module,
                           statics: Dict[str, Tuple[Set[str], Set[int]]],
                           jitted_names: Set[str]) -> List[Finding]:
        jitted = set(statics) | jitted_names
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = _terminal_name(node.func)
            if callee not in jitted:
                continue
            names, nums = statics.get(callee, (set(), set()))
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.JoinedStr):
                    findings.append(Finding(
                        RULE, module.rel, arg.lineno,
                        f"f-string argument to jitted '{callee}' — "
                        f"derives a fresh static value per call "
                        f"(recompile hazard)"))
                elif i in nums and isinstance(
                        arg, (ast.List, ast.Dict, ast.Set)):
                    findings.append(Finding(
                        RULE, module.rel, arg.lineno,
                        f"unhashable {type(arg).__name__.lower()} "
                        f"literal as static arg {i} of jitted "
                        f"'{callee}' (recompile hazard: TypeError "
                        f"at call time)"))
            for kw in node.keywords:
                if isinstance(kw.value, ast.JoinedStr):
                    findings.append(Finding(
                        RULE, module.rel, kw.value.lineno,
                        f"f-string argument to jitted '{callee}' — "
                        f"derives a fresh static value per call "
                        f"(recompile hazard)"))
                elif kw.arg in names and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    findings.append(Finding(
                        RULE, module.rel, kw.value.lineno,
                        f"unhashable "
                        f"{type(kw.value).__name__.lower()} literal "
                        f"as static arg '{kw.arg}' of jitted "
                        f"'{callee}' (recompile hazard: TypeError "
                        f"at call time)"))
        return findings
