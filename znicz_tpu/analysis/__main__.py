"""CLI: ``python -m znicz_tpu.analysis [paths] [--json] [--rules ...]``.

Exit status 0 when the scan is clean (zero unbaselined findings), 1
otherwise — suitable as a CI gate.  ``--json`` emits one machine-
readable document (findings + per-rule counts + baselined/suppressed
totals) so benches and dashboards can track finding counts over time.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from . import DEFAULT_BASELINE, run

PKG_DIR = pathlib.Path(__file__).resolve().parent.parent


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m znicz_tpu.analysis",
        description="znicz-lint: AST static analysis for znicz_tpu "
                    "(thread-safety, JAX tracer hygiene, config/counter "
                    "discipline)")
    parser.add_argument(
        "paths", nargs="*", type=pathlib.Path,
        help="files or directories to scan (default: the znicz_tpu "
             "package)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON document instead of text")
    parser.add_argument(
        "--rules", default=None,
        help="comma-separated subset of rules to run (default: all)")
    parser.add_argument(
        "--baseline", default=str(DEFAULT_BASELINE),
        help="baseline file of triaged-and-accepted findings; pass "
             "'none' to disable and see everything")
    args = parser.parse_args(argv)

    baseline = None if args.baseline == "none" \
        else pathlib.Path(args.baseline)
    rules = [r.strip() for r in args.rules.split(",")] \
        if args.rules else None
    try:
        analysis = run(PKG_DIR, rules=rules, baseline_path=baseline,
                       paths=args.paths or None)
    except ValueError as exc:
        parser.error(str(exc))

    if args.json:
        print(json.dumps(analysis.to_json(), indent=2))
    else:
        print(analysis.render_text())
    return 0 if analysis.clean else 1


if __name__ == "__main__":
    sys.exit(main())
