"""event-journal: fleet state transitions must journal (ISSUE 20).

The structured event journal (``znicz_tpu/telemetry/events.py``) is the
fleet's causal timeline — "why did the fleet do X at t?" is only
answerable if every state transition actually emits.  Counters made
this mistake once already (PRs 1-4 grew silent ad-hoc accounting until
the counter-registry rule fenced it); this rule fences the journal the
same way: the named decision points below — the functions that mutate
fleet membership, generation capacity, or quorum — must contain a
``telemetry.emit(...)`` (or ``journal().emit(...)``) call.

Two finding shapes:

  - a listed function exists but has NO emit call — the transition
    would be invisible to ``/events.json`` (fix: emit, with the numbers
    that drove the decision);
  - a listed function is GONE (renamed/refactored away) — the table
    below is the contract and must move with the code, otherwise the
    rule silently guards nothing.

New transition sites join :data:`SITES` in the same PR that adds them;
baseline-gated like every other rule (0 entries at introduction).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List

from .core import Checker, Finding, Module

RULE = "event-journal"

#: path (relative to znicz_tpu/) -> {qualified function: event kinds it
#: must emit}.  The kinds are documentation for the reader; the check
#: is "an emit call is present".
SITES: Dict[str, Dict[str, str]] = {
    "serving/balancer.py": {
        "ReplicaBalancer._evict_member": "replica_lost",
        "ReplicaBalancer._failover": "failover",
        "ReplicaBalancer._maybe_heal": "heal",
        "ReplicaBalancer._tick_autoscale": "autoscale_up/autoscale_down",
        "ReplicaBalancer._handle_swap": "swap_begin",
        "ReplicaBalancer._enter_phase": "swap_phase/swap_done",
        "ReplicaBalancer._abort_to_rollback": "rollback",
    },
    "server.py": {
        "Server._replan": "replan",
        "Server._evict_dead_slaves": "preemption",
        "Server._note_quorum": "quorum_degraded/quorum_restored",
    },
    "serving/model.py": {
        "PrefixCache.evict_one": "prefix_evict",
    },
    "serving/batcher.py": {
        "GenerationScheduler.submit": "page_shed (queue-bound shed)",
        "GenerationScheduler._note_page_pressure": "page_shed",
    },
    "transport/retry.py": {
        "CircuitBreaker._open": "breaker_open",
    },
}


def _has_emit_call(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "emit":
            return True
        if isinstance(func, ast.Name) and func.id == "emit":
            return True
    return False


def _qualified_functions(tree: ast.Module) -> Dict[str, ast.AST]:
    """{qualname: funcdef} for module- and class-level functions (one
    nesting level — the depth every site in the table uses)."""
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[node.name] = node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out[f"{node.name}.{sub.name}"] = sub
    return out

class EventJournalChecker(Checker):
    name = RULE

    def __init__(self, sites: Dict[str, Dict[str, str]] = SITES):
        self.sites = sites

    def check(self, module: Module) -> Iterable[Finding]:
        table = self.sites.get(module.rel)
        if not table:
            return []
        findings: List[Finding] = []
        fns = _qualified_functions(module.tree)
        for qualname, kinds in sorted(table.items()):
            fn = fns.get(qualname)
            if fn is None:
                findings.append(Finding(
                    RULE, module.rel, 1,
                    f"journaled transition site '{qualname}' not found — "
                    f"the function moved or was renamed; update SITES in "
                    f"znicz_tpu/analysis/event_journal.py so the rule "
                    f"keeps guarding it"))
                continue
            if not _has_emit_call(fn):
                findings.append(Finding(
                    RULE, module.rel, fn.lineno,
                    f"state transition '{qualname}' ({kinds}) does not "
                    f"journal — emit a structured event via "
                    f"telemetry.emit(...) with the numbers that drove "
                    f"the decision"))
        return findings
