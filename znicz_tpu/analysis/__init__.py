"""znicz-lint: AST static analysis tuned to this stack (ISSUE 9).

Five rules over one shared AST walk of ``znicz_tpu/``:

  - ``thread-shared-state`` — attributes mutated on a worker thread and
    accessed elsewhere with no enclosing lock (the PR 6/7
    review-hardening bug class, automated);
  - ``jit-purity``         — Python side effects, tracer leaks, and
    recompile hazards inside jit/custom_vjp/pallas traced functions;
  - ``config-knob``        — every ``root.common.{engine,serving}.*``
    read/write resolved through local aliases and checked against the
    declared DEFAULTS tables;
  - ``counter-registry``   — no new ad-hoc ``self.<counter> += 1``
    outside the telemetry registry;
  - ``transport-core``     — no new dataplane machinery outside
    ``znicz_tpu/transport``: raw ``zmq.Poller()``/socket ``.bind()``,
    hand-rolled poller dispatch loops, fresh-socket reconnect cycles
    and raw exponential-backoff sleeps are all flagged (the grown
    ``zmq-loop`` rule; ROADMAP item 4, landed in ISSUE 14).

Run ``python -m znicz_tpu.analysis`` (add ``--json`` for dashboards).
Suppress one site with ``# znicz: ignore[rule]``; accept a triaged
finding by adding it to ``znicz_tpu/analysis/baseline.json`` with a
one-line reason.  The tier-1 gate (tests/test_analysis.py) fails on any
unbaselined finding.
"""

from .core import (Analysis, Checker, DEFAULT_BASELINE, Finding, Module,
                   collect_modules, default_checkers, load_baseline, run)

__all__ = ["Analysis", "Checker", "DEFAULT_BASELINE", "Finding",
           "Module", "collect_modules", "default_checkers",
           "load_baseline", "run"]
