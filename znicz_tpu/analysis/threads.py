"""thread-shared-state: unlocked attributes shared with a worker thread.

The PR 6 / PR 7 review-hardening bug class, automated: ``admission_stats``
snapshots, the compute-fault cursor, and the ``DecodePool`` futures dict
were each mutated on a worker thread and read elsewhere with no lock —
found by a human on the third pass every time.  This checker finds them
mechanically:

  1. **thread entry points** per class: any method handed to
     ``threading.Thread(target=self.m)`` / ``threading.Timer(t, self.m)``
     anywhere in the class body, plus methods submitted to an executor
     (``<pool>.submit(self.m, ...)``);
  2. the **worker-reachable set**: the entry methods plus everything
     they call through ``self.m()`` (transitive);
  3. per-attribute **mutation sites** (``self.a = ...``, ``self.a += 1``,
     ``self.a[k] = v``, ``del self.a[k]``, and container-mutator calls
     like ``self.a.append/pop/update``) and **access sites**, each tagged
     with whether an enclosing ``with self.<lock>`` (or a name that looks
     like a lock/cond/gate/mutex) guards it;
  4. a finding for every attribute that is mutated UNLOCKED on a
     worker-reachable method and also touched by a non-worker method —
     ``__init__`` is exempt on both sides (it runs before any thread
     starts).

Heuristics, stated plainly: ``queue.Queue`` traffic (``put``/``get``)
is not a mutation (those objects lock internally); a ``with`` on any
``self.<attr>`` counts as a guard (in this codebase every such context
manager is a Lock/RLock/Condition); attributes only the workers touch
are not findings (no sharing, no race).  Accepted leftovers are
baselined with a justification, not silenced.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from .core import Checker, Finding, Module

RULE = "thread-shared-state"

#: container-mutation method names that count as writing the attribute
MUTATORS = {"append", "appendleft", "extend", "add", "insert", "pop",
            "popleft", "popitem", "update", "clear", "remove", "discard",
            "setdefault", "__setitem__"}

#: names that read as a synchronization primitive when used in ``with``
LOCKISH = ("lock", "cond", "gate", "mutex", "sem")

#: constructor names whose instances synchronize internally — an
#: attribute initialized from one of these is exempt from the rule
#: (``self._stop.set()`` on an Event, ``self._q.put()`` on a Queue):
#: their "mutations" are the thread-safe API, not shared raw state
SYNC_TYPES = {"Event", "Lock", "RLock", "Condition", "Semaphore",
              "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
              "LifoQueue", "PriorityQueue"}


def _self_attr(node: ast.AST) -> str | None:
    """``self.<attr>`` -> attr name, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lockish_ctx(expr: ast.expr) -> bool:
    """Does this ``with`` context expression look like a lock?"""
    if _self_attr(expr) is not None:
        return True                       # with self._anything: = a guard
    name = None
    if isinstance(expr, ast.Name):
        name = expr.id
    elif isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Call):
        return _is_lockish_ctx(expr.func)
    return name is not None and any(t in name.lower() for t in LOCKISH)


class _MethodScan(ast.NodeVisitor):
    """Collect one method's self-attribute writes/reads (with lock
    context), ``self.m()`` calls, and thread-target registrations."""

    def __init__(self) -> None:
        self.writes: List[Tuple[str, int, bool]] = []   # (attr, line, locked)
        self.reads: List[Tuple[str, int, bool]] = []
        self.calls: Set[str] = set()
        self.spawn_targets: Set[str] = set()
        self.sync_attrs: Set[str] = set()   # self.x = threading.Event()
        self._lock_depth = 0

    # -- lock context --------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        guarded = any(_is_lockish_ctx(item.context_expr)
                      for item in node.items)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        if guarded:
            self._lock_depth += 1
        for stmt in node.body:
            self.visit(stmt)
        if guarded:
            self._lock_depth -= 1

    # -- writes --------------------------------------------------------------

    def _record_write(self, attr: str, line: int) -> None:
        self.writes.append((attr, line, self._lock_depth > 0))

    def _scan_target(self, target: ast.expr) -> None:
        attr = _self_attr(target)
        if attr is not None:
            self._record_write(attr, target.lineno)
            return
        if isinstance(target, (ast.Subscript, ast.Attribute)):
            # self.a[k] = v / self.a.b = v mutate container/object a
            inner = _self_attr(target.value)
            if inner is not None:
                self._record_write(inner, target.lineno)
            else:
                self.visit(target.value)
            if isinstance(target, ast.Subscript):
                self.visit(target.slice)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._scan_target(elt)
        else:
            self.visit(target)

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            ctor = node.value.func
            ctor_name = ctor.attr if isinstance(ctor, ast.Attribute) \
                else (ctor.id if isinstance(ctor, ast.Name) else None)
            if ctor_name in SYNC_TYPES:
                for target in node.targets:
                    attr = _self_attr(target)
                    if attr is not None:
                        self.sync_attrs.add(attr)
        for target in node.targets:
            self._scan_target(target)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._scan_target(node.target)
        if node.value is not None:
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._scan_target(node.target)
        self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                inner = _self_attr(target.value)
                if inner is not None:
                    self._record_write(inner, target.lineno)
            self.generic_visit(target)

    # -- reads, calls, spawns ------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self.reads.append((attr, node.lineno, self._lock_depth > 0))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        spawnish = False
        if isinstance(func, ast.Attribute):
            recv = _self_attr(func.value)
            if recv is not None and func.attr in MUTATORS:
                self._record_write(recv, node.lineno)
            method = _self_attr(func)
            if method is not None:
                self.calls.add(method)
            # thread / timer / executor handing out self.<m>
            if func.attr in ("Thread", "Timer"):
                spawnish = True
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = _self_attr(kw.value)
                        if tgt is not None:
                            self.spawn_targets.add(tgt)
                for arg in node.args:
                    tgt = _self_attr(arg)
                    if tgt is not None:
                        self.spawn_targets.add(tgt)
            elif func.attr == "submit":
                spawnish = True
                if node.args:
                    tgt = _self_attr(node.args[0])
                    if tgt is not None:
                        self.spawn_targets.add(tgt)
        elif isinstance(func, ast.Name) and func.id in ("Thread", "Timer"):
            spawnish = True
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _self_attr(kw.value)
                    if tgt is not None:
                        self.spawn_targets.add(tgt)
        if not spawnish:
            # a bound method handed BY REFERENCE to an ordinary call —
            # loop.register(sock, self._handle), add_tick(self._flush)
            # (the ISSUE 14 TransportLoop handler pattern) — runs on
            # the CALLER's thread when the loop dispatches it: treat
            # the reference as a call edge, or every handler registered
            # this way would drop out of the worker-reachable set and
            # its whole dispatch tree would misclassify as "other
            # threads" (Thread/Timer/submit references stay SPAWN
            # targets — new-thread entries, not same-thread edges)
            for val in list(node.args) + [kw.value for kw in
                                          node.keywords]:
                tgt = _self_attr(val)
                if tgt is not None:
                    self.calls.add(tgt)
        self.generic_visit(node)

    # nested defs/lambdas inside a method run on the same thread as the
    # method that CALLS them, which we approximate as the enclosing
    # method's thread — keep scanning (worker loops build closures)


class ThreadSharedStateChecker(Checker):
    name = RULE

    def check(self, module: Module):
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    def _check_class(self, module: Module, cls: ast.ClassDef):
        methods: Dict[str, ast.FunctionDef] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                methods[stmt.name] = stmt
        scans: Dict[str, _MethodScan] = {}
        entries: Set[str] = set()
        sync_attrs: Set[str] = set()
        for name, fn in methods.items():
            scan = _MethodScan()
            for stmt in fn.body:
                scan.visit(stmt)
            scans[name] = scan
            entries |= scan.spawn_targets & set(methods)
            sync_attrs |= scan.sync_attrs
        if not entries:
            return []

        # worker-reachable closure over self.m() edges
        worker: Set[str] = set()
        frontier = list(entries)
        while frontier:
            m = frontier.pop()
            if m in worker or m not in methods:
                continue
            worker.add(m)
            frontier.extend(scans[m].calls & set(methods))

        findings: List[Finding] = []
        for wname in sorted(worker):
            if wname == "__init__":
                continue
            # one finding PER UNLOCKED WRITE SITE (not per attribute):
            # identical sites share a line-free (rule, path, message)
            # key, so the baseline's count cap stays meaningful — a NEW
            # unlocked mutation of an already-baselined attribute is
            # the N+1th identical finding and comes up LIVE
            for attr, line, locked in scans[wname].writes:
                if locked or attr in sync_attrs:
                    continue
                others = sorted(
                    oname for oname in methods
                    if oname not in worker and oname != "__init__"
                    and any(a == attr for a, _, _ in
                            scans[oname].writes + scans[oname].reads))
                if not others:
                    continue
                entry = sorted(entries)[0]
                findings.append(Finding(
                    RULE, module.rel, line,
                    f"{cls.name}.{attr} is mutated in {wname}() on the "
                    f"worker thread (entry {entry}()) without an "
                    f"enclosing lock, but is also accessed from "
                    f"{others[0]}() — guard both sides with the same "
                    f"'with self.<lock>' or baseline with a "
                    f"justification"))
        return findings
