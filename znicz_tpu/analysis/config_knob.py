"""config-knob: every engine/serving config read must be declared.

The ``Config`` tree autovivifies: a typo'd or undeclared
``root.common.engine.<knob>`` read silently returns its fallback
forever, and dotted CLI overrides of it are silently ignored.  PR 6 and
PR 7 added regex lints forcing every literal ``root.common.serving.*`` /
``root.common.engine.*`` chain into the DEFAULTS declaration tables —
but the regexes were blind to aliasing, so they REFUSED subtree
aliasing outright (``adm = root.common.serving.admission`` was itself an
offense).  This checker is the AST-accurate generalization that retires
that workaround: it resolves attribute/``.get`` chains *through local
aliases* and checks the resulting dotted key against the declared
tables, which are themselves read from the AST of
``core/config.py`` (``ENGINE_DEFAULTS``) and ``serving/frontend.py``
(``DEFAULTS``) — no jax import needed to lint.

Resolved and checked:

  - literal chains: ``root.common.engine.fuse``,
    ``root.common.serving.admission.get("rate_limit", d)``;
  - aliased chains: ``adm = root.common.serving.admission`` then
    ``adm.get("rate_limit", d)`` (aliases of aliases too);
  - writes: ``root.common.engine.foo = 1`` needs ``foo`` declared just
    like a read (sample configs SET knobs the engine later reads).

Deliberately silent (the true negatives):

  - dynamic reads ``node.get(name, ...)`` with a non-literal key — the
    frontend's ``_cfg`` helper is keyed off DEFAULTS by construction;
  - Config's own dict-ish methods (``update``/``items``/...);
  - trees other than ``common.engine`` / ``common.serving``.

Still flagged: a subtree that ESCAPES local analysis (stored on an
object, passed to a call, returned) — reads beyond that point would be
invisible to the lint, which is the hole the old blanket alias refusal
plugged.  Spell reads locally, or baseline the escape with a reason.
"""

from __future__ import annotations

import ast
import pathlib
from typing import Dict, List, Optional, Set, Tuple

from .core import Checker, Finding, Module

RULE = "config-knob"

#: dict-ish methods of Config that take no literal key / are not reads
_CONFIG_METHODS = {"update", "items", "keys", "values", "flat",
                   "snapshot", "restore", "as_dict", "to_dict",
                   "set_by_path"}

_TREES = {("common", "engine"): "engine",
          ("common", "serving"): "serving"}

Path = Tuple[str, ...]


def _dict_tables(node: ast.Dict, prefix: str = ""
                 ) -> Tuple[Set[str], Set[str]]:
    """(leaf keys, subtree keys) of a (possibly nested) dict literal,
    dotted-flattened."""
    leaves: Set[str] = set()
    subtrees: Set[str] = set()
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue
        dotted = prefix + k.value
        if isinstance(v, ast.Dict):
            subtrees.add(dotted)
            sub_leaves, sub_trees = _dict_tables(v, dotted + ".")
            leaves |= sub_leaves
            subtrees |= sub_trees
        else:
            leaves.add(dotted)
    return leaves, subtrees


def load_declared_tables(pkg_dir: pathlib.Path
                         ) -> Dict[str, Tuple[Set[str], Set[str]]]:
    """AST-extract the declaration tables: ``ENGINE_DEFAULTS`` from
    core/config.py and ``DEFAULTS`` from serving/frontend.py.  Returns
    {tree: (leaf keys, subtree keys)}."""
    sources = {"engine": (pkg_dir / "core" / "config.py",
                          "ENGINE_DEFAULTS"),
               "serving": (pkg_dir / "serving" / "frontend.py",
                           "DEFAULTS")}
    out: Dict[str, Tuple[Set[str], Set[str]]] = {}
    for tree, (path, var) in sources.items():
        leaves: Set[str] = set()
        subtrees: Set[str] = set()
        if path.exists():
            mod = ast.parse(path.read_text(), filename=str(path))
            for node in ast.walk(mod):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Dict)
                        and any(isinstance(t, ast.Name) and t.id == var
                                for t in node.targets)):
                    leaves, subtrees = _dict_tables(node.value)
                    break
        out[tree] = (leaves, subtrees)
    return out


class _ScopeWalker:
    """Statement-ordered walk of one scope, carrying the alias
    environment {local name -> absolute config path}."""

    def __init__(self, checker: "ConfigKnobChecker", module: Module,
                 out: List[Finding]) -> None:
        self.checker = checker
        self.module = module
        self.out = out
        self._scope = "module"      # "module" | "class" | "function"

    # -- path resolution -----------------------------------------------------

    def resolve_ref(self, expr: ast.expr, env: Dict[str, Path]
                    ) -> Optional[Path]:
        """Pure attribute chain -> absolute path from ``root``."""
        if isinstance(expr, ast.Name):
            if expr.id == "root":
                return ()
            return env.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.resolve_ref(expr.value, env)
            if base is not None:
                return base + (expr.attr,)
        return None

    def _tree_of(self, path: Path) -> Optional[Tuple[str, Path]]:
        for prefix, tree in _TREES.items():
            if path[:2] == prefix:
                return tree, path[2:]
        return None

    def _classify(self, path: Path) -> str:
        """'outside' | 'subtree' | 'leaf' | 'undeclared' for a full
        absolute path."""
        hit = self._tree_of(path)
        if hit is None:
            # root / root.common / unrelated trees: track, never flag
            return "outside"
        tree, keys = hit
        if not keys:
            return "subtree"
        leaves, subtrees = self.checker.tables[tree]
        dotted = ".".join(keys)
        if dotted in subtrees:
            return "subtree"
        if dotted in leaves:
            return "leaf"
        return "undeclared"

    def _check_access(self, path: Path, line: int) -> None:
        hit = self._tree_of(path)
        if hit is None:
            return
        tree, keys = hit
        if not keys:
            return
        dotted = ".".join(keys)
        leaves, subtrees = self.checker.tables[tree]
        if dotted not in leaves and dotted not in subtrees:
            table = ("ENGINE_DEFAULTS (znicz_tpu/core/config.py)"
                     if tree == "engine" else
                     "serving DEFAULTS (znicz_tpu/serving/frontend.py)")
            self.out.append(Finding(
                RULE, self.module.rel, line,
                f"undeclared {tree} config key "
                f"'root.common.{tree}.{dotted}' — missing from {table}; "
                f"an undeclared knob is silently ignored by dotted "
                f"overrides (declare it or fix the typo)"))

    def _escape(self, path: Path, line: int, how: str) -> None:
        hit = self._tree_of(path)
        if hit is None:
            return
        tree, keys = hit
        dotted = ".".join(("root", "common", tree) + tuple(keys))
        self.out.append(Finding(
            RULE, self.module.rel, line,
            f"config subtree '{dotted}' {how} — reads beyond this "
            f"point are invisible to the lint; keep reads on local "
            f"aliases or literal chains"))

    # -- expressions ---------------------------------------------------------

    def walk_expr(self, expr: ast.expr, env: Dict[str, Path]) -> None:
        if isinstance(expr, ast.Call):
            func = expr.func
            if isinstance(func, ast.Attribute):
                base = self.resolve_ref(func.value, env)
                if base is not None and self._tree_of(base) is not None:
                    if func.attr == "get":
                        key = expr.args[0] if expr.args else None
                        if isinstance(key, ast.Constant) and isinstance(
                                key.value, str):
                            self._check_access(base + (key.value,),
                                               expr.lineno)
                        # dynamic key: contributes nothing by design
                        for arg in expr.args[1:]:
                            self.walk_expr(arg, env)
                        for kw in expr.keywords:
                            self.walk_expr(kw.value, env)
                        return
                    if func.attr in _CONFIG_METHODS:
                        for arg in expr.args:
                            self.walk_expr(arg, env)
                        for kw in expr.keywords:
                            self.walk_expr(kw.value, env)
                        return
            self.walk_expr(func, env)
            for arg in list(expr.args) + [kw.value
                                          for kw in expr.keywords]:
                ref = self.resolve_ref(arg, env)
                if ref is not None and self._classify(ref) == "subtree":
                    self._escape(ref, arg.lineno,
                                 "passed as a call argument")
                else:
                    self.walk_expr(arg, env)
            return
        if isinstance(expr, ast.Attribute):
            ref = self.resolve_ref(expr, env)
            if ref is not None:
                if self._classify(ref) in ("leaf", "undeclared"):
                    self._check_access(ref, expr.lineno)
                # bare subtree in expression position (comparison,
                # str(), ...) reads nothing — silent
                return
            self.walk_expr(expr.value, env)
            return
        if isinstance(expr, ast.Lambda):
            self.walk_expr(expr.body, dict(env))
            return
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self.walk_expr(child, env)
            elif isinstance(child, ast.comprehension):
                self.walk_expr(child.iter, env)
                for cond in child.ifs:
                    self.walk_expr(cond, env)
            elif isinstance(child, ast.keyword):
                self.walk_expr(child.value, env)

    # -- statements ----------------------------------------------------------

    def walk_body(self, stmts: List[ast.stmt],
                  env: Dict[str, Path]) -> None:
        for stmt in stmts:
            self.walk_stmt(stmt, env)

    def _assign_value(self, targets: List[ast.expr], value: ast.expr,
                      env: Dict[str, Path], lineno: int) -> None:
        ref = self.resolve_ref(value, env)
        kind = self._classify(ref) if ref is not None else None
        if kind in ("outside", "subtree"):
            for target in targets:
                if isinstance(target, ast.Name) \
                        and self._scope != "class":
                    env[target.id] = ref      # a trackable alias
                else:
                    # class-body bindings are reachable as self.<name>
                    # from any method — not locally trackable
                    if kind == "subtree":
                        self._escape(ref, lineno,
                                     "stored outside the local scope")
                    self._walk_target(target, env)
            return
        if kind in ("leaf", "undeclared"):
            self._check_access(ref, lineno)   # value is a key READ
        else:
            self.walk_expr(value, env)
        for target in targets:
            if isinstance(target, ast.Name):
                env.pop(target.id, None)      # rebound to a non-ref
            else:
                self._walk_target(target, env)

    def _walk_target(self, target: ast.expr,
                     env: Dict[str, Path]) -> None:
        """Attribute-chain write targets are key accesses too."""
        if isinstance(target, ast.Attribute):
            ref = self.resolve_ref(target, env)
            if ref is not None:
                self._check_access(ref, target.lineno)
                return
            self.walk_expr(target.value, env)
        elif isinstance(target, ast.Subscript):
            self.walk_expr(target.value, env)
            self.walk_expr(target.slice, env)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._walk_target(elt, env)

    def walk_stmt(self, stmt: ast.stmt, env: Dict[str, Path]) -> None:
        if isinstance(stmt, ast.Assign):
            self._assign_value(stmt.targets, stmt.value, env,
                               stmt.lineno)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign_value([stmt.target], stmt.value, env,
                                   stmt.lineno)
            else:
                self._walk_target(stmt.target, env)
        elif isinstance(stmt, ast.AugAssign):
            self._walk_target(stmt.target, env)
            self.walk_expr(stmt.value, env)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                ref = self.resolve_ref(stmt.value, env)
                if ref is not None and self._classify(ref) == "subtree":
                    self._escape(ref, stmt.lineno,
                                 "returned from the function")
                else:
                    self.walk_expr(stmt.value, env)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in stmt.decorator_list:
                self.walk_expr(dec, env)
            for default in (stmt.args.defaults
                            + [d for d in stmt.args.kw_defaults if d]):
                self.walk_expr(default, env)
            outer, self._scope = self._scope, "function"
            self.walk_body(stmt.body, dict(env))
            self._scope = outer
        elif isinstance(stmt, ast.ClassDef):
            for dec in stmt.decorator_list:
                self.walk_expr(dec, env)
            outer, self._scope = self._scope, "class"
            self.walk_body(stmt.body, dict(env))
            self._scope = outer
        else:
            # generic: walk sub-statements in order (same env — flow-
            # insensitive), and every expression child
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt):
                    self.walk_stmt(child, env)
                elif isinstance(child, ast.expr):
                    self.walk_expr(child, env)
                elif isinstance(child, (ast.excepthandler, ast.withitem,
                                        ast.match_case)):
                    for sub in ast.iter_child_nodes(child):
                        if isinstance(sub, ast.stmt):
                            self.walk_stmt(sub, env)
                        elif isinstance(sub, ast.expr):
                            self.walk_expr(sub, env)


class ConfigKnobChecker(Checker):
    name = RULE

    def __init__(self, pkg_dir: pathlib.Path,
                 tables: Optional[Dict[str, Tuple[Set[str], Set[str]]]]
                 = None) -> None:
        self.tables = tables if tables is not None \
            else load_declared_tables(pathlib.Path(pkg_dir))

    def check(self, module: Module):
        out: List[Finding] = []
        walker = _ScopeWalker(self, module, out)
        # two phases, matching runtime semantics: module-level
        # statements EXECUTE in order, but functions/classes are merely
        # DEFINED then called after the module finishes — so defs are
        # walked second, against the complete module alias env (a
        # module-level alias textually below a def is still visible
        # inside it)
        env: Dict[str, Path] = {}
        defs: List[ast.stmt] = []
        for stmt in module.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                defs.append(stmt)
            else:
                walker.walk_stmt(stmt, env)
        for stmt in defs:
            walker.walk_stmt(stmt, env)
        # the declaration tables declare; their own module assigns the
        # documented defaults — those writes are leaf accesses and pass
        # (declared), so no special-casing is needed here
        return out
