"""zmq-loop: no NEW forked ZMQ dataplane loops outside network_common.

ROADMAP item 4 names the debt: the stack grew four hand-rolled ZMQ
loops (master REP, relay, serving frontend, chaos proxy) before PR 9
extracted the first shared piece (``network_common.bind_with_retry``)
and ISSUE 12 the second (``network_common.make_poller``).  Every loop
that re-forks the raw primitives re-forks the conventions with them —
the EADDRINUSE restart-race retry, the POLLIN registration discipline,
and (eventually) the telemetry spans and chaos hooks a single dataplane
core will carry.  This rule keeps new planes on the shared helpers:

Flagged (outside ``network_common.py``):

  - ``zmq.Poller()`` instantiation — use
    ``network_common.make_poller(*socks)``;
  - ``.bind(...)`` on a ZMQ socket — a receiver assigned from a
    ``*.socket(...)`` call in the same function scope (``sock =
    ctx.socket(zmq.ROUTER); sock.bind(...)`` and the ``self._sock``
    spelling both) — use ``network_common.bind_with_retry``.

Deliberately silent: ``.connect(...)`` (no restart race to retry),
``.bind`` on non-socket receivers (an HTTP server, argparse), and
sockets created in one scope but bound in another (rare; the reviewer's
job, not worth cross-function dataflow here).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from .core import Checker, Finding, Module

RULE = "zmq-loop"

#: the one sanctioned home for raw binds/pollers
EXEMPT_FILES = ("network_common.py",)


def _receiver_key(node: ast.expr) -> str | None:
    """A trackable receiver: a bare name or a ``self.<attr>`` chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                      ast.Name) \
            and node.value.id == "self":
        return f"self.{node.attr}"
    return None


def _scope_nodes(body: Iterable[ast.stmt]):
    """Every node of one scope, PRUNING nested function bodies — they
    are their own scopes and are scanned separately (``ast.walk`` has
    no pruning, so a naive walk double-counts)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue                # a nested scope: scanned separately
        stack.extend(ast.iter_child_nodes(node))


def _socket_assigns(body: Iterable[ast.stmt]) -> set:
    """Receiver keys assigned from a ``*.socket(...)`` call anywhere in
    this scope (order-insensitive: ZMQ code conventionally creates and
    binds within one function)."""
    out = set()
    for node in _scope_nodes(body):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "socket"):
            for target in node.targets:
                key = _receiver_key(target)
                if key is not None:
                    out.add(key)
    return out


class ZmqLoopChecker(Checker):
    name = RULE

    def check(self, module: Module):
        if module.rel in EXEMPT_FILES:
            return []
        findings: List[Finding] = []
        # Poller instantiation: flagged anywhere in the file
        for node in ast.walk(module.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Poller"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "zmq"):
                findings.append(Finding(
                    RULE, module.rel, node.lineno,
                    "raw zmq.Poller() forked outside network_common — "
                    "use network_common.make_poller(*socks) so every "
                    "dataplane loop shares one poll-registration "
                    "convention (ROADMAP item 4)"))
        # socket binds: per function scope (+ the module scope)
        scopes: List[Iterable[ast.stmt]] = [module.tree.body]
        scopes += [n.body for n in ast.walk(module.tree)
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        for body in scopes:
            sockets = _socket_assigns(body)
            if not sockets:
                continue
            for node in _scope_nodes(body):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "bind"
                        and _receiver_key(node.func.value)
                        in sockets):
                    findings.append(Finding(
                        RULE, module.rel, node.lineno,
                        "raw ZMQ socket .bind() outside "
                        "network_common — use network_common."
                        "bind_with_retry(sock, endpoint): a "
                        "restarted peer races its predecessor's "
                        "port release (EADDRINUSE), and the retry "
                        "policy has ONE home (ROADMAP item 4)"))
        return findings
