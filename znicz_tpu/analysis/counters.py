"""counter-registry: no NEW ad-hoc ``self.<counter> += 1`` accounting.

PRs 1-4 each grew bespoke ``self.<name> += 1`` counters (``bad_frames``,
``prefetch_hits``, ``shed``, ...), readable only through whichever panel
their owner happened to wire up; ISSUE 5 moved them all into the
telemetry registry (znicz_tpu/telemetry/) where every counter exports
uniformly on ``/metrics``.  The original guard was a line-anchored regex
(tests/test_no_adhoc_counters.py) — this is its AST-accurate port: a
counter increment is flagged wherever the statement sits (after a ``;``,
inside a one-line ``if``, multi-target), and the ``self.x = self.x + 1``
spelling the regex could never see is caught too.

Flagged: ``self.<name> += <expr>`` and ``self.<name> = self.<name> +
<expr>`` (either operand order) where ``<name>`` ends in a counter
suffix — the union of every counter name the registry migration
absorbed, so the regression class is exactly "a counter like the ones
we already centralized".

Exempt: ``znicz_tpu/telemetry/`` (the registry implements itself), and
the :data:`ALLOWLIST` below — attributes that LOOK counter-ish but are
training/streaming STATE, not metrics, each with its reason.  New
non-metric state joins the allowlist with a justification; new metrics
go through ``telemetry.scope(...).counter(...).inc()``.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Checker, Finding, Module

RULE = "counter-registry"

#: attribute-name suffixes that mean "this is a counter"
SUFFIXES = ("count", "total", "hits", "frames", "saves", "done",
            "requeued", "reconnects", "replies", "registrations",
            "updates", "rejected", "shed", "oversized", "compiles",
            "received", "served", "batches", "errors", "resends")

#: (path-relative-to-znicz_tpu, attribute) pairs that look counter-ish
#: but are STATE, not metrics — each with its reason (moved verbatim
#: from the original regex lint's ALLOWLIST; tests/
#: test_no_adhoc_counters.py asserts this table stays the single
#: source of truth)
ALLOWLIST = {
    # PRNG/step-key stream position: training semantics (jax_key(step)),
    # not accounting; mirrored into the registry as trainer/train_steps
    ("parallel/fused.py", "steps_done"),
    # loader cursor over the resident set (drives epoch bookkeeping)
    ("loader/base.py", "samples_served"),
    # graphics PUB/SUB frame cursor on the plotting side-channel
    ("graphics.py", "received"),
    # kohonen epoch accumulators (averaged into qerror / the winners
    # histogram, then reset)
    ("kohonen.py", "_batches"),
    ("kohonen.py", "total"),
    # ScriptedReplica's scripted-accounting state (fleet test double,
    # ISSUE 12): per-instance request count driving the stall_every
    # script, read back by tests — not a service metric
    ("parallel/chaos.py", "served"),
}


def _counter_name(node: ast.expr) -> str | None:
    """``self.<attr>`` with a counter suffix -> attr, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr.endswith(SUFFIXES)):
        return node.attr
    return None


class CounterRegistryChecker(Checker):
    name = RULE

    def __init__(self, allowlist=ALLOWLIST, exempt_dirs=("telemetry/",)):
        self.allowlist = set(allowlist)
        self.exempt_dirs = tuple(exempt_dirs)

    def check(self, module: Module):
        if module.rel.startswith(self.exempt_dirs):
            return []
        findings: List[Finding] = []

        def flag(attr: str, line: int) -> None:
            if (module.rel, attr) in self.allowlist:
                return
            findings.append(Finding(
                RULE, module.rel, line,
                f"ad-hoc counter increment 'self.{attr}' — register it "
                f"in znicz_tpu/telemetry instead "
                f"(telemetry.scope(...).counter(...).inc()), or "
                f"allowlist non-metric state with a justification"))

        for node in ast.walk(module.tree):
            if isinstance(node, ast.AugAssign) and isinstance(
                    node.op, ast.Add):
                attr = _counter_name(node.target)
                if attr is not None:
                    flag(attr, node.lineno)
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.BinOp) and isinstance(
                    node.value.op, ast.Add):
                for target in node.targets:
                    attr = _counter_name(target)
                    if attr is None:
                        continue
                    for operand in (node.value.left, node.value.right):
                        if (isinstance(operand, ast.Attribute)
                                and ast.unparse(operand)
                                == ast.unparse(target)):
                            flag(attr, node.lineno)
                            break
        return findings
