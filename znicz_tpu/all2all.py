"""Fully-connected forward units (rebuild of ``znicz/all2all.py``).

Reference classes (SURVEY.md §2.2 "Fully connected"): ``All2All`` (linear),
``All2AllTanh``, ``All2AllRELU`` (softplus!), ``All2AllStrictRELU``,
``All2AllSigmoid``, ``All2AllSoftmax``.  The reference ran clBLAS/cuBLAS GEMM
plus a bias+activation kernel; here the whole thing is one jitted
``linear``+activation, which XLA fuses onto the MXU.

``All2AllSoftmax``'s output is the probability distribution itself; argmax /
n_err / confusion all happen inside the evaluator's jitted metrics step (the
reference exported a separate ``max_idx`` buffer instead).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from znicz_tpu.nn_units import ForwardBase
from znicz_tpu.ops import activations
from znicz_tpu.ops.linear import linear


class All2All(ForwardBase):
    """y = activation(x @ W^T + b); output_sample_shape sets the width."""

    ACTIVATION = staticmethod(activations.identity)

    def __init__(self, workflow=None, name=None, output_sample_shape=(),
                 **kwargs):
        super().__init__(workflow=workflow, name=name, **kwargs)
        if isinstance(output_sample_shape, int):
            output_sample_shape = (output_sample_shape,)
        self.output_sample_shape = tuple(output_sample_shape)
        self.output_samples_number = int(np.prod(self.output_sample_shape))

    def output_shape_for(self, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        return (in_shape[0],) + self.output_sample_shape

    def apply(self, params, x):
        y = linear(x, params["weights"], params.get("bias"),
                   weights_transposed=self.weights_transposed)
        y = type(self).ACTIVATION(y)
        return y.reshape((x.shape[0],) + self.output_sample_shape)

    def initialize(self, device=None, **kwargs):
        in_size = self.input.sample_size
        out_size = self.output_samples_number
        if self.weights.mem is None:
            self.init_weights((out_size, in_size), (out_size,))
        self.create_output()
        super().initialize(device=device, **kwargs)


class All2AllTanh(All2All):
    ACTIVATION = staticmethod(activations.tanh_scaled)


class All2AllRELU(All2All):
    """Reference "RELU" = softplus log(1+e^x)."""

    ACTIVATION = staticmethod(activations.relu_log)


class All2AllStrictRELU(All2All):
    ACTIVATION = staticmethod(activations.strict_relu)


class All2AllSigmoid(All2All):
    ACTIVATION = staticmethod(activations.sigmoid)


class All2AllSoftmax(All2All):
    """Output is the softmax distribution itself (reference semantics); the
    paired GDSoftmax treats err_output as the logits cotangent.  (The
    reference also exported a ``max_idx`` argmax buffer; here the evaluator
    computes argmax inside its own jitted metrics step instead.)"""

    ACTIVATION = staticmethod(activations.softmax)
