"""Seeded chaos injection for the async master/slave stack (server.py /
client.py) — the fault-tolerance layer's proof harness.

Three pieces:

  - :class:`FaultSchedule`: deterministic per-frame fault decisions — a
    pure function of ``(seed, frame_index)``, so two runs with the same
    seed produce IDENTICAL fault schedules (the CI determinism contract);
  - :class:`ChaosProxy`: a ZeroMQ ROUTER<->DEALER proxy between REQ
    slaves and the REP master that drops, delays, duplicates, and
    corrupts frames per the schedule.  Fault decisions apply to WHOLE
    logical messages (one decision per multipart stack, v3-aware), and
    corruption mutates exactly one PAYLOAD frame — the v3 metadata frame
    or one of the raw tensor frames, chosen deterministically from
    (seed, frame_no) — never the ROUTER routing envelope, so a refusal
    reply still finds its way back to the broken peer.  Every decision
    is counted per direction (``req`` = slave->master, ``rep`` =
    master->slave) and logged, so a test can hold the master's/slaves'
    robustness counters to account for every injected fault;
  - process-level kill harnesses: :func:`take_job_and_die` (a slave that
    takes a job and vanishes mid-job) and :class:`MasterHarness`
    (kill/restart a Server mid-epoch, restoring from its crash-resume
    snapshot — the ``--master-resume`` path);
  - compute/resource faults (ISSUE 6): the schedule additionally
    carries ``stall`` decisions — a SEPARATE seeded stream
    (:meth:`FaultSchedule.decide_compute`, so existing wire-fault
    schedules replay unchanged) that the serving ``ModelRunner``'s
    ``inject_compute_faults`` hook turns into slow-compute sleeps — and
    :class:`FloodDriver`, one client hammering an inference service at
    N× its per-client rate limit, accounting every accepted reply and
    every refusal by the ``policy`` that refused it (the batcher's
    admission counters are the server-side half of that accounting).

Everything is CPU-only, in-process, and seeded: the chaos suite runs
deterministically in CI forever after (ISSUE 2).
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

#: schedule actions, in cumulative-probability order (``partition`` is
#: the window-based drop-ALL kind, ISSUE 14 — not in the per-message
#: probability cascade)
ACTIONS = ("drop", "corrupt", "dup", "delay", "forward", "partition")


class FaultSchedule:
    """Deterministic fault decisions: ``decide(i)`` derives a fresh RNG
    from ``(seed, i)``, so the decision for frame *i* depends on nothing
    but the seed — not on thread timing, not on how many frames came
    before.  Two schedules with the same seed are identical everywhere.

    Probabilities are per frame and must sum to < 1; the remainder is
    forwarded untouched.  ``delay_s`` bounds the injected latency — keep
    its upper bound below the slaves' ``recv_timeout`` or every delay
    also becomes a (counted) client reconnect.
    """

    #: salt for the compute-fault decision stream — decide_compute(i)
    #: must not correlate with decide(i), and adding stall to a schedule
    #: must leave its WIRE decisions byte-identical
    COMPUTE_SALT = 0x57A11

    #: salt for the subtree-preemption timetable stream (ISSUE 11) —
    #: same independence contract: adding preemptions to a schedule
    #: leaves its wire and compute decisions byte-identical
    PREEMPT_SALT = 0x5B07

    #: salt for the network-partition window stream (ISSUE 14) — same
    #: independence contract: adding partitions to a schedule leaves
    #: wire/compute/preempt decisions byte-identical
    PARTITION_SALT = 0x9A27

    #: salt for the transport core's built-in ingress hook
    #: (TransportLoop.inject_faults) — its drop/corrupt stream must not
    #: correlate with a ChaosProxy sharing the same seed
    TRANSPORT_SALT = 0x7C04E

    def __init__(self, seed: int, drop: float = 0.0, corrupt: float = 0.0,
                 duplicate: float = 0.0, delay: float = 0.0,
                 delay_s: Tuple[float, float] = (0.05, 0.2),
                 stall: float = 0.0,
                 stall_s: Tuple[float, float] = (0.02, 0.1),
                 partition_s: Tuple[float, float] = (0.0, 0.0),
                 partition_gap_s: Tuple[float, float] = (0.5, 2.0)):
        total = drop + corrupt + duplicate + delay
        if not 0.0 <= total < 1.0:
            raise ValueError(f"fault probabilities sum to {total}; "
                             "must be in [0, 1)")
        if not 0.0 <= stall <= 1.0:
            raise ValueError(f"stall probability {stall} not in [0, 1]")
        self.seed = int(seed)
        self.drop = float(drop)
        self.corrupt = float(corrupt)
        self.duplicate = float(duplicate)
        self.delay = float(delay)
        self.delay_s = (float(delay_s[0]), float(delay_s[1]))
        #: compute-fault stream (ISSUE 6): probability a model dispatch
        #: stalls, and the stall-length range — keep the upper bound
        #: well under request deadlines or every stall also becomes a
        #: (counted) deadline refusal
        self.stall = float(stall)
        self.stall_s = (float(stall_s[0]), float(stall_s[1]))
        #: network partitions (ISSUE 14): a SEEDED drop-ALL window per
        #: direction, distinct from per-message ``drop`` — during a
        #: window EVERY frame of that direction is discarded, which is
        #: what a real partition looks like to a reconnect state
        #: machine (N consecutive timeouts, not N independent coin
        #: flips).  ``partition_s`` is the window-duration range
        #: ((0, 0) disables); ``partition_gap_s`` the connected-gap
        #: range between windows.  Keep durations under the give-up
        #: budgets (reconnect budget x backoff) or the soak proves
        #: give-up instead of ride-through.
        self.partition_s = (float(partition_s[0]), float(partition_s[1]))
        self.partition_gap_s = (float(partition_gap_s[0]),
                                float(partition_gap_s[1]))
        if self.partition_s[0] < 0 or \
                self.partition_s[1] < self.partition_s[0]:
            raise ValueError(f"bad partition_s range {partition_s}")
        if self.partition_s[1] > 0 and self.partition_gap_s[0] <= 0:
            raise ValueError("partition_gap_s lower bound must be > 0 "
                             "(back-to-back windows are one window)")
        #: derived-window cache per direction (windows are pure in
        #: (seed, k, direction) but deriving one costs an RNG build —
        #: a proxy asking in_partition() per MESSAGE must not re-walk
        #: the whole timetable each time).  Lock-guarded: one schedule
        #: may drive several proxies/loops on different threads.
        self._pwin: Dict[str, List[Tuple[float, float]]] = {}
        self._pwin_lock = threading.Lock()

    def decide(self, frame_no: int) -> Tuple[str, float]:
        """(action, delay_seconds) for the frame_no-th frame."""
        rng = np.random.default_rng((self.seed, int(frame_no)))
        u = float(rng.random())
        edge = self.drop
        if u < edge:
            return "drop", 0.0
        edge += self.corrupt
        if u < edge:
            return "corrupt", 0.0
        edge += self.duplicate
        if u < edge:
            return "dup", 0.0
        edge += self.delay
        if u < edge:
            lo, hi = self.delay_s
            return "delay", lo + float(rng.random()) * (hi - lo)
        return "forward", 0.0

    def decisions(self, n: int) -> List[Tuple[str, float]]:
        """The first ``n`` decisions — the full fault schedule a run of
        ``n`` frames would see (the determinism-test surface)."""
        return [self.decide(i) for i in range(n)]

    def decide_compute(self, dispatch_no: int) -> Tuple[str, float]:
        """(action, stall_seconds) for the dispatch_no-th model
        dispatch: ``("stall", s)`` or ``("run", 0.0)``.  A separate
        pure-function-of-(seed, dispatch_no) stream — wire decisions
        for the same indices are untouched."""
        rng = np.random.default_rng(
            (self.seed, int(dispatch_no), self.COMPUTE_SALT))
        u = float(rng.random())
        if u < self.stall:
            lo, hi = self.stall_s
            return "stall", lo + float(rng.random()) * (hi - lo)
        return "run", 0.0

    def decide_transport(self, message_no: int) -> Tuple[str, float]:
        """(action, 0.0) for the message_no-th inbound message of a
        :class:`~znicz_tpu.transport.TransportLoop` built-in fault hook
        (ISSUE 14): ``drop``/``corrupt``/``forward`` per this
        schedule's drop/corrupt probabilities, on an independently
        salted stream — a ChaosProxy sharing the seed keeps its own
        decisions byte-identical.  (``dup``/``delay`` need a proxy in
        the path; the in-loop hook maps their probability mass to
        ``forward``.)"""
        rng = np.random.default_rng(
            (self.seed, int(message_no), self.TRANSPORT_SALT))
        u = float(rng.random())
        if u < self.drop:
            return "drop", 0.0
        if u < self.drop + self.corrupt:
            return "corrupt", 0.0
        return "forward", 0.0

    #: directions a partition window stream exists for (the proxy's
    #: two relay directions)
    PARTITION_DIRECTIONS = ("req", "rep")

    def _derive_window(self, direction: str, k: int,
                       pos: float) -> Tuple[float, float]:
        """Window ``k`` for ``direction`` given the previous window's
        end ``pos`` — the pure derivation both accessors share."""
        d = self.PARTITION_DIRECTIONS.index(direction)
        rng = np.random.default_rng(
            (self.seed, int(k), self.PARTITION_SALT, d))
        gap = self.partition_gap_s[0] + float(rng.random()) * (
            self.partition_gap_s[1] - self.partition_gap_s[0])
        dur = self.partition_s[0] + float(rng.random()) * (
            self.partition_s[1] - self.partition_s[0])
        start = pos + gap
        return start, start + dur

    def _windows_through(self, direction: str, t: float,
                         n: int = 0) -> List[Tuple[float, float]]:
        """The cached window list, extended until it covers relative
        time ``t`` (and holds at least ``n`` windows)."""
        with self._pwin_lock:
            wins = self._pwin.setdefault(direction, [])
            while len(wins) < n or not wins or wins[-1][1] <= t:
                start, end = self._derive_window(
                    direction, len(wins),
                    wins[-1][1] if wins else 0.0)
                wins.append((start, end))
            return list(wins)

    def partition_windows(self, direction: str,
                          n: int) -> List[Tuple[float, float]]:
        """The first ``n`` partition windows for ``direction``, as
        (start, end) seconds relative to the observer's epoch (the
        proxy's loop start) — a pure function of (seed, direction), so
        a soak's partition timetable replays identically run to run.
        Empty when partitions are disabled."""
        if self.partition_s[1] <= 0:
            return []
        return self._windows_through(direction, -1.0, n=int(n))[:int(n)]

    def in_partition(self, direction: str, t: float) -> bool:
        """True while ``direction`` is inside a partition window at
        relative time ``t`` (drop ALL its frames).  O(log windows) per
        call off the cache — the proxy asks once per MESSAGE."""
        if self.partition_s[1] <= 0 or t < 0:
            return False
        import bisect

        wins = self._windows_through(direction, t)
        i = bisect.bisect_right(wins, (t, float("inf"))) - 1
        return i >= 0 and wins[i][0] <= t < wins[i][1]

    def decide_preempt(self, target_no: int,
                       kill_s: Tuple[float, float] = (0.5, 2.0),
                       down_s: Tuple[float, float] = (1.0, 3.0)
                       ) -> Tuple[float, float]:
        """``(kill_at, down)`` seconds for subtree target ``target_no``
        (ISSUE 11): when the target is killed, relative to the driver's
        start, and how long it stays down before restart.  A pure
        function of ``(seed, target_no)`` on its own salted stream, so
        a preemption timetable replays identically run to run and never
        perturbs the wire/compute decisions of the same seed."""
        rng = np.random.default_rng(
            (self.seed, int(target_no), self.PREEMPT_SALT))
        kill_at = kill_s[0] + float(rng.random()) * (kill_s[1] - kill_s[0])
        down = down_s[0] + float(rng.random()) * (down_s[1] - down_s[0])
        return float(kill_at), float(down)


# deterministic frame corruption: moved to the transport core (ISSUE
# 14) so the proxy and TransportLoop's built-in ingress hook share one
# mutation; re-exported here under the historical name
from znicz_tpu.transport.core import (corrupt_message,      # noqa: E402
                                      corrupt_payload)      # noqa: F401


class ChaosProxy:
    """Seeded fault-injecting ROUTER<->DEALER proxy.

    Slaves connect their REQ sockets to ``front_endpoint``; the proxy
    relays to the master's REP socket at ``back_endpoint``.  Frames are
    numbered in arrival order across both directions and each gets one
    :class:`FaultSchedule` decision.  ``counters[direction][action]``
    and ``log`` (``(frame_no, direction, action)``) record everything
    injected, so nothing is lost silently even by the chaos itself.
    """

    def __init__(self, front_endpoint: str, back_endpoint: str,
                 schedule: FaultSchedule):
        from znicz_tpu import telemetry

        self.front_endpoint = front_endpoint
        self.back_endpoint = back_endpoint
        self.schedule = schedule
        # fault accounting lives in the telemetry registry (ISSUE 5):
        # one labeled family znicz_faults_total{component="chaos",
        # direction=..., action=...}; ``counters`` below keeps the
        # historical nested-dict READ shape the chaos tests hold their
        # robustness-counter accounting against
        _sc = telemetry.scope("chaos")
        self._fault_counters = {
            (d, a): _sc.counter("faults", "injected proxy fault decisions",
                                direction=d, action=a)
            for d in ("req", "rep") for a in ACTIONS}
        self.log: List[Tuple[int, str, str]] = []
        self._frame_no = 0
        self._t0: Optional[float] = None    # partition-window epoch
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def counters(self) -> Dict[str, Dict[str, int]]:
        """``{direction: {action: count}}`` snapshot of the registry
        counters (the historical read shape)."""
        return {d: {a: self._fault_counters[(d, a)].value for a in ACTIONS}
                for d in ("req", "rep")}

    def faults_toward(self, direction: str) -> int:
        """Injected faults a peer in ``direction``'s receive path can
        observe as a timeout or bad reply: drops (either way starve the
        requester) plus corruptions of that direction's frames."""
        c = self.counters
        return (c["req"]["drop"] + c["rep"]["drop"]
                + c[direction]["corrupt"])

    def total_faults(self) -> int:
        return sum(n for d in self.counters.values()
                   for a, n in d.items() if a != "forward")

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ChaosProxy":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="chaos-proxy")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError("chaos proxy failed to bind "
                               f"{self.front_endpoint}")
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # -- the relay loop (rides the transport core, ISSUE 14) -------------------

    def _corrupt_one(self, frames: List[bytes], frame_no: int
                     ) -> List[bytes]:
        """Multipart-aware corruption (v3 framing): exactly ONE payload
        frame, picked as a pure function of (seed, frame_no), never the
        routing envelope — the shared transport-core mutation."""
        return corrupt_message(frames,
                               (self.schedule.seed, int(frame_no), 0xC0))

    def _relay(self, frames: List[bytes], direction: str, out,
               held: list, seq: List[int]) -> None:
        """One message, one schedule decision (the fault-injection
        dispatch both directions share).  A partition window for this
        direction supersedes the per-message cascade: EVERY frame is
        dropped and counted ``partition`` (its per-message stream index
        is still consumed, so ``decide(i)`` purity is untouched)."""
        fno = self._frame_no
        self._frame_no += 1
        if self.schedule.in_partition(direction,
                                      time.time() - self._t0):
            self._fault_counters[(direction, "partition")].inc()
            self.log.append((fno, direction, "partition"))
            return
        action, delay = self.schedule.decide(fno)
        self._fault_counters[(direction, action)].inc()
        self.log.append((fno, direction, action))
        if action == "drop":
            return
        if action == "corrupt":
            out.send_multipart(self._corrupt_one(frames, fno))
        elif action == "dup":
            out.send_multipart(frames)
            out.send_multipart(frames)
        elif action == "delay":
            seq[0] += 1
            heapq.heappush(held,
                           (time.time() + delay, seq[0], out, frames))
        else:
            out.send_multipart(frames)

    def _loop(self) -> None:
        from znicz_tpu.transport import TransportLoop

        loop = TransportLoop("chaos_proxy", stop=self._stop,
                             instance=self.front_endpoint)
        held: list = []                 # (release_t, seq, out_sock, frames)
        seq = [0]
        try:
            front = loop.bind_router(self.front_endpoint)
            back = loop.connect_dealer(self.back_endpoint)
            loop.register(front, lambda frames: self._relay(
                frames, "req", back, held, seq), drain=True)
            loop.register(back, lambda frames: self._relay(
                frames, "rep", front, held, seq), drain=True)

            def release_due():
                now = time.time()
                while held and held[0][0] <= now:
                    _, _, out, frames = heapq.heappop(held)
                    out.send_multipart(frames)

            def next_timeout_ms() -> int:
                if not held:
                    return 20
                return max(1, min(20, int((held[0][0] - time.time())
                                          * 1000)))

            loop.add_tick(release_due)
            self._t0 = time.time()
            self._ready.set()
            loop.run(timeout_fn=next_timeout_ms)
        finally:
            loop.close()


# -- resource-fault drivers (ISSUE 6) ------------------------------------------


class FloodDriver:
    """One client flooding an inference service at ``factor``× its
    per-client rate limit — the admission-control fairness proof's
    misbehaving tenant.

    Open-loop arrivals totalling ``rate_rows_per_s * factor`` rows/s
    on a daemon thread — ``x`` may carry several rows per request (the
    admission bucket meters ROWS, so a row-batched flood is the same
    10× overload with proportionally fewer messages; the per-message
    variant doubles as a packet flood).  Every reply is accounted,
    none raises:
    ``accepted`` counts ok replies, ``refusals`` buckets refusal
    replies by the ``policy`` that refused them (a fairness test
    asserts this is ALL ``rate_limited``).  The breaker is disabled on
    purpose — a polite client would back off, and the flood must not.
    """

    def __init__(self, endpoint: str, x, rate_rows_per_s: float,
                 factor: float = 10.0, client_id: str = "flooder",
                 max_in_flight: int = 256):
        self.endpoint = endpoint
        self.x = x
        self.rows = int(x.shape[0]) if getattr(x, "ndim", 1) > 1 else 1
        self.rate = float(rate_rows_per_s) * float(factor)
        self.client_id = client_id
        self.max_in_flight = int(max_in_flight)
        self.accepted = 0
        self.refusals: Dict[str, int] = {}
        self.sent = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def outcomes(self) -> int:
        return self.accepted + sum(self.refusals.values())

    def start(self) -> "FloodDriver":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="chaos-flood")
        self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        from znicz_tpu.serving.client import InferenceClient

        cli = InferenceClient(self.endpoint, timeout=60.0,
                              resend_after_s=5.0, max_resends=100,
                              client_id=self.client_id,
                              breaker_failures=0)
        t0 = time.perf_counter()
        try:
            while not self._stop.is_set():
                # burst catch-up: send EVERY due request, not one per
                # loop tick — the offered rate must actually reach
                # factor x rate_limit, not the loop's poll cadence
                while (time.perf_counter() - t0
                       >= (self.sent * self.rows) / self.rate
                       and cli.in_flight < self.max_in_flight
                       and not self._stop.is_set()):
                    cli.submit(self.x)
                    self.sent += 1
                for rep in cli.collect(0.002):
                    if rep.get("ok"):
                        self.accepted += 1
                    else:
                        pol = rep.get("policy", "error")
                        self.refusals[pol] = self.refusals.get(pol, 0) + 1
        except Exception:                   # pragma: no cover - driver
            pass                            # a dying flood is just quiet
        finally:
            cli.close()


class FloodProcess:
    """:class:`FloodDriver` in a SEPARATE interpreter process — the
    honest tenant model for latency-band assertions: a real flooding
    client shares no GIL with the service or the well-behaved clients,
    while an in-process flood thread bills its own Python overhead
    onto every latency sample of everything else on a 1-core host.

    The child is ``python -m znicz_tpu.parallel.chaos --flood ...`` (no
    jax import — it comes up in <1s); flood windows are toggled over
    stdin (``start``/``stop``), each ``stop`` returning the window's
    accounting (sent/accepted/refusals-by-policy) as one JSON line.
    """

    def __init__(self, endpoint: str, sample_dim: int,
                 rate_rows_per_s: float, factor: float = 10.0,
                 client_id: str = "flooder", max_in_flight: int = 32,
                 rows: int = 1):
        import subprocess
        import sys

        self._proc = subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu.parallel.chaos", "--flood",
             endpoint, str(int(sample_dim)), str(float(rate_rows_per_s)),
             str(float(factor)), client_id, str(int(max_in_flight)),
             str(int(rows))],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
            bufsize=1)
        line = self._proc.stdout.readline().strip()
        if line != "ready":                 # pragma: no cover - defensive
            raise RuntimeError(f"flood child failed to come up: {line!r}")

    def start_flood(self) -> None:
        self._proc.stdin.write("start\n")
        self._proc.stdin.flush()

    def stop_flood(self) -> Dict:
        """Stop the current flood window; returns its accounting."""
        import json

        self._proc.stdin.write("stop\n")
        self._proc.stdin.flush()
        return json.loads(self._proc.stdout.readline())

    def close(self) -> None:
        try:
            self._proc.stdin.write("quit\n")
            self._proc.stdin.flush()
        except (BrokenPipeError, ValueError):  # pragma: no cover
            pass
        self._proc.wait(timeout=30)


def _flood_main(argv: List[str]) -> None:  # pragma: no cover - subprocess
    """Child half of :class:`FloodProcess` (kept here so the flood
    logic has ONE home — this just wraps FloodDriver in a stdin/stdout
    command loop)."""
    import json
    import sys

    endpoint, dim, rate, factor, client_id, mif, rows = argv
    x = np.zeros((int(rows), int(dim)), np.float32)
    print("ready", flush=True)
    driver: Optional[FloodDriver] = None
    for line in sys.stdin:
        cmd = line.strip()
        if cmd == "start" and driver is None:
            driver = FloodDriver(endpoint, x, float(rate),
                                 factor=float(factor),
                                 client_id=client_id,
                                 max_in_flight=int(mif)).start()
        elif cmd == "stop" and driver is not None:
            driver.stop()
            print(json.dumps({"sent": driver.sent,
                              "accepted": driver.accepted,
                              "refusals": driver.refusals}), flush=True)
            driver = None
        elif cmd == "quit":
            break
    if driver is not None:
        driver.stop()


# -- replica-fleet drivers (ISSUE 12) ------------------------------------------


class ReplicaHarness:
    """Kill/restart driver for a serving replica behind the balancer
    (the fleet twin of :class:`RelayHarness`): ``make_server`` builds a
    fresh ``InferenceServer`` each (re)start — at the SAME bind, so the
    balancer's data DEALER reconnects into the restarted process and
    its requests ride the existing failover machinery.  ``kill()`` is a
    simulated replica crash: queued batches, in-flight computes and the
    retained-previous generation die with it, exactly what a preempted
    process loses; the restarted replica re-announces with its BOOT
    snapshot and the balancer heals it back onto the fleet path."""

    def __init__(self, make_server):
        self.make_server = make_server
        self.server = None
        self.kills = 0

    def start(self):
        self.server = self.make_server()
        return self.server.start()

    def kill(self) -> None:
        self.server.stop()
        self.kills += 1

    def restart(self):
        """A fresh replica process-equivalent at the same bind."""
        return self.start()


class ScriptedReplica:
    """Model-free fake replica (ISSUE 12): speaks the replica side of
    the balancer protocol — ROUTER bind for data traffic, DEALER
    heartbeats piggybacking readiness/queue-depth/p99 — with a SCRIPTED
    forward ``y = x * scale`` instead of a jitted model, so fleet
    failover/hedging/rollback tests pay zero warmup.

    ``snapshots`` maps swap paths to the scale each "generation"
    computes with — or to a dict ``{"scale": s, "stall_s": t}`` for a
    generation that is also SLOW (the scripted p99-regression canary);
    ``swap`` to an unknown path is refused like a broken snapshot, and
    ``rollback`` restores the retained previous (scale, stall,
    generation, path) exactly like ``ModelRunner.rollback``.  Fault
    scripting: ``stall_every``/``stall_s`` sleeps before every Nth
    reply (the tail the hedger races), ``blackhole`` accepts requests
    and never answers (the failover path), ``refuse`` answers every
    infer with that ``(policy, scope)`` refusal.  ``kill()`` stops the
    thread mid-everything; ``restart()`` comes back at the SAME bind
    with BOOT state (generation 1, boot scale/path) — a restarted
    process remembers nothing, which is what the balancer's healing is
    for.  Scripted state is lock-guarded: tests read counters while the
    serve thread mutates."""

    def __init__(self, announce: str, replica_id: str,
                 bind: str = "tcp://127.0.0.1:*",
                 snapshots: Optional[Dict[str, float]] = None,
                 boot_path: str = "boot", boot_scale: float = 1.0,
                 heartbeat_s: float = 0.05, stall_s: float = 0.0,
                 stall_every: int = 0, blackhole: bool = False,
                 refuse: Optional[Tuple[str, str]] = None):
        self.announce = announce
        self.replica_id = replica_id
        self.bind = bind
        self.endpoint: Optional[str] = None
        self.snapshots = dict(snapshots or {})
        self.boot_path = boot_path
        self.boot_scale = float(boot_scale)
        self.heartbeat_s = float(heartbeat_s)
        self.stall_s = float(stall_s)
        self.stall_every = int(stall_every)
        self.blackhole = blackhole
        self.refuse = refuse
        self._lock = threading.Lock()
        self._reset_state()
        self.served = 0
        self.swallowed = 0                  # blackholed requests
        self.kills = 0
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _reset_state(self) -> None:
        """Boot state: what a restarted process remembers (nothing)."""
        self.gen = 1
        self._hwm = 1
        self.scale = self.boot_scale
        self.gen_stall_s = 0.0
        self.path = self.boot_path
        self._previous: Optional[Tuple[float, float, int, str]] = None

    def start(self) -> "ScriptedReplica":
        self._stop = threading.Event()
        self._ready.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"fake-{self.replica_id}")
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError(f"scripted replica {self.replica_id} "
                               f"failed to bind {self.bind}")
        return self

    def kill(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        with self._lock:
            self.kills += 1

    def restart(self) -> "ScriptedReplica":
        """Back at the SAME bind with boot state (fresh process)."""
        if self._thread is not None:
            self.kill()
        with self._lock:
            self._reset_state()
        self.bind = self.endpoint or self.bind
        return self.start()

    def _heartbeat(self) -> Dict:
        with self._lock:
            return {"cmd": "heartbeat", "replica_id": self.replica_id,
                    "endpoint": self.endpoint, "ready": True,
                    "draining": False, "swapping": False,
                    "gen": self.gen, "snapshot_path": self.path,
                    "queue_depth": 0, "served": self.served,
                    # warm provenance (ISSUE 17): scripted replicas
                    # have no executables — boot is instant by
                    # construction, which is exactly what the fleet
                    # autoscale tests want (zero-warmup fleets)
                    "warm_source": "scripted", "warm_hits": 0,
                    "warm_misses": 0, "boot_s": 0.0,
                    "p99_ms_by_bucket": {}}

    def _answer(self, req: Dict) -> Optional[Dict]:
        """One scripted reply (None = swallow it), state under lock."""
        cmd = req.get("cmd")
        rid = req.get("req_id")
        base = {"req_id": rid, "replica_id": self.replica_id}
        if cmd == "ping":
            return dict(base, ok=True, pong=True)
        if cmd == "swap":
            path = req.get("path")
            with self._lock:
                if path not in self.snapshots:
                    return dict(base, ok=False,
                                error=f"unknown snapshot {path!r}")
                val = self.snapshots[path]
                if not isinstance(val, dict):
                    val = {"scale": float(val)}
                self._previous = (self.scale, self.gen_stall_s,
                                  self.gen, self.path)
                self._hwm += 1
                self.gen = self._hwm
                self.scale = float(val.get("scale", 1.0))
                self.gen_stall_s = float(val.get("stall_s", 0.0))
                self.path = path
                return dict(base, ok=True, swap_started=True,
                            generation=self.gen)
        if cmd == "rollback":
            with self._lock:
                if self._previous is None:
                    return dict(base, ok=False,
                                error="no previous generation retained")
                (self.scale, self.gen_stall_s, self.gen,
                 self.path) = self._previous
                self._previous = None
                return dict(base, ok=True, rolled_back=True,
                            generation=self.gen)
        if cmd != "infer":
            return dict(base, ok=False, error=f"unknown cmd {cmd!r}")
        with self._lock:
            self.served += 1
            n = self.served
            scale, gen = self.scale, self.gen
        if self.refuse is not None:
            policy, scope = self.refuse
            return dict(base, ok=False, rejected=True, policy=policy,
                        scope=scope, error=f"scripted {policy} refusal")
        if self.blackhole:
            with self._lock:
                self.swallowed += 1
            return None
        if self.stall_every and n % self.stall_every == 0:
            time.sleep(self.stall_s)
        if self.gen_stall_s:
            time.sleep(self.gen_stall_s)    # a SLOW generation (the
            # scripted p99-regression canary)
        x = np.asarray(req.get("x"), np.float32)
        return dict(base, ok=True, gen=gen,
                    y=(x * np.float32(scale)).astype(np.float32))

    def _loop(self) -> None:
        from znicz_tpu.parallel import wire
        from znicz_tpu.transport import TransportLoop, bad_frame_reply

        loop = TransportLoop("scripted_replica", stop=self._stop,
                             instance=self.replica_id)
        state = {"next_hb": 0.0}
        try:
            sock = loop.bind_router(self.bind)
            with self._lock:
                self.endpoint = loop.resolved_endpoint(sock)
            hb = loop.connect_dealer(self.announce)

            def on_data(raw: List[bytes]) -> None:
                envelope, payload = wire.split_envelope(raw)
                try:
                    req, _ = wire.decode_message(payload or raw)
                except wire.WireError as exc:
                    bad, _ = wire.encode_message(dict(
                        bad_frame_reply(exc),
                        replica_id=self.replica_id, error=str(exc)))
                    sock.send_multipart(list(envelope) + bad)
                    return
                rep = self._answer(req)
                if rep is None:
                    return                  # blackholed
                out, _ = wire.encode_message(rep)
                sock.send_multipart(list(envelope) + out, copy=False)

            def beat() -> None:
                now = time.time()
                if now >= state["next_hb"]:
                    state["next_hb"] = now + self.heartbeat_s
                    frames, _ = wire.encode_message(self._heartbeat())
                    hb.send_multipart([b""] + frames)

            loop.register(sock, on_data, drain=True)
            loop.register(hb, lambda _frames: None,  # acks discarded
                          drain=True)
            loop.add_tick(beat)
            beat()                          # first heartbeat pre-poll
            self._ready.set()
            loop.run(poll_ms=5)
        finally:
            loop.close()


class FleetScaler:
    """In-process spawn/retire driver for the balancer's autoscaler
    (ISSUE 17): ``factory(i)`` builds a startable replica (a
    :class:`ScriptedReplica`, or an ``InferenceServer`` factory like
    :class:`ReplicaHarness` uses) for fleet index ``i``.  ``spawn()``
    boots the next index on a daemon thread — the balancer calls it
    outside its lock, but a model replica's warmup must not stall the
    caller either — and ``retire(replica_id)`` kills the matching
    handle.  Externally started replicas join via :meth:`adopt` so the
    autoscaler can retire the INITIAL fleet too.  Tallies are read by
    tests/bench after the dust settles."""

    def __init__(self, factory):
        import logging

        self.factory = factory
        self.log = logging.getLogger("znicz.chaos")
        self._lock = threading.Lock()
        self._handles: Dict[str, object] = {}
        self._next = 0
        self._n = {"spawned": 0, "retired": 0, "spawn_failures": 0}

    def adopt(self, replica) -> None:
        """Track an already-running replica (the pre-autoscale fleet)."""
        with self._lock:
            self._handles[replica.replica_id] = replica

    def spawn(self) -> None:
        with self._lock:
            i = self._next
            self._next += 1

        def boot() -> None:
            try:
                rep = self.factory(i)
                rep.start()
                with self._lock:
                    self._handles[rep.replica_id] = rep
                    self._n["spawned"] += 1
            except Exception:
                with self._lock:
                    self._n["spawn_failures"] += 1
                self.log.exception("fleet scaler: spawn %d failed", i)

        threading.Thread(target=boot, daemon=True,
                         name=f"fleet-spawn-{i}").start()

    def retire(self, replica_id: str) -> None:
        with self._lock:
            rep = self._handles.pop(replica_id, None)
        if rep is None:
            self.log.warning("fleet scaler: retire(%s) — no handle "
                             "(already gone?)", replica_id)
            return
        rep.kill()
        with self._lock:
            self._n["retired"] += 1

    def replica_ids(self) -> List[str]:
        with self._lock:
            return sorted(self._handles)

    @property
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._n)

    def stop_all(self) -> None:
        """Teardown: kill every tracked replica."""
        with self._lock:
            handles = list(self._handles.values())
            self._handles.clear()
        for rep in handles:
            try:
                rep.kill()
            except Exception:           # pragma: no cover - teardown race
                pass


# -- process-level kill harness ------------------------------------------------


class SubtreePreempter:
    """Spot/preempt chaos (ISSUE 11): kill and restart whole relay
    subtrees on a seeded timetable.

    Each target is ``(name, kill_fn, restart_fn)`` — typically closures
    over a :class:`RelayHarness` per relay of the subtree plus
    ``Client.preempt()`` calls for its slaves.  The timetable comes from
    :meth:`FaultSchedule.decide_preempt` (pure function of (seed,
    target_no)); ``start()`` runs it on a daemon thread, recording each
    executed action with its WALL time so a gate can hold progress
    counters to the exact kill window (``window()``).  All recorded
    state is lock-guarded: the driver thread writes while the test
    thread reads mid-run."""

    def __init__(self, schedule: FaultSchedule, targets,
                 kill_s: Tuple[float, float] = (0.5, 2.0),
                 down_s: Tuple[float, float] = (1.0, 3.0)):
        self.schedule = schedule
        self.targets = list(targets)
        self.timetable: List[tuple] = []    # (at_s, idx, action, fn, name)
        for i, (name, kill_fn, restart_fn) in enumerate(self.targets):
            kill_at, down = schedule.decide_preempt(i, kill_s, down_s)
            self.timetable.append((kill_at, i, "kill", kill_fn, name))
            self.timetable.append((kill_at + down, i, "restart",
                                   restart_fn, name))
        self.timetable.sort(key=lambda t: (t[0], t[1], t[2]))
        self._lock = threading.Lock()
        self._events: List[Tuple[float, str, str]] = []  # (wall, name, act)
        self._preempted = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def events(self) -> List[Tuple[float, str, str]]:
        with self._lock:
            return list(self._events)

    @property
    def preemptions(self) -> int:
        with self._lock:
            return self._preempted

    def window(self) -> Optional[Tuple[float, float]]:
        """(first kill wall time, last restart wall time) of everything
        executed so far — the degraded window a progress gate holds its
        counters to; None before the first kill."""
        with self._lock:
            kills = [t for t, _, a in self._events if a == "kill"]
            rests = [t for t, _, a in self._events if a == "restart"]
        if not kills:
            return None
        return min(kills), max(rests) if rests else time.time()

    def start(self) -> "SubtreePreempter":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="chaos-preempter")
        self._thread.start()
        return self

    def join(self, timeout: float = 120.0) -> bool:
        """Wait for the whole timetable to execute; True when it did."""
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    def _loop(self) -> None:
        t0 = time.time()
        for at, _, action, fn, name in self.timetable:
            while time.time() - t0 < at:
                if self._stop.wait(min(0.02, max(0.001,
                                                 at - (time.time() - t0)))):
                    return
            if self._stop.is_set():
                return
            fn()
            with self._lock:
                self._events.append((time.time(), name, action))
                if action == "kill":
                    self._preempted += 1


class RelayHarness:
    """Kill/restart driver for an aggregation-tree relay (ISSUE 10).

    ``start()`` builds a fresh :class:`relay.Relay` and serves it on a
    daemon thread; ``kill()`` stops it mid-run — jobs in its queue and
    contributions in its flush buffer are deliberately lost, exactly
    what a crashed relay process loses; the master's TTL reaper
    recovers the jobs (``jobs_requeued``) and the children either ride
    out a ``restart()`` at the same bind via their existing
    reconnect/re-register machinery, or fall back to the relay's
    advertised upstream once their budget is spent.
    """

    def __init__(self, upstream: str, bind: str, **relay_kwargs):
        self.upstream = upstream
        self.bind = bind
        self.relay_kwargs = relay_kwargs
        self.relay = None
        self.kills = 0

    def start(self):
        from znicz_tpu.parallel.relay import Relay

        self.relay = Relay(self.upstream, self.bind, **self.relay_kwargs)
        return self.relay.start()

    def kill(self, timeout: float = 30.0) -> None:
        """Simulated relay crash: buffered state dies with it."""
        self.relay.stop(timeout)
        self.kills += 1

    def restart(self):
        """A fresh relay at the SAME bind (children reconnect into it
        and re-register through the existing path)."""
        self.kill()
        return self.start()


def take_job_and_die(endpoint: str, workflow, slave_id: str = "doomed",
                     timeout_ms: int = 10_000,
                     attempts: int = 40) -> Optional[int]:
    """The canonical mid-job slave death: register, take ONE job, vanish
    without replying.  Returns the job_id the master now holds in flight
    — it must come back via the reaper (``jobs_requeued``) for the
    no-silent-loss property to hold — or None if training already ended.

    Rides transport faults like a real slave (fresh socket +
    re-register on a timeout, a corrupted reply, or a ``bad_frame``
    refusal of its own corrupted frame, bounded by ``attempts``) — when
    driven through the ChaosProxy its frames get corrupted like
    anyone else's, and the doomed slave must still reach its job.
    Rides the shared :class:`~znicz_tpu.transport.Endpoint` (ISSUE 14),
    like every other client link."""
    from znicz_tpu.network_common import handshake_request
    from znicz_tpu.transport import Endpoint, TransportFault

    ep = Endpoint(endpoint, recv_timeout_s=timeout_ms / 1000.0)
    last: Optional[BaseException] = None

    def rpc(msg: dict) -> dict:
        return ep.rpc_message(dict(msg, id=slave_id))

    try:
        for _ in range(attempts):
            try:
                rep = rpc(handshake_request(workflow))
                if rep.get("bad_frame"):
                    # our register corrupted in flight: fresh cycle
                    # (fresh socket too — REQ_RELAXED would allow
                    # reuse, but the historical fresh-socket retry is
                    # what the chaos accounting was calibrated on)
                    ep.reset()
                    continue
                if not rep.get("ok"):
                    raise RuntimeError(
                        f"registration refused: {rep.get('error')}")
                while True:
                    rep = rpc({"cmd": "job"})
                    if "job" in rep:
                        return rep["job_id"]
                    if rep.get("done"):
                        return None
                    if rep.get("unregistered"):
                        ep.reset()
                        break   # master lost us: fresh cycle, re-register
                    time.sleep(0.05)
            except TransportFault as exc:
                last = exc      # socket already reset: reconnect fresh
    finally:
        ep.close()              # died mid-job, update never sent
    raise RuntimeError(
        f"doomed slave never reached a job through the chaos "
        f"({attempts} attempts; last fault: {last!r})")


class MasterHarness:
    """Kill/restart driver for the master half of the chaos harness.

    ``start()`` builds a fresh workflow + Server (restoring from
    ``resume_path`` when the file exists — exactly what a restarted
    ``--master-resume`` process does) and serves it on a daemon thread;
    ``kill()`` is a simulated crash: serving stops at the next poll tick
    with NO final snapshot, so only the periodic resume snapshot
    survives.  ``wait()`` joins the serving thread.
    """

    def __init__(self, make_workflow, endpoint: str, resume_path: str,
                 snapshot_every_s: float = 0.3, linger: float = 3.0,
                 **server_kwargs):
        self.make_workflow = make_workflow
        self.endpoint = endpoint
        self.resume_path = resume_path
        self.snapshot_every_s = snapshot_every_s
        self.linger = linger
        self.server_kwargs = server_kwargs
        self.server = None
        self.workflow = None
        self.kills = 0
        self._thread: Optional[threading.Thread] = None

    def start(self):
        from znicz_tpu.server import Server

        self.workflow = self.make_workflow()
        self.server = Server(self.workflow, endpoint=self.endpoint,
                             resume_path=self.resume_path,
                             snapshot_every_s=self.snapshot_every_s,
                             **self.server_kwargs)
        self._thread = threading.Thread(
            target=self.server.serve, kwargs={"linger": self.linger},
            daemon=True, name="chaos-master")
        self._thread.start()
        return self.server

    def kill(self, timeout: float = 30.0) -> None:
        """Simulated master crash mid-epoch (no final snapshot)."""
        self.server.stop()
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise RuntimeError("master thread did not stop")
        self.kills += 1

    def wait(self, timeout: float = 120.0) -> bool:
        """Join the serving thread; True when it exited (run complete)."""
        self._thread.join(timeout)
        return not self._thread.is_alive()


if __name__ == "__main__":              # pragma: no cover - subprocess
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "--flood":
        _flood_main(sys.argv[2:])
