from znicz_tpu.parallel.mesh import make_mesh  # noqa: F401
from znicz_tpu.parallel.fused import FusedTrainer  # noqa: F401
